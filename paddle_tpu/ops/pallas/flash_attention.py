"""Flash attention — Pallas TPU kernel with custom VJP.

Capability analog of the reference's flash-attn v2 integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu + third_party/flashattn,
python surface python/paddle/nn/functional/flash_attention.py), built
TPU-native: online-softmax tiling sized to the MXU (128-lane blocks),
VMEM accumulators, causal block skipping, and a two-kernel backward
(dq; dk/dv) using the saved logsumexp — the standard flash-attention-2
recurrence, scheduled for TPU rather than ported from CUDA.

Layouts: public API takes paddle's (batch, seq, heads, head_dim);
kernels run (batch*heads, seq, head_dim). f32 accumulation everywhere
(MXU preferred_element_type), io dtype preserved.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.framework.jax_compat import (
    pallas_tpu_compiler_params as _compiler_params,
)

__all__ = ["flash_attention_op", "flash_attention_fn"]

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                num_k_blocks, offset=0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _visible():  # causal: process only k blocks not fully masked
        q = q_ref[0]                              # (BQ, D) io dtype (bf16 ok)
        k = k_ref[0]                              # (BK, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            # offset = sk - sq: bottom-right-aligned causal (KV-cache
            # chunked prefill; query i sees keys <= i + offset)
            mask = (qi * block_q + rows + offset) >= (ki * block_k + cols)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, 0:1]                    # (BQ, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                    # (BQ, BK)
        l_new = alpha * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]
        # p in io dtype for the MXU (f32 accumulate keeps precision)
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        @pl.when(ki * block_k < (qi + 1) * block_q + offset)
        def _():
            _visible()
    else:
        _visible()

    @pl.when(ki == num_k_blocks - 1)
    def _finish():
        l = l_scr[:, 0:1]
        m = m_scr[:, 0:1]
        # Fully-masked rows come in two shapes: a q block whose k blocks
        # were ALL skipped (l == 0, needs the clamp) or a visited block
        # whose row was fully masked (m == _NEG_INF, p == exp(0) == 1 so
        # l == block_k and acc holds a uniform V sum). Zero both.
        safe_l = jnp.maximum(l, 1e-30)
        masked_row = m <= _NEG_INF * 0.5
        o_ref[0] = jnp.where(masked_row, 0.0,
                             acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(masked_row, _NEG_INF, m + jnp.log(safe_l))


def _fwd_single_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                       *, scale, causal, offset):
    """Whole-sequence block: plain softmax attention in VMEM. With one
    (q, k) block the online-softmax merge is pure overhead — no m/l
    scratch round-trips, no acc rescale, no alpha exp. Measured 1.8x the
    merged kernel at the BERT shape (bh=192, S=512, d=64, non-causal)."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(rows + offset >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    # A fully-masked row has m == _NEG_INF (finite), so p == 1 everywhere
    # and pv/l would be the uniform V average — zero it instead. Currently
    # defensive: flash_attention_fn rejects causal with sq > sk, the only
    # way such a row arises through the public surface.
    masked_row = m <= _NEG_INF * 0.5
    o_ref[0] = jnp.where(masked_row, 0.0, pv / l).astype(o_ref.dtype)
    lse_ref[0] = jnp.where(masked_row, _NEG_INF, m + jnp.log(l))


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq = sq // block_q
    nk = sk // block_k
    if nq == 1 and nk == 1:
        return pl.pallas_call(
            functools.partial(_fwd_single_kernel, scale=scale,
                              causal=causal, offset=sk - sq),
            grid=(bh,),
            in_specs=[pl.BlockSpec((1, sq, d), lambda b: (b, 0, 0)),
                      pl.BlockSpec((1, sk, d), lambda b: (b, 0, 0)),
                      pl.BlockSpec((1, sk, d), lambda b: (b, 0, 0))],
            out_specs=[pl.BlockSpec((1, sq, d), lambda b: (b, 0, 0)),
                       pl.BlockSpec((1, sq, 1), lambda b: (b, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                       jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32)],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel",)),
            interpret=interpret,
        )(q, k, v)
    grid = (bh, nq, nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=nk, offset=sk - sq)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward: dkv kernel (grid over k blocks, scan q blocks) + dq kernel
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, block_q, block_k, num_q_blocks, offset=0):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _visible():
        q = q_ref[0]                                # (BQ, D) io dtype
        k = k_ref[0]                                # (BK, D)
        v = v_ref[0]
        do = do_ref[0]                              # (BQ, D)
        lse = lse_ref[0]                            # (BQ, 1)
        delta = delta_ref[0]                        # (BQ, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            # offset = sk - sq: bottom-right-aligned causal (KV-cache
            # chunked prefill; query i sees keys <= i + offset)
            mask = (qi * block_q + rows + offset) >= (ki * block_k + cols)
            s = jnp.where(mask, s, _NEG_INF)
        # fully-masked rows carry the fwd sentinel lse == _NEG_INF; without
        # the guard p = exp(-1e30 - (-1e30)) == 1 would leak garbage dk/dv
        p = jnp.where(lse <= _NEG_INF * 0.5, 0.0, jnp.exp(s - lse))
        pc = p.astype(do.dtype)
        # dv += p^T do
        dv_scr[:] += jax.lax.dot_general(pc, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale)             # (BQ, BK) f32
        dsc = ds.astype(q.dtype)
        # dk += ds^T q
        dk_scr[:] += jax.lax.dot_general(dsc, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        @pl.when((qi + 1) * block_q + offset > ki * block_k)
        def _():
            _visible()
    else:
        _visible()

    @pl.when(qi == num_q_blocks - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr,
               *, scale, causal, block_q, block_k, num_k_blocks, offset=0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _visible():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            # offset = sk - sq: bottom-right-aligned causal (KV-cache
            # chunked prefill; query i sees keys <= i + offset)
            mask = (qi * block_q + rows + offset) >= (ki * block_k + cols)
            s = jnp.where(mask, s, _NEG_INF)
        # masked-row guard: see _dkv_kernel
        p = jnp.where(lse <= _NEG_INF * 0.5, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k < (qi + 1) * block_q + offset)
        def _():
            _visible()
    else:
        _visible()

    @pl.when(ki == num_k_blocks - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(q, k, v, out, lse, do, scale, causal, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq = sq // block_q
    nk = sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (bh, sq, 1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          offset=sk - sq),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          offset=sk - sq),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper on (bh, s, d) layout
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _bwd(q, k, v, out, lse, do, scale, causal, block_q, block_k,
                interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _divisor_block(s: int, target: int) -> int:
    """Largest divisor of s that is <= target (so any seq length that the
    old fixed-128 default handled still divides cleanly)."""
    b = min(target, s)
    while s % b:
        b -= 1
    return b


def _auto_blocks(sq: int, sk: int):
    """Pick block sizes for the v5e VMEM budget: big blocks amortize grid
    overhead and keep the online-softmax VPU work per MXU op low. Up to
    1024×1024 the whole S×S f32 score tile (4MB) + accumulators fit VMEM,
    so short sequences run single-block (no online-softmax recurrence at
    all); longer sequences tile at <=512 (measured fastest at S>=2048).
    block_k is additionally capped at 1024 so the K/V tiles stay inside
    VMEM for skewed shapes (short query, very long KV)."""
    if sq * sk <= 1024 * 1024 and sk <= 1024:
        return sq, sk
    bq, bk = _divisor_block(sq, 512), _divisor_block(sk, 512)
    if bq % 8 or bk % 8:
        # sublane-unfriendly tiling (odd seq len) — refuse so the routing
        # layer falls back to XLA sdpa instead of a degenerate grid
        raise ValueError(f"flash_attention: no TPU-friendly block tiling "
                         f"for seq ({sq},{sk})")
    return bq, bk


def flash_attention_fn(q, k, v, causal: bool = False, scale=None,
                       block_q: int = None, block_k: int = None):
    """Pure-jax flash attention on paddle layout (B, S, H, D).

    Falls back to unblocked shapes by shrinking blocks; requires S to be a
    multiple of the (possibly shrunk) block size — callers with ragged
    shapes use the reference sdpa path (nn/functional.py).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if block_q is None or block_k is None:
        abq, abk = _auto_blocks(sq, sk)
    block_q = min(block_q, sq) if block_q else abq
    block_k = min(block_k, sk) if block_k else abk
    if sq % block_q or sk % block_k:
        raise ValueError(f"flash_attention: seq ({sq},{sk}) not divisible by "
                         f"blocks ({block_q},{block_k})")
    if causal and sq > sk:
        # queries with no visible keys (bottom-right alignment needs
        # sk >= sq for every query to see at least one key)
        raise ValueError("flash_attention: causal requires sk >= sq")
    if k.shape[2] != h:
        raise ValueError("flash_attention: repeat kv heads before the kernel")
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(d)

    def to_bh(x):
        return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                             x.shape[1], x.shape[3])

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    ob = _flash(qb, kb, vb, scale, bool(causal), block_q, block_k,
                _use_interpret())
    return jnp.swapaxes(ob.reshape(b, h, sq, d), 1, 2)


from paddle_tpu.ops.registry import register_op


@register_op("flash_attention",
             ref="paddle/phi/kernels/gpu/flash_attn_kernel.cu (capability analog)")
def flash_attention_op(q, k, v, causal=False, scale=None):
    return flash_attention_fn(q, k, v, causal=causal, scale=scale)
