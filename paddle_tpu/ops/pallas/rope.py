"""Fused rotary position embedding — Pallas TPU kernel.

Capability analog of the reference's fused_rope
(paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu, python surface
paddle.incubate.nn.functional.fused_rotary_position_embedding): the
rotation (split halves, multiply by cos/sin tables, re-concat) runs as a
single pass over the activation instead of XLA's slice/mul/concat chain.

Layout: x is (B, S, H, D), tables are (S, D/2), Llama half-split
convention (models/llama.py _rope_op). Grid tiles (batch, seq-blocks);
heads and head_dim stay whole inside a block. The backward is the inverse
rotation (same kernel, negated sin), wired through a custom VJP.

Measured honestly (v5e, 134M Llama, B=8 S=1024): standalone the kernel is
within noise of the XLA chain, but in the full train step the pallas_call
boundary blocks XLA from fusing rope into its neighbors (67.2 -> 73.9
ms/step), so routing defaults OFF (FLAGS_use_fused_rope) and the kernel
remains available for decode/irregular shapes and as the fusion anchor
for the pass framework.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["supported", "rope_fused"]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _seq_block(s: int) -> int:
    for cand in (256, 128, 64, 32, 16, 8):
        if s % cand == 0:
            return cand
    return 0


def supported(x_shape, cos_shape, x_dtype=None, cos_dtype=None) -> bool:
    if len(x_shape) != 4 or len(cos_shape) != 2:
        return False
    b, s, h, d = x_shape
    if d % 2 != 0 or tuple(cos_shape) != (s, d // 2):
        return False
    # the kernel emits x.dtype; the XLA fallback promotes with the table
    # dtype — only route shapes where the two agree
    if x_dtype is not None and cos_dtype is not None and x_dtype != cos_dtype:
        return False
    return _seq_block(s) > 0


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref, *, d2):
    x = x_ref[0]                       # (BS, H, D)
    c = cos_ref[:]                     # (BS, 1, D/2) — pre-shaped outside
    s = sin_ref[:]
    x1 = x[..., :d2]
    x2 = x[..., d2:]
    o_ref[0] = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def _run(x, cos, sin):
    b, s, h, d = x.shape
    d2 = d // 2
    bs = _seq_block(s)
    return pl.pallas_call(
        functools.partial(_rope_kernel, d2=d2),
        grid=(b, s // bs),
        in_specs=[
            pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((bs, 1, d2), lambda i, j: (j, 0, 0)),
            pl.BlockSpec((bs, 1, d2), lambda i, j: (j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, h, d), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_use_interpret(),
    )(x, cos.reshape(s, 1, d2), sin.reshape(s, 1, d2))


@jax.custom_vjp
def rope_fused(x, cos, sin):
    return _run(x, cos, sin)


def _fwd(x, cos, sin):
    return _run(x, cos, sin), (x, cos, sin)


def _bwd(res, g):
    x, cos, sin = res
    # rotation matrices are orthogonal: dx is the inverse rotation (kernel);
    # table grads are tiny (S, D/2) reductions, left to XLA
    dx = _run(g, cos, -sin)
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    g1, g2 = g[..., :d2], g[..., d2:]
    gf1, gf2 = g1.astype(jnp.float32), g2.astype(jnp.float32)
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    dcos = jnp.sum(gf1 * xf1 + gf2 * xf2, axis=(0, 2)).astype(cos.dtype)
    dsin = jnp.sum(gf2 * xf1 - gf1 * xf2, axis=(0, 2)).astype(sin.dtype)
    return dx, dcos, dsin


rope_fused.defvjp(_fwd, _bwd)
