"""Fused GroupNorm(+SiLU) — Pallas TPU kernels (forward + backward).

Capability analog of the reference's fused GroupNorm kernels
(paddle/phi/kernels/fusion/gpu/fused_layernorm / add_group_norm_silu —
the SD-UNet serving path). The round-4 UNet device profile
(bench_profile_unet.json) showed the model NORMALIZATION-bound, not
conv-bound: GroupNorm+SiLU chains cost ~60ms of a 207ms step as XLA
elementwise/reduce fusions making 4-5 HBM passes each. This kernel does
one read + one write per direction, f32 statistics in VMEM, and folds
the SiLU (and its backward) into the same pass.

Layout: x is channels-first (B, C, *spatial), flattened to rows of
(B*C, HW). One grid program handles one (batch, group) block of
(C/G, HW) rows — stats reduce over the whole block, the per-channel
affine rides the sublane dim. HW must be a lane multiple (128) on real
TPU; the 8x8-latent UNet level (HW=64) falls back to XLA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["supported", "gn_fwd", "gn_bwd"]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(x_shape, groups: int) -> bool:
    if len(x_shape) < 3:
        return False
    c = x_shape[1]
    if c % groups:
        return False
    hw = 1
    for d in x_shape[2:]:
        hw *= d
    # VMEM ceiling: each program holds the full (C/G, HW) slab (x, out,
    # grad in bwd, plus f32 temporaries) — bound the f32 slab at 4MB so
    # ~4 live copies stay inside ~16MB VMEM; larger groups fall back to
    # XLA, which handled them before this kernel existed
    if (c // groups) * hw * 4 > 4 * 1024 * 1024:
        return False
    if _use_interpret():
        return True
    return hw % 128 == 0


def _silu_fwd(y):
    return y * jax.nn.sigmoid(y)


def _silu_bwd(z, g):
    s = jax.nn.sigmoid(z)
    return g * (s * (1.0 + z * (1.0 - s)))


def _fwd_kernel(x_ref, w_ref, b_ref, o_ref, mean_ref, rstd_ref,
                *, eps, act, out_dtype):
    xf = x_ref[0].astype(jnp.float32)              # (Cg, HW)
    m = jnp.mean(xf)
    # shifted two-pass variance: E[x²]−m² cancels catastrophically for
    # mean-shifted activations (f32 rounding of E[x²] can exceed the true
    # variance, going negative -> rsqrt NaN); the second pass stays in
    # VMEM/registers so it costs VPU time, not HBM traffic
    d = xf - m
    var = jnp.mean(d * d)
    r = jax.lax.rsqrt(var + eps)
    xhat = (xf - m) * r
    y = xhat * w_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    if act == "silu":
        y = _silu_fwd(y)
    o_ref[0] = y.astype(out_dtype)
    # (1,1) vector stores — Mosaic rejects true scalar stores to VMEM
    mean_ref[0] = jnp.full((1, 1), m, jnp.float32)
    rstd_ref[0] = jnp.full((1, 1), r, jnp.float32)


def gn_fwd(x, w, b, groups: int, eps: float, act=None):
    """Returns (out, mean, rstd); mean/rstd are (B*G, 1) f32 residuals."""
    B, C = x.shape[0], x.shape[1]
    hw = x.size // (B * C)
    cg = C // groups
    # 3D blocks: (1, Cg, HW) with the trailing two dims covering the FULL
    # array dims — Cg is rarely a sublane multiple (e.g. 10 for SD's
    # C=320, G=32), and Mosaic only allows non-multiple blocks when they
    # span the whole dimension
    x3 = x.reshape(B * groups, cg, hw)
    out, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, act=act, out_dtype=x.dtype),
        grid=(B * groups,),
        in_specs=[
            pl.BlockSpec((1, cg, hw), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, cg, 1), lambda i, g=groups: (i % g, 0, 0)),
            pl.BlockSpec((1, cg, 1), lambda i, g=groups: (i % g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, cg, hw), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * groups, cg, hw), x.dtype),
            jax.ShapeDtypeStruct((B * groups, 1, 1), jnp.float32),
            jax.ShapeDtypeStruct((B * groups, 1, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x3, w.reshape(groups, cg, 1), b.reshape(groups, cg, 1))
    return out.reshape(x.shape), mean, rstd


def _bwd_kernel(x_ref, w_ref, b_ref, mean_ref, rstd_ref, g_ref,
                dx_ref, dwp_ref, dbp_ref, *, act, x_dtype):
    xf = x_ref[0].astype(jnp.float32)
    m = mean_ref[0, 0, 0]
    r = rstd_ref[0, 0, 0]
    xhat = (xf - m) * r
    w = w_ref[0].astype(jnp.float32)
    gf = g_ref[0].astype(jnp.float32)
    if act == "silu":
        z = xhat * w + b_ref[0].astype(jnp.float32)
        dz = _silu_bwd(z, gf)
    else:
        dz = gf
    dwp_ref[0] = jnp.sum(dz * xhat, axis=1, keepdims=True)   # (Cg, 1)
    dbp_ref[0] = jnp.sum(dz, axis=1, keepdims=True)
    dxhat = dz * w
    mu1 = jnp.mean(dxhat)
    mu2 = jnp.mean(dxhat * xhat)
    dx_ref[0] = (r * (dxhat - mu1 - xhat * mu2)).astype(x_dtype)


def gn_bwd(x, w, b, mean, rstd, g, groups: int, act=None):
    """Returns (dx, dw, db) given the forward residuals."""
    B, C = x.shape[0], x.shape[1]
    hw = x.size // (B * C)
    cg = C // groups
    x3 = x.reshape(B * groups, cg, hw)
    g3 = g.reshape(B * groups, cg, hw)
    dx, dw_parts, db_parts = pl.pallas_call(
        functools.partial(_bwd_kernel, act=act, x_dtype=x.dtype),
        grid=(B * groups,),
        in_specs=[
            pl.BlockSpec((1, cg, hw), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, cg, 1), lambda i, gr=groups: (i % gr, 0, 0)),
            pl.BlockSpec((1, cg, 1), lambda i, gr=groups: (i % gr, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, cg, hw), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, cg, hw), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, cg, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, cg, 1), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * groups, cg, hw), x.dtype),
            jax.ShapeDtypeStruct((B * groups, cg, 1), jnp.float32),
            jax.ShapeDtypeStruct((B * groups, cg, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x3, w.reshape(groups, cg, 1), b.reshape(groups, cg, 1), mean, rstd,
      g3)
    # per-(b,g) channel partials -> (C,) by summing the batch axis
    dw = jnp.sum(dw_parts.reshape(B, C), axis=0).astype(w.dtype)
    db = jnp.sum(db_parts.reshape(B, C), axis=0).astype(b.dtype)
    return dx.reshape(x.shape), dw, db
