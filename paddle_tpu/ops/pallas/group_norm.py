"""Fused GroupNorm(+SiLU) — Pallas TPU kernels (forward + backward).

Capability analog of the reference's fused GroupNorm kernels
(paddle/phi/kernels/fusion/gpu/fused_layernorm / add_group_norm_silu —
the SD-UNet serving path). The round-4 UNet device profile
(bench_profile_unet.json) showed the model NORMALIZATION-bound, not
conv-bound: GroupNorm+SiLU chains cost ~60ms of a 207ms step as XLA
elementwise/reduce fusions making 4-5 HBM passes each. This kernel does
one read + one write per direction, f32 statistics in VMEM, and folds
the SiLU (and its backward) into the same pass.

Layout (round 5): 4D conv maps (B, C, H, W) are consumed NATIVELY —
the only pre-kernel reshape is the leading-dim split (B, C, ...) ->
(B*G, C/G, ...), which preserves the (H, W) tiling, so the kernel reads
exactly the layout the surrounding convolutions produce. The round-4
kernel flattened spatial dims to (B*G, C/G, HW), which retiled the
array (HW lanes vs W lanes) and cost a relayout copy on BOTH sides of
every norm — the dominant share of the 37 ms/step of copy/reshape
traffic in the round-4 profile. Full-dim trailing blocks also lift the
HW % 128 restriction, so the 8x8-latent level runs the kernel too.
Non-4D inputs keep the flattened path (HW lane-multiple required).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["supported", "gn_fwd", "gn_bwd"]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _padded_elems(cg: int, spatial) -> int:
    """VMEM footprint in ELEMENTS of one (cg, *spatial) f32 block: VMEM
    buffers live in tiled layout, so the minor dim pads to 128 lanes and
    the second-minor to 8 sublanes."""
    dims = (cg,) + tuple(spatial)
    minor = -(-dims[-1] // 128) * 128
    second = -(-dims[-2] // 8) * 8 if len(dims) >= 2 else 1
    rest = 1
    for d in dims[:-2]:
        rest *= d
    return rest * second * minor


def _layout_for(x_shape, groups: int):
    """'native4d' (no relayout around the kernel, any H/W), 'flat'
    (HW lanes; needs HW % 128), or None (XLA fallback)."""
    if len(x_shape) < 3:
        return None
    c = x_shape[1]
    if c % groups:
        return None
    cg = c // groups
    hw = 1
    for d in x_shape[2:]:
        hw *= d
    # VMEM ceiling: each program holds the full (C/G, spatial) slab (x,
    # out, grad in bwd, plus f32 temporaries) — bound the f32 slab at 4MB
    # so ~4 live copies stay inside ~16MB VMEM. The 4D-native footprint
    # counts LANE PADDING (W rounds to 128): narrow-W levels whose padded
    # slab blows the budget fall back to the flattened layout (one
    # relayout copy each side) rather than to XLA.
    budget = 4 * 1024 * 1024
    if (len(x_shape) == 4
            and _padded_elems(cg, x_shape[2:]) * 4 <= budget):
        return "native4d"
    # interpret mode has no lane-tiling constraint on 'flat'; everything
    # else routes identically so CPU tests exercise the TPU decisions
    if (hw % 128 == 0 or _use_interpret()) and cg * hw * 4 <= budget:
        return "flat"
    return None


def supported(x_shape, groups: int) -> bool:
    return _layout_for(x_shape, groups) is not None


def _silu_fwd(y):
    return y * jax.nn.sigmoid(y)


def _silu_bwd(z, g):
    s = jax.nn.sigmoid(z)
    return g * (s * (1.0 + z * (1.0 - s)))


def _block_shapes(x, groups):
    """(blocked x, spatial dims tuple) — 4D keeps (H, W) native when the
    padded block fits VMEM, else flattens (one relayout, still one HBM
    pass inside the kernel)."""
    B, C = x.shape[0], x.shape[1]
    cg = C // groups
    if x.ndim == 4 and _layout_for(x.shape, groups) == "native4d":
        spatial = tuple(x.shape[2:])
    else:
        spatial = (x.size // (B * C),)
    return x.reshape((B * groups, cg) + spatial), cg, spatial


def _fwd_kernel(x_ref, w_ref, b_ref, o_ref, mean_ref, rstd_ref,
                *, eps, act, out_dtype):
    xf = x_ref[0].astype(jnp.float32)              # (Cg, *spatial)
    # pivot-shifted mean: summing (x - x[0]) keeps the accumulation at the
    # activations' SPREAD scale instead of their absolute scale, so a
    # 1000±0.01 block loses no mantissa to the offset
    pivot = xf[(0,) * xf.ndim]
    m = pivot + jnp.mean(xf - pivot)
    # shifted two-pass variance: E[x²]−m² cancels catastrophically for
    # mean-shifted activations (f32 rounding of E[x²] can exceed the true
    # variance, going negative -> rsqrt NaN); the second pass stays in
    # VMEM/registers so it costs VPU time, not HBM traffic
    d = xf - m
    var = jnp.mean(d * d)
    r = jax.lax.rsqrt(var + eps)
    xhat = (xf - m) * r
    y = xhat * w_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    if act == "silu":
        y = _silu_fwd(y)
    o_ref[0] = y.astype(out_dtype)
    # full-block vector stores — Mosaic rejects true scalar stores to VMEM
    mean_ref[0] = jnp.full(mean_ref.shape[1:], m, jnp.float32)
    rstd_ref[0] = jnp.full(rstd_ref.shape[1:], r, jnp.float32)


def gn_fwd(x, w, b, groups: int, eps: float, act=None):
    """Returns (out, mean, rstd); mean/rstd are (B*G, 1...) f32 residuals."""
    B = x.shape[0]
    xb, cg, spatial = _block_shapes(x, groups)
    ones = (1,) * len(spatial)
    zeros = (0,) * len(spatial)
    blk = (1, cg) + spatial
    out, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, act=act, out_dtype=x.dtype),
        grid=(B * groups,),
        in_specs=[
            pl.BlockSpec(blk, lambda i: (i, 0) + zeros),
            pl.BlockSpec((1, cg) + ones,
                         lambda i, g=groups: (i % g, 0) + zeros),
            pl.BlockSpec((1, cg) + ones,
                         lambda i, g=groups: (i % g, 0) + zeros),
        ],
        out_specs=[
            pl.BlockSpec(blk, lambda i: (i, 0) + zeros),
            pl.BlockSpec((1, 1) + ones, lambda i: (i, 0) + zeros),
            pl.BlockSpec((1, 1) + ones, lambda i: (i, 0) + zeros),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * groups, cg) + spatial, x.dtype),
            jax.ShapeDtypeStruct((B * groups, 1) + ones, jnp.float32),
            jax.ShapeDtypeStruct((B * groups, 1) + ones, jnp.float32),
        ],
        interpret=_use_interpret(),
    )(xb, w.reshape((groups, cg) + ones), b.reshape((groups, cg) + ones))
    return out.reshape(x.shape), mean, rstd


def _bwd_kernel(x_ref, w_ref, b_ref, mean_ref, rstd_ref, g_ref,
                dx_ref, dwp_ref, dbp_ref, *, act, x_dtype):
    xf = x_ref[0].astype(jnp.float32)              # (Cg, *spatial)
    m = mean_ref[tuple([0] * mean_ref.ndim)]
    r = rstd_ref[tuple([0] * rstd_ref.ndim)]
    xhat = (xf - m) * r
    w = w_ref[0].astype(jnp.float32)
    gf = g_ref[0].astype(jnp.float32)
    if act == "silu":
        z = xhat * w + b_ref[0].astype(jnp.float32)
        dz = _silu_bwd(z, gf)
    else:
        dz = gf
    sp_axes = tuple(range(1, xf.ndim))
    dwp_ref[0] = jnp.sum(dz * xhat, axis=sp_axes, keepdims=True)
    dbp_ref[0] = jnp.sum(dz, axis=sp_axes, keepdims=True)
    dxhat = dz * w
    mu1 = jnp.mean(dxhat)
    mu2 = jnp.mean(dxhat * xhat)
    dx_ref[0] = (r * (dxhat - mu1 - xhat * mu2)).astype(x_dtype)


def gn_bwd(x, w, b, mean, rstd, g, groups: int, act=None):
    """Returns (dx, dw, db) given the forward residuals."""
    B, C = x.shape[0], x.shape[1]
    xb, cg, spatial = _block_shapes(x, groups)
    gb = g.reshape(xb.shape)
    ones = (1,) * len(spatial)
    zeros = (0,) * len(spatial)
    blk = (1, cg) + spatial
    mean = mean.reshape((B * groups, 1) + ones)
    rstd = rstd.reshape((B * groups, 1) + ones)
    dx, dw_parts, db_parts = pl.pallas_call(
        functools.partial(_bwd_kernel, act=act, x_dtype=x.dtype),
        grid=(B * groups,),
        in_specs=[
            pl.BlockSpec(blk, lambda i: (i, 0) + zeros),
            pl.BlockSpec((1, cg) + ones,
                         lambda i, gr=groups: (i % gr, 0) + zeros),
            pl.BlockSpec((1, cg) + ones,
                         lambda i, gr=groups: (i % gr, 0) + zeros),
            pl.BlockSpec((1, 1) + ones, lambda i: (i, 0) + zeros),
            pl.BlockSpec((1, 1) + ones, lambda i: (i, 0) + zeros),
            pl.BlockSpec(blk, lambda i: (i, 0) + zeros),
        ],
        out_specs=[
            pl.BlockSpec(blk, lambda i: (i, 0) + zeros),
            pl.BlockSpec((1, cg) + ones, lambda i: (i, 0) + zeros),
            pl.BlockSpec((1, cg) + ones, lambda i: (i, 0) + zeros),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * groups, cg) + spatial, x.dtype),
            jax.ShapeDtypeStruct((B * groups, cg) + ones, jnp.float32),
            jax.ShapeDtypeStruct((B * groups, cg) + ones, jnp.float32),
        ],
        interpret=_use_interpret(),
    )(xb, w.reshape((groups, cg) + ones), b.reshape((groups, cg) + ones),
      mean, rstd, gb)
    # per-(b,g) channel partials -> (C,) by summing the batch axis
    dw = jnp.sum(dw_parts.reshape(B, C), axis=0).astype(w.dtype)
    db = jnp.sum(db_parts.reshape(B, C), axis=0).astype(b.dtype)
    return dx.reshape(x.shape), dw, db
