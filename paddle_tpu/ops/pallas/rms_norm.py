"""Fused RMSNorm — Pallas TPU kernels (forward + backward).

Capability analog of the reference's fused norm kernels
(paddle/phi/kernels/fusion/gpu/fused_rms_norm via
paddle.incubate.nn.functional.fused_rms_norm): one pass over HBM per
direction instead of XLA's default elementwise graph, f32 statistics for
bf16 activations, and a backward that recomputes the cheap per-row
statistics instead of spilling them.

Layout: the normalized axis is the last one; leading axes are flattened
to rows. Row blocks ride the VPU sublanes, the hidden dim sits in lanes
(needs H % 128 == 0 on real TPU). The backward emits per-block partial
weight grads (n_blocks, H) reduced outside the kernel — cross-block
accumulation in HBM would serialize the grid.

Routing/eligibility lives in ``supported``; callers (ops/fused_norm.py)
fall back to the lax composition when ineligible. Off-TPU the kernels run
in interpret mode so tests exercise the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["supported", "rms_fwd", "rms_bwd"]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _row_block(rows: int) -> int:
    for cand in (256, 128, 64, 32, 16, 8):
        if rows % cand == 0:
            return cand
    return 0


def supported(x_shape, w_shape) -> bool:
    if len(x_shape) < 2 or len(w_shape) != 1 or x_shape[-1] != w_shape[0]:
        return False
    h = x_shape[-1]
    rows = 1
    for d in x_shape[:-1]:
        rows *= d
    if _use_interpret():
        return _row_block(rows) > 0  # interpret mode has no lane constraint
    return h % 128 == 0 and _row_block(rows) > 0


def _fwd_kernel(x_ref, w_ref, o_ref, inv_ref, *, eps, out_dtype):
    xf = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    y = (xf * inv).astype(x_ref.dtype)
    o_ref[:] = (y.astype(jnp.float32)
                * w_ref[:].astype(jnp.float32)).astype(out_dtype)
    inv_ref[:] = inv


def rms_fwd(x, w, eps: float):
    """Returns (out, inv) with inv = rsqrt(mean(x^2, -1) + eps) as (rows, 1)
    f32 residual for the backward."""
    orig_shape = x.shape
    h = orig_shape[-1]
    rows = x.size // h
    br = _row_block(rows)
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    x2 = x.reshape(rows, h)
    out, inv = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, out_dtype=out_dtype),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), out_dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2, w.reshape(1, h))
    return out.reshape(orig_shape[:-1] + (h,)), inv


def _bwd_kernel(x_ref, w_ref, inv_ref, g_ref, dx_ref, dwp_ref, *, x_dtype,
                block_rows):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dwp_ref[:] = jnp.zeros_like(dwp_ref)

    xf = x_ref[:].astype(jnp.float32)
    inv = inv_ref[:]                                    # (BR, 1) f32
    yn = xf * inv                                       # normalized, f32
    gf = g_ref[:].astype(jnp.float32)
    dy = gf * w_ref[:].astype(jnp.float32)
    dx = inv * (dy - yn * jnp.mean(dy * yn, axis=1, keepdims=True))
    dx_ref[:] = dx.astype(x_dtype)
    # forward quantized yn to x.dtype before the w-multiply; dw sees the same.
    # Partial weight grads keep 8 sublanes (Mosaic tile floor) and accumulate
    # into one revisited output block — the TPU grid runs sequentially.
    yq = yn.astype(x_dtype).astype(jnp.float32)
    h = dwp_ref.shape[-1]
    part = jnp.sum((gf * yq).reshape(8, block_rows // 8, h), axis=1)
    dwp_ref[:] = dwp_ref[:] + part


def rms_bwd(x, w, inv, g):
    """Returns (dx, dw) given the forward residual ``inv``."""
    orig_shape = x.shape
    h = orig_shape[-1]
    rows = x.size // h
    br = _row_block(rows)
    nb = rows // br
    x2 = x.reshape(rows, h)
    g2 = g.reshape(rows, h)
    dx, dw_parts = pl.pallas_call(
        functools.partial(_bwd_kernel, x_dtype=x.dtype, block_rows=br),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, h), lambda i: (i, 0)),
            pl.BlockSpec((8, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), x.dtype),
            jax.ShapeDtypeStruct((8, h), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2, w.reshape(1, h), inv, g2)
    dw = jnp.sum(dw_parts, axis=0).astype(w.dtype)
    return dx.reshape(orig_shape), dw
