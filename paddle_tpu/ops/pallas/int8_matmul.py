"""Weight-only int8 matmul — Pallas TPU kernel (dequant INSIDE the tile).

Capability analog of the reference's ``weight_only_linear``
(paddle/phi/kernels/fusion/gpu/, python API
paddle.nn.quant.weight_only_linear): small-batch decode is bound by
weight HBM bandwidth, so the int8 weight must stream int8 all the way to
VMEM. XLA's ``x @ w_int8.astype(bf16)`` does not deliver that (measured
SLOWER than bf16 on v5e: the convert runs as its own pass); this kernel
loads int8 tiles, converts in VMEM, and feeds the MXU — weight traffic
halves.

Layout: x (B, K) bf16/f32, w (K, N) int8, per-output-channel scale (N,)
f32 -> out (B, N) in x.dtype. 1-D grid over N tiles with the FULL
contraction axis per program (decode cost is per-program latency, not
FLOPs); one dot per program, scale in the epilogue; non-divisible N rides
a padded trailing tile. Inference-path only (no custom VJP; decode runs
under no_grad).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["supported", "int8_matmul"]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(x, w) -> bool:
    """Decode-shaped only: small row count (the weight-bandwidth-bound
    regime this kernel exists for) and MXU-tileable K/N. Prefill and
    training shapes stay on XLA's dot — they are compute-bound and the
    full-row x tile would not fit VMEM."""
    if x.ndim != 2 or w.ndim != 2 or w.dtype != jnp.int8:
        return False
    K, N = w.shape
    # K is read whole per program: it only needs lane/sublane alignment
    # (128 covers both bf16 lanes and the int8 32-sublane tile)
    return x.shape[0] <= 64 and K % 128 == 0 and N % 128 == 0


def _kernel(x_ref, w_ref, s_ref, o_ref):
    wt = w_ref[...].astype(x_ref.dtype)            # dequant in VMEM
    acc = jax.lax.dot_general(
        x_ref[...], wt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_n",))
def int8_matmul(x, w, scale, block_n: int = 1024):
    """x (B, K) @ dequant(w (K, N) int8, scale (N,)) -> (B, N).

    1-D grid over N tiles with the FULL contraction axis per program:
    at decode batch sizes the cost is per-program latency, not FLOPs, so
    fewer/bigger programs win (the K axis of the quantized matrices is at
    most a few thousand — a (K, block_n) int8 tile stays well inside
    VMEM)."""
    B, K = x.shape
    Kw, N = w.shape
    assert K == Kw, (x.shape, w.shape)
    bn = min(block_n, N)
    # keep the double-buffered (K, bn) int8 tile within ~2MB of VMEM
    while K * bn > 2 * 1024 * 1024 and bn > 256:
        bn //= 2
    bn = max(128, (bn // 128) * 128)   # lane alignment
    # non-divisible N keeps the big block: pallas pads the trailing tile
    # (shrinking bn to a divisor fragments the grid — N=5504 would drop
    # to bn=128 and run 6x under HBM bandwidth)
    return pl.pallas_call(
        _kernel,
        grid=(pl.cdiv(N, bn),),
        in_specs=[
            pl.BlockSpec((B, K), lambda j: (0, 0)),
            pl.BlockSpec((K, bn), lambda j: (0, j)),
            pl.BlockSpec((1, bn), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((B, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((B, N), x.dtype),
        interpret=_use_interpret(),
    )(x, w, scale.astype(jnp.float32).reshape(1, N))
