"""Decode (single-token) cache attention — Pallas TPU kernel.

Capability analog of the reference's block_multi_head_attention
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu): at
decode time attention is a bandwidth-bound read of the KV cache. The XLA
path runs ~6 ops per layer (scores einsum, mask, softmax, weighted sum,
plus GQA head repeats that MATERIALIZE the cache rep x); this kernel does
the whole thing in one pass:

- grid (B, KV-heads, L-blocks); the ``rep`` query heads sharing a KV head
  ride one program (GQA without materializing repeated K/V),
- online-softmax accumulation across cache blocks in VMEM scratch,
- a dynamic length bound (``pos``, SMEM scalars — a traced scalar for
  the classic lockstep decode, or a PER-ROW ``(B,)`` vector for the
  chunked/speculative paths where rows sit at different cache offsets):
  blocks past a row's valid prefix skip their compute (``pl.when``), so
  padded cache tails cost DMA only, and masked positions never enter
  the softmax,
- optional int8 cache tiles (the ``int8wk`` decode recipe): K/V stream
  int8 from HBM and dequantize IN VMEM against their per-row scales
  (``k_scale``/``v_scale``, the cache's ``(..., 1)`` scale buffers) —
  the same dequant-inside-the-tile discipline as int8_matmul, so the
  quantized cache's bandwidth win survives into the kernel.

Layouts: q (B, H, D) one token per sequence; kc/vc (B, KV, L, D) padded
cache (head-major, so cache blocks are contiguous (L, D) tiles), f32/bf16
or int8 with (B, KV, L, 1) scales; out (B, H, D). Inference-path only
(no custom VJP).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["supported", "decode_attention"]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def supported(q, kc) -> bool:
    if q.ndim != 3 or kc.ndim != 4:
        return False
    B, H, D = q.shape
    _, KV, L, _ = kc.shape
    return H % KV == 0 and D % 8 == 0 and L % 128 == 0


def _kernel(pos_ref, q_ref, k_ref, v_ref, *rest, scale, bl, nl, rep, quant):
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    li = pl.program_id(2)

    @pl.when(li == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    n_valid = pos_ref[b]                           # THIS row's valid length

    @pl.when(li * bl < n_valid)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)        # (rep, D)
        k = k_ref[0, 0].astype(jnp.float32)        # (bl, D)
        v = v_ref[0, 0].astype(jnp.float32)
        if quant:
            # dequant in VMEM: int8 rows times their per-row scales —
            # the cache streamed int8 all the way from HBM
            k = k * ks_ref[0, 0].astype(jnp.float32)     # (bl, 1)
            v = v * vs_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        idx = li * bl + jax.lax.broadcasted_iota(jnp.int32, (rep, bl), 1)
        s = jnp.where(idx < n_valid, s, -jnp.inf)
        m_prev = m_scr[:, :1]                      # (rep, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :1] = corr * l_scr[:, :1] + jnp.sum(p, axis=1,
                                                     keepdims=True)
        m_scr[:, :1] = m_new
        acc_scr[...] = corr * acc_scr[...] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(li == nl - 1)
    def _done():
        o_ref[0, 0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_l",))
def decode_attention(q, kc, vc, pos, block_l: int = 256,
                     k_scale=None, v_scale=None):
    """q (B, H, D) x cache (B, KV, L, D), valid length ``pos`` (traced
    scalar, or a per-row ``(B,)`` vector when rows sit at different
    cache offsets; positions >= the row's bound are masked) -> (B, H, D).
    Int8 caches pass their per-row scale buffers via
    ``k_scale``/``v_scale`` ((B, KV, L, 1) f32) and dequantize inside
    the tile."""
    B, H, D = q.shape
    _, KV, L, _ = kc.shape
    rep = H // KV
    bl = min(block_l, L)
    while L % bl:
        bl //= 2
    nl = L // bl
    scale = 1.0 / math.sqrt(D)
    q4 = q.reshape(B, KV, rep, D)
    quant = k_scale is not None
    out_dtype = q.dtype
    pos_b = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (B,))
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, rep, D), lambda b, g, l: (b, g, 0, 0)),
        pl.BlockSpec((1, 1, bl, D), lambda b, g, l: (b, g, l, 0)),
        pl.BlockSpec((1, 1, bl, D), lambda b, g, l: (b, g, l, 0)),
    ]
    args = [pos_b, q4, kc, vc]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, bl, 1), lambda b, g, l: (b, g, l, 0)),
            pl.BlockSpec((1, 1, bl, 1), lambda b, g, l: (b, g, l, 0)),
        ]
        args += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, bl=bl, nl=nl, rep=rep,
                          quant=quant),
        grid=(B, KV, nl),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rep, D), lambda b, g, l: (b, g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, KV, rep, D), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((rep, 128), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
            pltpu.VMEM((rep, D), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(*args)
    return out.reshape(B, H, D)
