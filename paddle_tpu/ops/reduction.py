"""Reduction ops (paddle/phi/kernels reduce family; python/paddle/tensor/math.py
reductions; stat.py). Reductions lower to XLA reduce — MXU-adjacent VPU work
that XLA tiles per dtype; keepdim semantics follow paddle.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op

__all__ = [
    "sum", "mean", "prod", "max", "min", "amax", "amin", "argmax", "argmin",
    "all", "any", "std", "var", "median", "nanmedian", "nansum", "nanmean",
    "logsumexp", "count_nonzero", "mode", "quantile", "reduce_as",
]


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


@register_op("sum", ref="paddle/phi/ops/yaml/ops.yaml:sum")
def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


@register_op("mean", ref="paddle/phi/ops/yaml/ops.yaml:mean")
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@register_op("prod")
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


@register_op("max")
def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@register_op("min")
def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@register_op("amax")
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@register_op("amin")
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@register_op("argmax", differentiable=False)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    r = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return r.astype(jnp.dtype(dtype))


@register_op("argmin", differentiable=False)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    r = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return r.astype(jnp.dtype(dtype))


@register_op("all", differentiable=False)
def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@register_op("any", differentiable=False)
def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@register_op("std")
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@register_op("var")
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@register_op("median")
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


@register_op("nanmedian")
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=_axis(axis), keepdims=keepdim)


@register_op("nansum")
def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


@register_op("nanmean")
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@register_op("logsumexp")
def logsumexp(x, axis=None, keepdim=False):
    import jax.scipy.special as sp
    return sp.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@register_op("count_nonzero", differentiable=False)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim)


@register_op("mode", n_outputs=2, differentiable=False)
def mode(x, axis=-1, keepdim=False):
    from jax import lax
    sorted_x = jnp.sort(x, axis=axis)
    n = x.shape[axis]
    ax = axis % x.ndim
    # run length with segment reset: position - index of the run's start
    same = jnp.concatenate(
        [jnp.zeros_like(jnp.take(sorted_x, jnp.array([0]), axis=ax), dtype=jnp.int32),
         (jnp.diff(sorted_x, axis=ax) == 0).astype(jnp.int32)], axis=ax)
    shape = [1] * x.ndim
    shape[ax] = n
    pos = jnp.reshape(jnp.arange(n, dtype=jnp.int32), shape)
    start = lax.associative_scan(jnp.maximum, jnp.where(same == 1, -1, pos), axis=ax)
    run = pos - start + 1
    idx = jnp.argmax(run, axis=ax, keepdims=True)
    vals = jnp.take_along_axis(sorted_x, idx, axis=ax)
    # index into the ORIGINAL tensor: first position holding the mode value
    orig_idx = jnp.argmax(x == vals, axis=ax, keepdims=True)
    if not keepdim:
        vals = jnp.squeeze(vals, axis=ax)
        orig_idx = jnp.squeeze(orig_idx, axis=ax)
    return vals, orig_idx.astype(jnp.int64)


@register_op("quantile")
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


@register_op("reduce_as", ref="paddle/phi/kernels/reduce_as_kernel.h")
def reduce_as(x, target):
    """Sum x down to target's shape (the broadcast-inverse reduction)."""
    tshape = tuple(target.shape)
    nd = len(x.shape) - len(tshape)
    axes = tuple(range(nd)) + tuple(
        i + nd for i, t in enumerate(tshape) if t == 1 and x.shape[i + nd] != 1)
    out = jnp.sum(x, axis=axes, keepdims=False)
    return jnp.reshape(out, tshape)
