"""Reference-yaml op-compat table (VERDICT round-4 item 5).

Analog of paddle/phi/api/yaml/op_compat.yaml: a mechanical mapping from
every op name in the reference's ops.yaml + legacy_ops.yaml (441 names)
to where the capability lives in this framework. Four resolution tiers:

- same-name: the registry (``OPS``) or a public namespace carries the
  exact name (scanned automatically, see ``NAMESPACES``);
- alias: renamed/re-homed equivalent — value is a dotted path rooted at
  ``paddle_tpu`` that the audit IMPORTS AND VALIDATES;
- analog ("=..."): the capability exists under a different factoring
  (e.g. GSPMD sharding replaces c_embedding); prose names the owner;
- absent ("~..."): genuinely not built, with the engineering reason.

``audit()`` returns the full classification; tests/test_op_sweep.py
asserts >=95%% of yaml names resolve (same-name/alias/analog) and every
absence carries a reason.
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

__all__ = ["OP_COMPAT", "audit", "yaml_op_names"]

import os


def _yaml_files():
    # Reference checkout root; override with PADDLE_TPU_REFERENCE_ROOT on
    # machines where the reference lives elsewhere. Read per call (not at
    # import) so setting the env var after import still takes effect.
    # yaml_op_names() returns [] when the files are absent and
    # tests/test_op_sweep.py skips explicitly.
    root = os.environ.get("PADDLE_TPU_REFERENCE_ROOT", "/root/reference")
    return (os.path.join(root, "paddle/phi/api/yaml/ops.yaml"),
            os.path.join(root, "paddle/phi/api/yaml/legacy_ops.yaml"))

# alias: value = dotted attr path under paddle_tpu (validated by audit());
# analog: "=prose"; absent: "~reason"
OP_COMPAT: Dict[str, str] = {
    # ---- optimizers (yaml *_ ops are the apply kernels; the optimizer
    #      classes own the same math as one compiled update) ----
    "sgd_": "optimizer.SGD", "momentum_": "optimizer.Momentum",
    "adagrad_": "optimizer.Adagrad", "adam_": "optimizer.Adam",
    "adamw_": "optimizer.AdamW", "adamax_": "optimizer.Adamax",
    "adadelta_": "optimizer.Adadelta", "asgd_": "optimizer.ASGD",
    "rprop_": "optimizer.Rprop", "rmsprop_": "optimizer.RMSProp",
    "lamb_": "optimizer.Lamb",
    "fused_adam_": "=multi-tensor adam: the compiled train step applies "
                   "every param in ONE XLA program (parallel/train.py)",
    "merged_adam_": "=same as fused_adam_: XLA fuses the per-param "
                    "updates; no separate multi-tensor kernel needed",
    "merged_momentum_": "=see merged_adam_",
    "average_accumulates_": "incubate.ModelAverage",
    # ---- collectives (c_* fluid ops -> distributed API over mesh
    #      collectives) ----
    "c_allgather": "distributed.all_gather",
    "c_allreduce_max": "distributed.all_reduce",
    "c_allreduce_min": "distributed.all_reduce",
    "c_allreduce_prod": "distributed.all_reduce",
    "c_allreduce_sum": "distributed.all_reduce",
    "c_broadcast": "distributed.broadcast",
    "c_concat": "distributed.all_gather",
    "c_reduce_sum": "distributed.reduce",
    "c_identity": "assign",
    "c_embedding": "=tensor-parallel embedding is the GSPMD-sharded "
                   "nn.Embedding (models/llama.py llama_tp_plan shards "
                   "the table; XLA inserts the collective)",
    "c_sync_calc_stream": "=XLA owns stream ordering; documented no-op "
                          "surface in device/__init__.py",
    "c_sync_comm_stream": "=see c_sync_calc_stream",
    # ---- amp / numerics ----
    "check_finite_and_unscale_": "amp.GradScaler",
    "update_loss_scaling_": "amp.GradScaler",
    "check_numerics": "amp.debugging.check_numerics",
    "enable_check_model_nan_inf": "set_flags",
    "disable_check_model_nan_inf": "set_flags",
    "accuracy_check": "=CINN-vs-dense accuracy alignment op; this build's "
                      "equivalent gate is tests/op_test.py numeric-diff "
                      "harness + utils/subgraph_checker.py",
    # ---- losses / activations renames ----
    "bce_loss": "nn.functional.binary_cross_entropy",
    "sigmoid_cross_entropy_with_logits":
        "nn.functional.binary_cross_entropy_with_logits",
    "cross_entropy_with_softmax":
        "nn.functional.softmax_with_cross_entropy",
    "kldiv_loss": "nn.functional.kl_div",
    "logsigmoid": "nn.functional.log_sigmoid",
    "tanh_shrink": "nn.functional.tanhshrink",
    "identity_loss": "=IPU-only loss-marker op in the reference; mean/sum "
                     "reductions cover the math",
    "warpctc": "nn.functional.ctc_loss",
    "warprnnt": "nn.functional.rnnt_loss",
    # ---- interpolate family ----
    "bicubic_interp": "nn.functional.interpolate",
    "bilinear_interp": "nn.functional.interpolate",
    "linear_interp": "nn.functional.interpolate",
    "nearest_interp": "nn.functional.interpolate",
    "trilinear_interp": "nn.functional.interpolate",
    # ---- conv / pool renames ----
    "depthwise_conv2d": "nn.functional.conv2d",
    "depthwise_conv2d_transpose": "nn.functional.conv2d_transpose",
    "conv2d_transpose_bias": "nn.functional.conv2d_transpose",
    "pool2d": "nn.functional.avg_pool2d",
    "pool3d": "nn.functional.avg_pool3d",
    "max_pool2d_with_index": "nn.functional.max_pool2d",
    "max_pool3d_with_index": "nn.functional.max_pool3d",
    "unpool": "nn.functional.max_unpool2d",
    "unpool3d": "nn.functional.max_unpool3d",
    "pad3d": "nn.functional.pad",
    "shuffle_channel": "nn.functional.channel_shuffle",
    "deformable_conv": "vision.ops.deform_conv2d",
    "cudnn_lstm": "nn.LSTM",
    "rnn": "nn.RNN",
    "sync_batch_norm_": "nn.SyncBatchNorm",
    "fused_batch_norm_act": "=XLA fuses BN+activation chains (SURVEY "
                            "§7.1: elementwise fusion is the compiler's)",
    "fused_bn_add_activation": "=see fused_batch_norm_act",
    "fused_gemm_epilogue":
        "incubate.nn.functional.fused_linear_activation",
    "fused_multi_transformer":
        "incubate.nn.functional.fused_multi_head_attention",
    "fused_softmax_mask": "nn.functional.softmax_mask_fuse",
    "fused_softmax_mask_upper_triangle":
        "nn.functional.softmax_mask_fuse",
    # ---- attention ----
    "flash_attn": "nn.functional.flash_attention",
    "flash_attn_qkvpacked": "nn.functional.flash_attention",
    "memory_efficient_attention":
        "nn.functional.scaled_dot_product_attention",
    "flash_attn_unpadded": "nn.functional.flash_attn_varlen",
    "flash_attn_varlen_qkvpacked": "nn.functional.flash_attn_varlen",
    "flash_attn_with_sparse_mask": "~sparse-mask flash variant not "
                                   "built; dense mask path covers "
                                   "correctness (sdpa attn_mask)",
    "masked_multihead_attention_": "=decode-attention Pallas kernel "
                                   "(ops/pallas/decode_attention.py) "
                                   "serves the cache-attention role",
    # ---- random / init ----
    "gaussian": "normal",
    "gaussian_inplace": "normal",
    "uniform_inplace": "uniform",
    "truncated_gaussian_random": "nn.initializer.TruncatedNormal",
    "dirichlet": "distribution.Dirichlet",
    "exponential_": "Tensor.exponential_",
    "top_p_sampling": "=inference/generate.py _sample_logits "
                      "(temperature/top-k/top-p filtered sampling)",
    "random_routing": "=dropless MoE (incubate/nn/moe.py) routes all "
                      "tokens; capacity-based random routing is a "
                      "dropping variant not used on TPU",
    # ---- fft ----
    "fft_c2c": "fft.fft", "fft_r2c": "fft.rfft", "fft_c2r": "fft.irfft",
    # ---- quantization ----
    "dequantize_abs_max": "quantization.dequantize",
    "dequantize_log": "quantization.dequantize",
    "fake_quantize_abs_max": "quantization.fake_quantize",
    "fake_quantize_moving_average_abs_max": "quantization.fake_quantize",
    "fake_quantize_range_abs_max": "quantization.fake_quantize",
    "weight_dequantize": "quantization.dequantize",
    "apply_per_channel_scale": "=per-channel scales are applied inside "
                               "quantization.weight_only_linear / the "
                               "int8 Pallas matmul tile",
    # ---- tensor manipulation renames ----
    "fill": "Tensor.fill_",
    "fill_diagonal_tensor": "Tensor.fill_diagonal_tensor",
    "assign_out_": "assign",
    "assign_value_": "assign",
    "full_batch_size_like": "full",
    "full_int_array": "full",
    "full_with_tensor": "full",
    "copy_to": "Tensor.to",
    "memcpy_d2h": "=PJRT owns transfers (Tensor.numpy is the D2H path)",
    "memcpy_h2d": "=PJRT owns transfers (to_tensor is the H2D path)",
    "npu_identity": "assign",
    "trans_layout": "=XLA layout assignment owns physical layouts",
    "merge_selected_rows": "~selected-rows sparse-gradient format is not "
                           "used: embedding grads are dense under jax AD",
    "coalesce_tensor": "=XLA fuses buffers; no bucket fusion needed "
                       "(SURVEY D18 by-design)",
    "reverse": "flip",
    "elementwise_pow": "pow",
    "mean_all": "mean",
    "split_with_num": "split",
    "repeat_interleave_with_tensor_index": "repeat_interleave",
    "index_select_strided": "index_select",
    "set_value": "=Tensor.__setitem__ (jnp .at functional updates)",
    "set_value_with_tensor": "=Tensor.__setitem__",
    "tensor_unfold": "unfold_axis",
    "view_shape": "Tensor.view",
    "inverse": "linalg.inv",
    "matrix_rank_tol": "linalg.matrix_rank",
    "data": "static.data",
    "embedding_grad_dense": "=jax AD produces the dense embedding "
                            "gradient (vjp of gather); no separate op",
    # ---- vision tail (detection training landed round 5) ----
    "generate_proposals": "vision.ops.generate_proposals",
    "matrix_nms": "vision.ops.matrix_nms",
    "multiclass_nms3": "vision.ops.multiclass_nms3",
    "detection_map": "~mAP evaluation is host-side metric code in every "
                     "ecosystem (pycocotools); not an op",
    "yolo_box_head": "=yolo_box (inference decode) + yolo_loss (training) "
                     "cover the capability; the reference's fused "
                     "head-op variant is a kernel-fusion detail",
    "yolo_loss": "vision.ops.yolo_loss",
    "crf_decoding": "text.viterbi_decode",
    # ---- graph sampling ----
    "graph_khop_sampler": "geometric.khop_sampler",
    "graph_sample_neighbors": "geometric.sample_neighbors",
    "segment_pool": "geometric.segment_sum",
    # ---- misc ----
    "auc": "metric.Auc",
    "moe": "incubate.nn.MoELayer",
    "clip_by_norm": "nn.ClipGradByNorm",
}

# names the automatic scan resolves via these namespaces
NAMESPACE_PATHS = (
    "", "nn.functional", "linalg", "fft", "geometric", "vision.ops",
    "signal", "quantization", "text", "incubate.nn.functional",
    "distributed", "metric", "static", "distribution", "nn",
)


def yaml_op_names():
    names = set()
    for f in _yaml_files():
        try:
            with open(f) as fh:
                for line in fh:
                    m = re.match(r"- op\s*:\s*(\w+)", line)
                    if m:
                        names.add(m.group(1))
        except OSError:
            pass
    return sorted(names)


def _lookup(path: str):
    import paddle_tpu as paddle

    obj = paddle
    if path.startswith("Tensor."):
        from paddle_tpu.framework.tensor import Tensor
        return getattr(Tensor, path.split(".", 1)[1])
    for part in path.split("."):
        obj = getattr(obj, part)
    return obj


def audit() -> Dict[str, Tuple[str, str]]:
    """Classify every reference yaml op name.

    Returns {name: (tier, detail)} with tier in
    {"same-name", "alias", "analog", "absent", "UNRESOLVED"}; alias
    targets are import-validated (a bad path shows as UNRESOLVED)."""
    import paddle_tpu as paddle
    from paddle_tpu.ops.registry import OPS

    mods = []
    for p in NAMESPACE_PATHS:
        try:
            mods.append(_lookup(p) if p else paddle)
        except AttributeError:
            pass

    out: Dict[str, Tuple[str, str]] = {}
    for n in yaml_op_names():
        entry = OP_COMPAT.get(n)
        if entry is not None:
            if entry.startswith("~"):
                out[n] = ("absent", entry[1:])
            elif entry.startswith("="):
                out[n] = ("analog", entry[1:])
            else:
                try:
                    _lookup(entry)
                    out[n] = ("alias", entry)
                except AttributeError:
                    out[n] = ("UNRESOLVED", f"bad alias target {entry!r}")
            continue
        base = n[:-1] if n.endswith("_") else n
        if n in OPS or base in OPS or any(
                hasattr(m, n) or hasattr(m, base) for m in mods):
            out[n] = ("same-name", "")
        else:
            out[n] = ("UNRESOLVED", "no mapping")
    return out
