"""Patch the paddle-style method surface onto Tensor.

Analog of the reference's monkey-patching of math methods onto the eager
Tensor (python/paddle/base/dygraph/tensor_patch_methods.py, math_op_patch).
Indexing (__getitem__/__setitem__) goes through jnp/.at so it is traceable
and differentiable.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops import (comparison, linalg, manipulation, math as _math,
                            reduction)
from paddle_tpu.ops.registry import register_op


def _coerce(other):
    if isinstance(other, Tensor):
        return other
    return other  # scalars handled by jnp broadcasting inside impls


@register_op("getitem")
def _getitem_op(x, idx_tensors, idx_template):
    # rebuild the index tuple, substituting tensor values back in
    it = iter(idx_tensors)
    idx = tuple(next(it) if e is _IDX_SLOT else e for e in idx_template)
    if len(idx) == 1:
        idx = idx[0]
    return x[idx]


@register_op("setitem")
def _setitem_op(x, value, idx_tensors, idx_template):
    it = iter(idx_tensors)
    idx = tuple(next(it) if e is _IDX_SLOT else e for e in idx_template)
    if len(idx) == 1:
        idx = idx[0]
    slot_shape = jnp.shape(x[idx] if not isinstance(idx, tuple) else x[idx])
    v = value
    if hasattr(v, "shape") and tuple(v.shape) != slot_shape:
        # numpy assignment semantics: size-1 dims may collapse ((1,) -> ())
        if int(np.prod(v.shape)) == int(np.prod(slot_shape)):
            v = jnp.reshape(v, slot_shape)
        else:
            v = jnp.broadcast_to(v, slot_shape)
    return x.at[idx].set(v)


_IDX_SLOT = object()


def _split_index(item):
    """Split an index expression into (template, tensor list) so tensor indices
    participate in dispatch (and bool-mask indices stay on device)."""
    if not isinstance(item, tuple):
        item = (item,)
    template, tensors = [], []
    for e in item:
        if isinstance(e, Tensor):
            template.append(_IDX_SLOT)
            tensors.append(e)
        else:
            template.append(e)
    return template, tensors


def _getitem(self, item):
    template, tensors = _split_index(item)
    return _getitem_op(self, tensors, template)


def _tape_alias(t: Tensor) -> Tensor:
    """Snapshot of a tensor's (value, grad edge) for in-place rebinding.

    In-place ops record the op against this alias, then rebind the original
    tensor to the op's output — otherwise the mutated tensor would appear as
    its own grad-node input (a self-loop the backward walk can never
    schedule). The inplace-version-counter analog of the reference
    (paddle/fluid/eager/tensor_wrapper.h inplace checks).
    """
    a = Tensor(t._value, stop_gradient=t.stop_gradient)
    a._grad_node = t._grad_node
    a._out_index = t._out_index
    return a


def _setitem(self, item, value):
    template, tensors = _split_index(item)
    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value))
    out = _setitem_op(_tape_alias(self), value, tensors, template)
    # paddle semantics: in-place; preserve autograd by rebinding value+node
    self._value = out._value
    self._grad_node = out._grad_node
    self._out_index = out._out_index
    self.stop_gradient = out.stop_gradient and self.stop_gradient
    return self


_BINOPS = {
    "__add__": _math.add, "__radd__": lambda a, b: _math.add(b if isinstance(b, Tensor) else Tensor(jnp.asarray(b)), a),
    "__sub__": _math.subtract,
    "__rsub__": lambda a, b: _math.subtract(b if isinstance(b, Tensor) else Tensor(jnp.asarray(b)), a),
    "__mul__": _math.multiply,
    "__rmul__": lambda a, b: _math.multiply(b if isinstance(b, Tensor) else Tensor(jnp.asarray(b)), a),
    "__truediv__": _math.divide,
    "__rtruediv__": lambda a, b: _math.divide(b if isinstance(b, Tensor) else Tensor(jnp.asarray(b)), a),
    "__floordiv__": _math.floor_divide,
    "__mod__": _math.mod,
    "__pow__": _math.pow,
    "__rpow__": lambda a, b: _math.pow(b if isinstance(b, Tensor) else Tensor(jnp.asarray(b)), a),
    "__matmul__": linalg.matmul,
    "__eq__": comparison.equal, "__ne__": comparison.not_equal,
    "__lt__": comparison.less_than, "__le__": comparison.less_equal,
    "__gt__": comparison.greater_than, "__ge__": comparison.greater_equal,
    "__and__": comparison.logical_and, "__or__": comparison.logical_or,
    "__xor__": comparison.logical_xor,
}

_METHODS = {
    # math
    "add": _math.add, "subtract": _math.subtract, "multiply": _math.multiply,
    "divide": _math.divide, "pow": _math.pow, "abs": _math.abs,
    "exp": _math.exp, "log": _math.log, "sqrt": _math.sqrt,
    "rsqrt": _math.rsqrt, "square": _math.square, "tanh": _math.tanh,
    "sigmoid": _math.sigmoid, "sin": _math.sin, "cos": _math.cos,
    "clip": _math.clip, "scale": _math.scale, "floor": _math.floor,
    "ceil": _math.ceil, "round": _math.round, "sign": _math.sign,
    "reciprocal": _math.reciprocal, "cumsum": _math.cumsum,
    "cumprod": _math.cumprod, "isnan": _math.isnan, "isinf": _math.isinf,
    "isfinite": _math.isfinite, "maximum": _math.maximum, "minimum": _math.minimum,
    "neg": _math.neg, "lerp": _math.lerp,
    # reduction
    "sum": reduction.sum, "mean": reduction.mean, "prod": reduction.prod,
    "max": reduction.max, "min": reduction.min, "argmax": reduction.argmax,
    "argmin": reduction.argmin, "all": reduction.all, "any": reduction.any,
    "std": reduction.std, "var": reduction.var, "logsumexp": reduction.logsumexp,
    # manipulation
    "reshape": manipulation.reshape, "transpose": manipulation.transpose,
    "squeeze": manipulation.squeeze, "unsqueeze": manipulation.unsqueeze,
    "flatten": manipulation.flatten, "tile": manipulation.tile,
    "expand": manipulation.expand, "broadcast_to": manipulation.broadcast_to,
    "gather": manipulation.gather, "gather_nd": manipulation.gather_nd,
    "scatter": manipulation.scatter, "index_select": manipulation.index_select,
    "flip": manipulation.flip, "roll": manipulation.roll,
    "split": manipulation.split, "chunk": manipulation.chunk,
    "unbind": manipulation.unbind, "topk": manipulation.topk,
    "sort": manipulation.sort, "argsort": manipulation.argsort,
    "tril": manipulation.tril, "triu": manipulation.triu,
    "masked_fill": manipulation.masked_fill, "masked_select": manipulation.masked_select,
    "take_along_axis": manipulation.take_along_axis,
    "repeat_interleave": manipulation.repeat_interleave,
    "diagonal": manipulation.diagonal, "where": manipulation.where,
    "pad": manipulation.pad,
    # comparison
    "equal": comparison.equal, "not_equal": comparison.not_equal,
    "less_than": comparison.less_than, "less_equal": comparison.less_equal,
    "greater_than": comparison.greater_than, "greater_equal": comparison.greater_equal,
    "logical_and": comparison.logical_and, "logical_or": comparison.logical_or,
    "logical_not": comparison.logical_not, "allclose": comparison.allclose,
    "isclose": comparison.isclose, "equal_all": comparison.equal_all,
    # linalg
    "matmul": linalg.matmul, "mm": linalg.mm, "bmm": linalg.bmm,
    "dot": linalg.dot, "norm": linalg.norm, "cholesky": linalg.cholesky,
    "inverse": linalg.inv,
}


def _inplace_variant(fn):
    def method(self, *args, **kwargs):
        out = fn(_tape_alias(self), *args, **kwargs)
        self._value = out._value
        self._grad_node = out._grad_node
        self._out_index = out._out_index
        self.stop_gradient = out.stop_gradient
        return self
    return method


_INPLACE = {
    "add_": _math.add, "subtract_": _math.subtract, "multiply_": _math.multiply,
    "divide_": _math.divide, "clip_": _math.clip, "scale_": _math.scale,
    "exp_": _math.exp, "sqrt_": _math.sqrt, "reciprocal_": _math.reciprocal,
    "tanh_": _math.tanh, "fill_": None, "zero_": None,
}


def monkey_patch_tensor() -> None:
    for name, fn in _BINOPS.items():
        setattr(Tensor, name, (lambda f: lambda self, other: f(self, other))(fn))
    Tensor.__neg__ = lambda self: _math.neg(self)
    Tensor.__abs__ = lambda self: _math.abs(self)
    Tensor.__invert__ = lambda self: comparison.logical_not(self)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    Tensor.__hash__ = lambda self: id(self)

    for name, fn in _METHODS.items():
        setattr(Tensor, name, (lambda f: lambda self, *a, **kw: f(self, *a, **kw))(fn))

    for name, fn in _INPLACE.items():
        if fn is not None:
            setattr(Tensor, name, _inplace_variant(fn))

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        return self

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        return self

    Tensor.fill_ = fill_
    Tensor.zero_ = zero_

    @property
    def T(self):
        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    Tensor.T = T

    def t(self):
        if self.ndim > 2:
            raise ValueError("t() expects a tensor with <= 2 dimensions")
        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    Tensor.t = t


def _patch_round4_methods():
    """Round-4 op-compat tail: Tensor.to / view / exponential_ (reference
    tensor_patch_methods analogs)."""
    from paddle_tpu.framework import random as rnd
    import jax

    def _to(self, *args, **kwargs):
        """Tensor.to(dtype) / .to(place[, dtype]): dtype casts apply,
        places are a no-op (PJRT owns placement)."""
        from paddle_tpu.framework.dtype import convert_dtype

        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a in (
                    "float32", "float64", "float16", "bfloat16", "int8",
                    "int16", "int32", "int64", "uint8", "bool"):
                dtype = a
                continue
            try:  # dtype OBJECTS (paddle.float16, np/jnp dtypes) count too
                dtype = convert_dtype(a)
            except Exception:
                pass  # a place/device spec: placement is PJRT's (no-op)
        if dtype is not None:
            return self.astype(dtype)
        return self

    def _view(self, shape_or_dtype):
        """Tensor.view: reshape for shapes, bitcast for dtypes (the
        reference's zero-copy view; XLA may materialize)."""
        if isinstance(shape_or_dtype, (list, tuple)):
            return manipulation.reshape(self, shape_or_dtype)
        return manipulation.view_dtype(self, shape_or_dtype)

    def _exponential_(self, lam=1.0):
        """In-place fill with Exponential(lam) samples."""
        u = jax.random.uniform(rnd.split_key(), self.shape,
                               minval=1e-7, maxval=1.0)
        self._set_value((-jnp.log(u) / lam).astype(self._value.dtype))
        return self

    Tensor.to = _to
    Tensor.view = _view
    Tensor.view_as = lambda self, other: manipulation.reshape(
        self, list(other.shape))
    Tensor.exponential_ = _exponential_


_patch_round4_methods()


def _patch_fill_diagonal():
    """Tensor.fill_diagonal_ / fill_diagonal_tensor_ (reference
    tensor_patch_methods + fill_diagonal kernels)."""
    import jax.numpy as _jnp

    def _fill_diagonal_(self, value, offset=0, wrap=False):
        v = self._value
        if v.ndim == 2:
            from paddle_tpu.ops.schema_defs import _fill_diagonal
            self._set_value(_fill_diagonal(v, value, offset, wrap))
            return self
        # ndim > 2: reference fills the main HYPER-diagonal (i, i, ..., i)
        # and requires equal dims
        if len(set(v.shape)) != 1:
            raise ValueError(
                "fill_diagonal_: tensors with ndim > 2 must have equal "
                f"dims, got {v.shape}")
        i = _jnp.arange(v.shape[0])
        self._set_value(v.at[tuple([i] * v.ndim)].set(value))
        return self

    def _fill_diagonal_tensor(self, y, offset=0, dim1=0, dim2=1):
        """Returns a copy with tensor ``y`` written along the
        (dim1, dim2) diagonal (fill_diagonal_tensor_kernel analog)."""
        v = self._value
        yv = y._value if isinstance(y, Tensor) else _jnp.asarray(y)
        if v.ndim != 2 or (dim1, dim2) != (0, 1):
            raise NotImplementedError(
                "fill_diagonal_tensor: only 2-D (dim1=0, dim2=1) "
                "supported")
        n = min(v.shape[0] + min(offset, 0),
                v.shape[1] - max(offset, 0), min(v.shape))
        if tuple(yv.shape) != (n,):
            raise ValueError(
                f"fill_diagonal_tensor: y shape {tuple(yv.shape)} != "
                f"diagonal length ({n},)")
        i = _jnp.arange(n)
        out = v.at[i - min(offset, 0), i + max(offset, 0)].set(yv)
        return Tensor(out)

    def _fill_diagonal_tensor_(self, y, offset=0, dim1=0, dim2=1):
        out = _fill_diagonal_tensor(self, y, offset, dim1, dim2)
        self._set_value(out._value)
        return self

    Tensor.fill_diagonal_ = _fill_diagonal_
    Tensor.fill_diagonal_tensor = _fill_diagonal_tensor
    Tensor.fill_diagonal_tensor_ = _fill_diagonal_tensor_


_patch_fill_diagonal()
