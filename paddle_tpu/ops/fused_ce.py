"""Fused lm-head + softmax cross-entropy, chunked over the vocab.

Capability analog of the reference's fused softmax-CE kernels
(paddle/phi/kernels/fusion/ + cross_entropy_with_softmax): the (T, V)
logits matrix for a 32k vocab at T = B*S tokens is the single largest
activation in an LM step (f32 logits alone are ~1GB at B=8, S=1024 —
pure HBM traffic). This op never materializes it:

- forward: one ``lax.scan`` over vocab chunks computes the online
  max/sum-exp merge (the flash-attention recurrence, applied to the
  softmax denominator) plus the gold-label logit; residuals are just
  (hidden, head, lse) — O(T) extra, not O(T*V),
- backward: a second scan recomputes each logits chunk, forms
  ``softmax - onehot`` locally, and accumulates dhidden / dhead chunk by
  chunk on the MXU.

Numerics: logits accumulate in f32 (preferred_element_type) regardless of
the io dtype; results match the unfused path to f32 roundoff.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_linear_cross_entropy"]


def _chunks(V: int, chunk: int) -> int:
    return (V + chunk - 1) // chunk


def _pad_head(head, V: int, chunk: int):
    n = _chunks(V, chunk)
    pad = n * chunk - V
    if pad:
        head = jnp.pad(head, ((0, 0), (0, pad)))
    return head, n, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(hidden, head, labels, chunk: int = 4096):
    """mean over tokens of CE(softmax(hidden @ head), labels).

    hidden: (T, H); head: (H, V); labels: (T,) int. Returns a scalar f32.
    Labels outside [0, V) (e.g. -100 padding) contribute zero loss and
    zero gradient, with the mean still taken over ALL T tokens — exactly
    the unfused path's semantics (one_hot of an invalid label is all-zero).
    """
    loss, _ = _fwd_impl(hidden, head, labels, chunk)
    return loss


def _fwd_impl(hidden, head, labels, chunk):
    T, H = hidden.shape
    V = head.shape[1]
    chunk = min(chunk, V)  # never pad past one chunk of waste
    headp, n, _ = _pad_head(head, V, chunk)
    hchunks = jnp.moveaxis(headp.reshape(H, n, chunk), 1, 0)  # (n, H, C)
    labels = labels.astype(jnp.int32)

    def body(carry, xs):
        m, s, gold = carry
        w, idx = xs                                   # (H, C), chunk index
        logits = jax.lax.dot_general(
            hidden, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (T, C) f32
        base = idx * chunk
        cols = base + jnp.arange(chunk)[None, :]
        valid = cols < V
        logits = jnp.where(valid, logits, -jnp.inf)
        # online logsumexp merge
        m_c = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        # gold logit if the label lands in this chunk
        in_chunk = (labels >= base) & (labels < base + chunk)
        local = jnp.clip(labels - base, 0, chunk - 1)
        g = jnp.take_along_axis(logits, local[:, None], axis=1)[:, 0]
        gold = jnp.where(in_chunk, g, gold)
        return (m_new, s, gold), None

    m0 = jnp.full((T,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((T,), jnp.float32)
    g0 = jnp.zeros((T,), jnp.float32)
    (m, s, gold), _ = jax.lax.scan(
        body, (m0, s0, g0), (hchunks, jnp.arange(n)))
    lse = m + jnp.log(s)
    valid = (labels >= 0) & (labels < V)
    loss = jnp.mean(jnp.where(valid, lse - gold, 0.0))
    return loss, lse


def _fwd(hidden, head, labels, chunk):
    loss, lse = _fwd_impl(hidden, head, labels, chunk)
    return loss, (hidden, head, labels.astype(jnp.int32), lse)


def _bwd(chunk, res, g):
    hidden, head, labels, lse = res
    T, H = hidden.shape
    V = head.shape[1]
    chunk = min(chunk, V)
    headp, n, _ = _pad_head(head, V, chunk)
    hchunks = jnp.moveaxis(headp.reshape(H, n, chunk), 1, 0)
    valid = ((labels >= 0) & (labels < V)).astype(jnp.float32)
    scale = (g / T) * valid  # mean over ALL tokens; ignored rows get 0

    def body(dh, xs):
        w, idx = xs
        logits = jax.lax.dot_general(
            hidden, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        base = idx * chunk
        cols = base + jnp.arange(chunk)[None, :]
        valid = cols < V
        p = jnp.where(valid, jnp.exp(logits - lse[:, None]), 0.0)
        onehot = (cols == labels[:, None]).astype(jnp.float32)
        dlogits = ((p - onehot) * scale[:, None]).astype(hidden.dtype)
        dh = dh + jax.lax.dot_general(
            dlogits, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw = jax.lax.dot_general(
            hidden, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # (H, C)
        return dh, dw

    dh0 = jnp.zeros((T, H), jnp.float32)
    dh, dws = jax.lax.scan(body, dh0, (hchunks, jnp.arange(n)))
    dhead = jnp.moveaxis(dws, 0, 1).reshape(H, n * chunk)[:, :V]
    return (dh.astype(hidden.dtype), dhead.astype(head.dtype), None)


fused_linear_cross_entropy.defvjp(_fwd, _bwd)


from paddle_tpu.ops.registry import register_op


@register_op("fused_linear_ce",
             ref="paddle/phi/kernels/fusion/ + cross_entropy_with_softmax "
                 "(capability analog)")
def fused_linear_ce_op(hidden, head, labels, chunk: int = 4096):
    return fused_linear_cross_entropy(hidden, head, labels, chunk)
