"""Fused lm-head + softmax cross-entropy, chunked over the vocab.

Capability analog of the reference's fused softmax-CE kernels
(paddle/phi/kernels/fusion/ + cross_entropy_with_softmax): the (T, V)
logits matrix for a 32k vocab at T = B*S tokens is the single largest
activation in an LM step (f32 logits alone are ~1GB at B=8, S=1024 —
pure HBM traffic). This op never materializes it:

- forward: one ``lax.scan`` over vocab chunks computes the online
  max/sum-exp merge (the flash-attention recurrence, applied to the
  softmax denominator) plus the gold-label logit; residuals are just
  (hidden, head, lse) — O(T) extra, not O(T*V),
- backward: a second scan recomputes each logits chunk, forms
  ``softmax - onehot`` locally, and accumulates dhidden / dhead chunk by
  chunk on the MXU.

Numerics: logits accumulate in f32 (preferred_element_type) regardless of
the io dtype; results match the unfused path to f32 roundoff.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_linear_cross_entropy"]


def _chunks(V: int, chunk: int) -> int:
    return (V + chunk - 1) // chunk


def _pad_head(head, V: int, chunk: int):
    n = _chunks(V, chunk)
    pad = n * chunk - V
    if pad:
        head = jnp.pad(head, ((0, 0), (0, pad)))
    return head, n, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(hidden, head, labels, chunk: int = 4096):
    """mean over VALID tokens of CE(softmax(hidden @ head), labels).

    hidden: (T, H); head: (H, V); labels: (T,) int. Returns a scalar f32.
    Labels outside [0, V) (e.g. -100 ignore padding) contribute zero loss
    and zero gradient and are excluded from the mean denominator — the
    F.cross_entropy(ignore_index=...) semantics. Callers with a
    non-negative ignore_index must map it to -1 before the call.
    """
    loss, _ = _fwd_impl(hidden, head, labels, chunk)
    return loss


def _fwd_impl(hidden, head, labels, chunk):
    T, H = hidden.shape
    V = head.shape[1]
    chunk = min(chunk, V)  # never pad past one chunk of waste
    headp, n, _ = _pad_head(head, V, chunk)
    hchunks = jnp.moveaxis(headp.reshape(H, n, chunk), 1, 0)  # (n, H, C)
    labels = labels.astype(jnp.int32)

    def body(carry, xs):
        m, s, gold = carry
        w, idx = xs                                   # (H, C), chunk index
        logits = jax.lax.dot_general(
            hidden, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (T, C) f32
        base = idx * chunk
        cols = base + jnp.arange(chunk)[None, :]
        valid = cols < V
        logits = jnp.where(valid, logits, -jnp.inf)
        # online logsumexp merge
        m_c = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        # gold logit if the label lands in this chunk
        in_chunk = (labels >= base) & (labels < base + chunk)
        local = jnp.clip(labels - base, 0, chunk - 1)
        g = jnp.take_along_axis(logits, local[:, None], axis=1)[:, 0]
        gold = jnp.where(in_chunk, g, gold)
        return (m_new, s, gold), None

    m0 = jnp.full((T,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((T,), jnp.float32)
    g0 = jnp.zeros((T,), jnp.float32)
    (m, s, gold), _ = jax.lax.scan(
        body, (m0, s0, g0), (hchunks, jnp.arange(n)))
    lse = m + jnp.log(s)
    valid = (labels >= 0) & (labels < V)
    denom = jnp.maximum(jnp.sum(valid), 1)
    loss = jnp.sum(jnp.where(valid, lse - gold, 0.0)) / denom
    return loss, lse


def _fwd(hidden, head, labels, chunk):
    loss, lse = _fwd_impl(hidden, head, labels, chunk)
    return loss, (hidden, head, labels.astype(jnp.int32), lse)


def _bwd(chunk, res, g):
    hidden, head, labels, lse = res
    T, H = hidden.shape
    V = head.shape[1]
    chunk = min(chunk, V)
    headp, n, _ = _pad_head(head, V, chunk)
    hchunks = jnp.moveaxis(headp.reshape(H, n, chunk), 1, 0)
    valid = ((labels >= 0) & (labels < V)).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    scale = (g / denom) * valid  # mean over VALID tokens; ignored rows get 0

    def body(dh, xs):
        w, idx = xs
        logits = jax.lax.dot_general(
            hidden, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        base = idx * chunk
        cols = base + jnp.arange(chunk)[None, :]
        valid = cols < V
        p = jnp.where(valid, jnp.exp(logits - lse[:, None]), 0.0)
        onehot = (cols == labels[:, None]).astype(jnp.float32)
        dlogits = ((p - onehot) * scale[:, None]).astype(hidden.dtype)
        dh = dh + jax.lax.dot_general(
            dlogits, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw = jax.lax.dot_general(
            hidden, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # (H, C)
        return dh, dw

    dh0 = jnp.zeros((T, H), jnp.float32)
    dh, dws = jax.lax.scan(body, dh0, (hchunks, jnp.arange(n)))
    dhead = jnp.moveaxis(dws, 0, 1).reshape(H, n * chunk)[:, :V]
    return (dh.astype(hidden.dtype), dhead.astype(head.dtype), None)


fused_linear_cross_entropy.defvjp(_fwd, _bwd)


from paddle_tpu.ops.registry import register_op


def auto_chunk(T: int, V: int) -> int:
    """Vocab chunk size bounding the transient f32 logits block.

    One chunk of (T, chunk) f32 logits lives at a time; if the FULL (T, V)
    block fits the budget, a single chunk (scan of length 1) wins — the
    scan serialization + per-chunk dW dynamic-update-slices cost more than
    the extra HBM traffic (v5e, T=8192 V=30522: fwd+bwd 6.9 ms at
    chunk=8192 vs 4.2 ms single-chunk). Floor: one 128-lane block — at
    extreme T even that may exceed the budget; the block is the smallest
    MXU-shaped unit, so the budget is best-effort there."""
    from paddle_tpu.flags import flags
    budget = flags.fused_ce_logits_budget_mb * 1e6
    if T * V * 4 <= budget:
        return V
    per = int(budget // (T * 4))
    return min(V, max(128, (per // 128) * 128))


def fused_lm_loss(hidden, head, labels, ignore_index: int = None):
    """Shared model-side routing for the fused lm-head CE (the single
    entry the Llama/GPT/BERT loss paths use — one place to tune
    thresholds/chunking): flattens (..., H) hidden against an (H, V)
    head, maps a non-negative ignore_index out of range (negative
    sentinels are already invalid to the kernel), auto-picks the vocab
    chunk, and dispatches through the op registry so the eager tape
    records it."""
    import paddle_tpu as paddle
    from paddle_tpu.ops.registry import op_api

    T = 1
    for d in hidden.shape[:-1]:
        T *= int(d)
    H = int(hidden.shape[-1])
    h2 = hidden.reshape([T, H])
    lab = labels.reshape([-1])
    if ignore_index is not None and ignore_index >= 0:
        lab = paddle.where(lab == ignore_index,
                           paddle.full_like(lab, -1), lab)
    return op_api("fused_linear_ce")(h2, head, lab,
                                     chunk=auto_chunk(T, int(head.shape[1])))


@register_op("fused_linear_ce",
             ref="paddle/phi/kernels/fusion/ + cross_entropy_with_softmax "
                 "(capability analog)")
def fused_linear_ce_op(hidden, head, labels, chunk: int = None):
    if chunk is None:
        chunk = auto_chunk(hidden.shape[0], head.shape[1])
    return fused_linear_cross_entropy(hidden, head, labels, chunk)
