"""Fused lm-head + softmax cross-entropy, chunked over the vocab.

Capability analog of the reference's fused softmax-CE kernels
(paddle/phi/kernels/fusion/ + cross_entropy_with_softmax): the (T, V)
logits matrix for a 32k vocab at T = B*S tokens is the single largest
activation in an LM step (f32 logits alone are ~1GB at B=8, S=1024 —
pure HBM traffic). This op never materializes it:

- forward: one ``lax.scan`` over vocab chunks computes the online
  max/sum-exp merge (the flash-attention recurrence, applied to the
  softmax denominator) plus the gold-label logit; residuals are just
  (hidden, head, lse) — O(T) extra, not O(T*V),
- backward: a second scan recomputes each logits chunk, forms
  ``softmax - onehot`` locally, and accumulates dhidden / dhead chunk by
  chunk on the MXU.

Numerics: logits accumulate in f32 (preferred_element_type) regardless of
the io dtype; results match the unfused path to f32 roundoff.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_linear_cross_entropy"]


def _chunks(V: int, chunk: int) -> int:
    return (V + chunk - 1) // chunk


def _pad_head(head, V: int, chunk: int):
    n = _chunks(V, chunk)
    pad = n * chunk - V
    if pad:
        head = jnp.pad(head, ((0, 0), (0, pad)))
    return head, n, pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(hidden, head, labels, chunk: int = 4096,
                               ignore_index: int = -100):
    """mean over non-ignored tokens of CE(softmax(hidden @ head), labels).

    hidden: (T, H); head: (H, V); labels: (T,) int. Returns a scalar f32.
    Exact F.cross_entropy(ignore_index=...) semantics: ONLY labels equal
    to ``ignore_index`` (any value, including the default -100) are
    excluded from the mean denominator; labels outside [0, V) that are
    not the ignore_index contribute zero loss and zero gradient but DO
    count in the denominator (matching one_hot's zeroing of out-of-range
    labels in the unfused path).

    Contract change from the round-3 kernel: ALL out-of-range labels used
    to be excluded from the denominator. Callers that followed the old
    "map your sentinel to -1" advice must now pass ``ignore_index=-1``.
    """
    _warn_legacy_sentinel(labels, ignore_index)
    loss, _ = _fwd_impl(hidden, head, labels, chunk, ignore_index)
    return loss


_checked_legacy_sentinel = False


def _warn_legacy_sentinel(labels, ignore_index):
    # Surface callers relying on the pre-round-4 contract ("map your
    # sentinel to -1"): under the new exact semantics a -1 label with the
    # default ignore_index=-100 counts in the mean denominator. Only
    # checkable when labels are concrete (eager); traced labels skip.
    # Checks only the FIRST eager call — a per-call jnp.any + host sync
    # would tax the eager hot path for a warning that never fires.
    global _checked_legacy_sentinel
    if _checked_legacy_sentinel or ignore_index == -1:
        return
    if isinstance(labels, jax.core.Tracer):
        return
    _checked_legacy_sentinel = True
    if bool(jnp.any(jnp.asarray(labels) == -1)):
        import warnings
        warnings.warn(
            "fused_linear_cross_entropy saw labels == -1 with "
            f"ignore_index={ignore_index}: since round 4 these count in the "
            "mean denominator (zero loss, larger denominator). Pass "
            "ignore_index=-1 to exclude them, matching the old behavior.",
            stacklevel=3)


def _fwd_impl(hidden, head, labels, chunk, ignore_index):
    T, H = hidden.shape
    V = head.shape[1]
    chunk = min(chunk, V)  # never pad past one chunk of waste
    headp, n, _ = _pad_head(head, V, chunk)
    hchunks = jnp.moveaxis(headp.reshape(H, n, chunk), 1, 0)  # (n, H, C)
    labels = labels.astype(jnp.int32)

    def body(carry, xs):
        m, s, gold = carry
        w, idx = xs                                   # (H, C), chunk index
        logits = jax.lax.dot_general(
            hidden, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # (T, C) f32
        base = idx * chunk
        cols = base + jnp.arange(chunk)[None, :]
        valid = cols < V
        logits = jnp.where(valid, logits, -jnp.inf)
        # online logsumexp merge
        m_c = jnp.max(logits, axis=1)
        m_new = jnp.maximum(m, m_c)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=1)
        # gold logit if the label lands in this chunk
        in_chunk = (labels >= base) & (labels < base + chunk)
        local = jnp.clip(labels - base, 0, chunk - 1)
        g = jnp.take_along_axis(logits, local[:, None], axis=1)[:, 0]
        gold = jnp.where(in_chunk, g, gold)
        return (m_new, s, gold), None

    m0 = jnp.full((T,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((T,), jnp.float32)
    g0 = jnp.zeros((T,), jnp.float32)
    (m, s, gold), _ = jax.lax.scan(
        body, (m0, s0, g0), (hchunks, jnp.arange(n)))
    lse = m + jnp.log(s)
    not_ignored = labels != ignore_index
    in_range = (labels >= 0) & (labels < V)
    denom = jnp.maximum(jnp.sum(not_ignored), 1)
    loss = jnp.sum(jnp.where(not_ignored & in_range, lse - gold, 0.0)) / denom
    return loss, lse


def _fwd(hidden, head, labels, chunk, ignore_index):
    loss, lse = _fwd_impl(hidden, head, labels, chunk, ignore_index)
    return loss, (hidden, head, labels.astype(jnp.int32), lse)


def _bwd(chunk, ignore_index, res, g):
    hidden, head, labels, lse = res
    T, H = hidden.shape
    V = head.shape[1]
    chunk = min(chunk, V)
    headp, n, _ = _pad_head(head, V, chunk)
    hchunks = jnp.moveaxis(headp.reshape(H, n, chunk), 1, 0)
    not_ignored = (labels != ignore_index).astype(jnp.float32)
    active = (not_ignored *
              ((labels >= 0) & (labels < V)).astype(jnp.float32))
    denom = jnp.maximum(jnp.sum(not_ignored), 1.0)
    # mean over non-ignored tokens; ignored AND out-of-range rows get 0 grad
    scale = (g / denom) * active

    def body(dh, xs):
        w, idx = xs
        logits = jax.lax.dot_general(
            hidden, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        base = idx * chunk
        cols = base + jnp.arange(chunk)[None, :]
        valid = cols < V
        p = jnp.where(valid, jnp.exp(logits - lse[:, None]), 0.0)
        onehot = (cols == labels[:, None]).astype(jnp.float32)
        dlogits = ((p - onehot) * scale[:, None]).astype(hidden.dtype)
        dh = dh + jax.lax.dot_general(
            dlogits, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw = jax.lax.dot_general(
            hidden, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # (H, C)
        return dh, dw

    dh0 = jnp.zeros((T, H), jnp.float32)
    dh, dws = jax.lax.scan(body, dh0, (hchunks, jnp.arange(n)))
    dhead = jnp.moveaxis(dws, 0, 1).reshape(H, n * chunk)[:, :V]
    return (dh.astype(hidden.dtype), dhead.astype(head.dtype), None)


fused_linear_cross_entropy.defvjp(_fwd, _bwd)


from paddle_tpu.ops.registry import register_op


def auto_chunk(T: int, V: int) -> int:
    """Vocab chunk size bounding the transient f32 logits block.

    One chunk of (T, chunk) f32 logits lives at a time; if the FULL (T, V)
    block fits the budget, a single chunk (scan of length 1) wins — the
    scan serialization + per-chunk dW dynamic-update-slices cost more than
    the extra HBM traffic (v5e, T=8192 V=30522: fwd+bwd 6.9 ms at
    chunk=8192 vs 4.2 ms single-chunk). Floor: one 128-lane block — at
    extreme T even that may exceed the budget; the block is the smallest
    MXU-shaped unit, so the budget is best-effort there."""
    from paddle_tpu.flags import flags
    budget = flags.fused_ce_logits_budget_mb * 1e6
    if T * V * 4 <= budget:
        return V
    per = int(budget // (T * 4))
    return min(V, max(128, (per // 128) * 128))


def fused_lm_loss(hidden, head, labels, ignore_index: int = -100):
    """Shared model-side routing for the fused lm-head CE (the single
    entry the Llama/GPT/BERT loss paths use — one place to tune
    thresholds/chunking): flattens (..., H) hidden against an (H, V)
    head, auto-picks the vocab chunk, and dispatches through the op
    registry so the eager tape records it. ``ignore_index`` is passed
    straight to the kernel (any value, F.cross_entropy semantics)."""
    from paddle_tpu.ops.registry import op_api

    T = 1
    for d in hidden.shape[:-1]:
        T *= int(d)
    H = int(hidden.shape[-1])
    h2 = hidden.reshape([T, H])
    lab = labels.reshape([-1])
    return op_api("fused_linear_ce")(h2, head, lab,
                                     chunk=auto_chunk(T, int(head.shape[1])),
                                     ignore_index=ignore_index)


def fused_ce_lax(hidden, head, labels, ignore_index: int = -100):
    """Canonical decomposition of the fused lm-head CE: materialized
    logits + stable logsumexp in base lax prims — same semantics as the
    chunked kernel to f32 roundoff. Used by passes.decompose_fused (and
    through it the ONNX exporter), which cannot lower the kernel's
    lax.scan over vocab chunks."""
    labels = labels.astype(jnp.int32)
    logits = jax.lax.dot_general(
        hidden, head, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m = jnp.max(logits, axis=1, keepdims=True)
    lse = (m[:, 0] + jnp.log(jnp.sum(jnp.exp(logits - m), axis=1)))
    V = head.shape[1]
    safe = jnp.clip(labels, 0, V - 1)
    gold = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
    not_ignored = labels != ignore_index
    in_range = (labels >= 0) & (labels < V)
    denom = jnp.maximum(jnp.sum(not_ignored), 1)
    return jnp.sum(jnp.where(not_ignored & in_range, lse - gold, 0.0)) / denom


@register_op("fused_linear_ce",
             ref="paddle/phi/kernels/fusion/ + cross_entropy_with_softmax "
                 "(capability analog)")
def fused_linear_ce_op(hidden, head, labels, chunk: int = None,
                       ignore_index: int = -100):
    from paddle_tpu.flags import flags
    if flags.decompose_fused_ops:
        _warn_legacy_sentinel(labels, ignore_index)
        return fused_ce_lax(hidden, head, labels, ignore_index)
    if chunk is None:
        chunk = auto_chunk(hidden.shape[0], head.shape[1])
    return fused_linear_cross_entropy(hidden, head, labels, chunk,
                                      ignore_index)
