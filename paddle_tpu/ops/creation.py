"""Tensor creation ops (python/paddle/tensor/creation.py + random.py analogs).

Random ops draw subkeys from the global splittable Generator
(paddle_tpu/framework/random.py), so `paddle_tpu.seed(n)` reproduces eager
runs; jitted model code threads keys explicitly instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.flags import flags
from paddle_tpu.framework import random as rnd
from paddle_tpu.framework.dtype import convert_dtype
from paddle_tpu.framework.tensor import Tensor, to_tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "meshgrid", "rand", "randn", "randint", "randperm", "uniform",
    "normal", "standard_normal", "bernoulli", "multinomial", "poisson",
    "tril_indices", "triu_indices", "clone", "numel", "diagflat",
    "binomial", "complex",
]


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = convert_dtype(default or flags.default_dtype)
    return d


def _wrap(v):
    return Tensor(v, stop_gradient=True)


def zeros(shape, dtype=None):
    return _wrap(jnp.zeros(tuple(shape), _dt(dtype)))


def ones(shape, dtype=None):
    return _wrap(jnp.ones(tuple(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None):
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return _wrap(jnp.full(tuple(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None):
    v = x.value if isinstance(x, Tensor) else x
    return _wrap(jnp.zeros_like(v, dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None):
    v = x.value if isinstance(x, Tensor) else x
    return _wrap(jnp.ones_like(v, dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None):
    v = x.value if isinstance(x, Tensor) else x
    return _wrap(jnp.full_like(v, fill_value, dtype=convert_dtype(dtype)))


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, float):
            dtype = dtype or flags.default_dtype
            break
    else:
        dtype = dtype or "int64"
    return _wrap(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None):
    return _wrap(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return _wrap(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return _wrap(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def meshgrid(*args):
    vals = [a.value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    if len(vals) == 1 and isinstance(args[0], (list, tuple)):
        vals = [a.value if isinstance(a, Tensor) else jnp.asarray(a) for a in args[0]]
    return tuple(_wrap(v) for v in jnp.meshgrid(*vals, indexing="ij"))


def diagflat(x, offset=0):
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    return _wrap(jnp.diagflat(v, k=offset))


def clone(x):
    return Tensor(x.value, stop_gradient=x.stop_gradient)


def numel(x):
    return _wrap(jnp.asarray(x.size, dtype=jnp.int64))


# ---- random ---------------------------------------------------------------

def _key():
    return rnd.split_key()


def rand(shape, dtype=None):
    return _wrap(jax.random.uniform(_key(), tuple(shape), _dt(dtype)))


def randn(shape, dtype=None):
    return _wrap(jax.random.normal(_key(), tuple(shape), _dt(dtype)))


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return _wrap(jax.random.randint(_key(), tuple(shape), low, high,
                                    dtype=convert_dtype(dtype)))


def randperm(n, dtype="int64"):
    return _wrap(jax.random.permutation(_key(), n).astype(convert_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    return _wrap(jax.random.uniform(_key(), tuple(shape), _dt(dtype),
                                    minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean.value if isinstance(mean, Tensor) else mean
        s = std.value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return _wrap(jax.random.normal(_key(), shp) * s + m)
    shape = shape or (1,)
    return _wrap(jax.random.normal(_key(), tuple(shape)) * std + mean)


def bernoulli(x):
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    return _wrap(jax.random.bernoulli(_key(), v).astype(v.dtype))


def multinomial(x, num_samples=1, replacement=False):
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement or num_samples == 1:
        out = jax.random.categorical(_key(), logits, axis=-1,
                                     shape=(*v.shape[:-1], num_samples))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(_key(), v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return _wrap(out.astype(jnp.int64))


def poisson(x):
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    return _wrap(jax.random.poisson(_key(), v).astype(v.dtype))


def tril_indices(row, col, offset=0):
    r, c = np.tril_indices(row, offset, col)
    return _wrap(jnp.asarray(np.stack([r, c]), dtype=jnp.int64))


def triu_indices(row, col=None, offset=0):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return _wrap(jnp.asarray(np.stack([r, c]), dtype=jnp.int64))


def binomial(count, prob, name=None):
    """Draws from Binomial(count, prob) elementwise
    (paddle/phi/kernels/cpu/binomial_kernel.cc analog; int64 output)."""
    c = count.value if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob.value if isinstance(prob, Tensor) else jnp.asarray(prob)
    shape = jnp.broadcast_shapes(jnp.shape(c), jnp.shape(p))
    out = jax.random.binomial(_key(), jnp.broadcast_to(c, shape).astype(jnp.float32),
                              jnp.broadcast_to(p, shape).astype(jnp.float32))
    return _wrap(out.astype(jnp.int64))


def complex(real, imag, name=None):
    """complex64/128 from real+imaginary parts (complex_kernel.cc)."""
    r = real.value if isinstance(real, Tensor) else jnp.asarray(real)
    i = imag.value if isinstance(imag, Tensor) else jnp.asarray(imag)
    return _wrap(jax.lax.complex(r, i))
