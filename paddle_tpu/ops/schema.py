"""Declarative op schemas + codegen fan-out (the ops.yaml analog).

The reference defines each op ONCE in YAML (paddle/phi/ops/yaml/ops.yaml:
args, output, infer_meta, kernel, backward) and generators fan that schema
out into the C++ API, grad nodes, dist (auto-parallel-aware) API and docs
(paddle/phi/api/yaml/generator/api_gen.py, backward_api_gen.py,
dist_api_gen.py). TPU-native redesign: the schema is a Python dataclass and
the "generators" are one function, because the targets collapsed —

  schema.impl          -> registry entry (eager dispatch + tape + jit; the
                          API/backward codegen: jax.vjp is the grad node)
  schema.spmd          -> SPMD-rule binding (the dist_api_gen analog,
                          ops/spmd_rules.py table)
  schema doc fields    -> generated docstring on the public API
  schema.sample        -> OpTest sweep inputs (tests/test_op_sweep.py),
                          so every schema'd op is numerics+grad tested

``describe(name)`` renders the schema as documentation; ``get_schema``
gives programmatic access (OpMetaInfo introspection analog).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

__all__ = ["OpSchema", "build_ops", "get_schema", "describe"]

_SCHEMAS: Dict[str, "OpSchema"] = {}


class OpSchema:
    """One op, declaratively.

    name     — registry name (= public API name)
    impl     — pure-JAX implementation (jax values in/out, traceable)
    args     — signature string for docs, e.g. "x, label, delta=1.0"
    doc      — one-paragraph description
    ref      — reference citation (file:anchor in /root/reference)
    spmd     — SPMD rule: a registered rule name ("elementwise",
               "reduction", ...) or None for the replicate-all default
    differentiable / n_outputs — registry dispatch properties
    sample   — OpTest sweep spec: dict(in_=[input makers], kw={}, grad=[...],
               jit=bool, rtol/atol) using the maker mini-language in
               tests/test_op_sweep.py ("f"/"fneg"/"ii"/"bb" tuples)
    """

    def __init__(self, name: str, impl: Callable, args: str, doc: str,
                 ref: str = "", spmd: Optional[str] = "elementwise",
                 differentiable: bool = True, n_outputs: int = 1,
                 sample: Optional[dict] = None):
        self.name = name
        self.impl = impl
        self.args = args
        self.doc = doc
        self.ref = ref
        self.spmd = spmd
        self.differentiable = differentiable
        self.n_outputs = n_outputs
        self.sample = sample


def get_schema(name: str) -> OpSchema:
    return _SCHEMAS[name]


def describe(name: str) -> str:
    """Render a schema as documentation (the docs-generation target)."""
    s = _SCHEMAS[name]
    lines = [f"{s.name}({s.args})", "", s.doc]
    lines.append("")
    lines.append(f"    differentiable: {s.differentiable}")
    lines.append(f"    sharding rule:  {s.spmd or 'default (replicate)'}")
    if s.ref:
        lines.append(f"    reference:      {s.ref}")
    return "\n".join(lines)


def build_ops(schemas: Sequence[OpSchema], namespace: Dict[str, Any]):
    """The generator: one schema -> registered op + doc'd API + SPMD rule
    binding. Returns the list of public names (for __all__)."""
    from paddle_tpu.ops.registry import register_op
    from paddle_tpu.ops import spmd_rules as R

    names = []
    for s in schemas:
        if s.name in _SCHEMAS:
            raise KeyError(f"op schema {s.name!r} defined twice")
        _SCHEMAS[s.name] = s
        api = register_op(s.name, ref=s.ref, n_outputs=s.n_outputs,
                          differentiable=s.differentiable)(s.impl)
        api.__name__ = s.name
        api.__qualname__ = s.name
        api.__doc__ = describe(s.name)
        api.schema = s
        if s.spmd is not None and s.name not in R.SPMD_RULES:
            R.SPMD_RULES[s.name] = R.get_spmd_rule(s.spmd)
        namespace[s.name] = api
        names.append(s.name)
    return names
