"""Shape/layout manipulation ops.

Analog of python/paddle/tensor/manipulation.py + phi view/stride kernels
(paddle/phi/kernels/stride/). On TPU these are mostly free at compile time —
XLA folds reshapes/transposes into surrounding fusions; there is no separate
"view kernel" generation to maintain.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.registry import register_op

__all__ = [
    "reshape", "transpose", "concat", "stack", "split", "chunk", "squeeze",
    "unsqueeze", "flatten", "tile", "expand", "broadcast_to", "expand_as",
    "gather", "gather_nd", "scatter", "scatter_nd_add", "index_select",
    "index_add", "index_put", "slice", "strided_slice", "flip", "roll", "cast",
    "assign", "take_along_axis", "put_along_axis", "unbind", "topk", "sort",
    "argsort", "searchsorted", "masked_select", "masked_fill", "where",
    "nonzero", "unique", "repeat_interleave", "unstack", "moveaxis",
    "swapaxes", "as_complex", "as_real", "diagonal", "diag", "diag_embed",
    "tril", "triu", "rot90", "one_hot", "pad", "crop", "tensordot",
    "scatter_nd", "unfold_axis", "as_strided", "view_dtype", "shape",
]


@register_op("reshape", ref="paddle/phi/ops/yaml/ops.yaml:reshape")
def reshape(x, shape):
    return jnp.reshape(x, tuple(int(s) for s in shape))


@register_op("transpose", ref="paddle/phi/ops/yaml/ops.yaml:transpose")
def transpose(x, perm=None):
    return jnp.transpose(x, axes=tuple(perm) if perm is not None else None)


@register_op("concat", ref="paddle/phi/ops/yaml/ops.yaml:concat")
def concat(xs, axis=0):
    return jnp.concatenate(list(xs), axis=axis)


@register_op("stack")
def stack(xs, axis=0):
    return jnp.stack(list(xs), axis=axis)


@register_op("split", n_outputs=-1)
def split(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    # sections list, possibly with one -1
    sections = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sections:
        i = sections.index(-1)
        sections[i] = total - (sum(s for s in sections if s != -1))
    idx = []
    acc = 0
    for s in sections[:-1]:
        acc += s
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


@register_op("chunk", n_outputs=-1)
def chunk(x, chunks, axis=0):
    return tuple(jnp.array_split(x, chunks, axis=axis))


@register_op("squeeze")
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, int):
        axis = [axis]
    axis = tuple(a for a in axis if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


@register_op("unsqueeze")
def unsqueeze(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    out = x
    for a in sorted(axis):
        out = jnp.expand_dims(out, a)
    return out


@register_op("flatten")
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    start = start_axis % nd
    stop = stop_axis % nd
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return jnp.reshape(x, shape)


@register_op("tile")
def tile(x, repeat_times):
    return jnp.tile(x, tuple(repeat_times))


@register_op("expand")
def expand(x, shape):
    shape = list(shape)
    # paddle: -1 means keep original dim
    x_shape = (1,) * (len(shape) - x.ndim) + tuple(x.shape)
    out_shape = tuple(x_shape[i] if s == -1 else int(s) for i, s in enumerate(shape))
    return jnp.broadcast_to(jnp.reshape(x, x_shape), out_shape)


@register_op("broadcast_to")
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))


@register_op("expand_as")
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@register_op("gather")
def gather(x, index, axis=0):
    idx = index
    if idx.ndim == 0:
        idx = jnp.reshape(idx, (1,))
    return jnp.take(x, idx, axis=axis)


@register_op("gather_nd")
def gather_nd(x, index):
    depth = index.shape[-1]
    idx = tuple(index[..., i] for i in range(depth))
    return x[idx]


@register_op("scatter")
def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    # paddle overwrite=False: destination rows are zeroed, then accumulated
    return x.at[index].set(jnp.zeros_like(updates)).at[index].add(updates)


@register_op("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    depth = index.shape[-1]
    idx = tuple(index[..., i] for i in range(depth))
    return x.at[idx].add(updates)


@register_op("index_select")
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@register_op("index_add")
def index_add(x, index, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[index].add(jnp.moveaxis(value, axis, 0))
    return jnp.moveaxis(moved, 0, axis)


@register_op("index_put")
def index_put(x, indices, value, accumulate=False):
    idx = tuple(indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


import builtins as _builtins
builtins_slice = _builtins.slice


@register_op("slice")
def slice(x, axes, starts, ends):
    sl = [builtins_slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        sl[a] = builtins_slice(s, e)
    return x[tuple(sl)]


@register_op("strided_slice")
def strided_slice(x, axes, starts, ends, strides):
    sl = [builtins_slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        sl[a] = builtins_slice(s, e, st)
    return x[tuple(sl)]


@register_op("flip")
def flip(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


@register_op("roll")
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@register_op("cast", ref="paddle/phi/ops/yaml/ops.yaml:cast")
def cast(x, dtype):
    from paddle_tpu.framework.dtype import convert_dtype
    return x.astype(convert_dtype(dtype))


@register_op("assign")
def assign(x):
    return jnp.asarray(x)


@register_op("take_along_axis")
def take_along_axis(x, indices, axis, broadcast=True):
    return jnp.take_along_axis(x, indices, axis=axis)


@register_op("put_along_axis")
def put_along_axis(x, indices, values, axis, reduce="assign"):
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    if reduce in ("add", "mul", "multiply"):
        # scatter with accumulate along one axis via explicit index grid
        idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in range(x.ndim)])
               for d, s in enumerate(indices.shape)]
        idx = [jnp.broadcast_to(g, indices.shape) for g in idx]
        idx[axis] = indices
        vals = jnp.broadcast_to(values, indices.shape)
        if reduce == "add":
            return x.at[tuple(idx)].add(vals)
        return x.at[tuple(idx)].multiply(vals)
    raise NotImplementedError(f"put_along_axis reduce={reduce}")


@register_op("unbind", n_outputs=-1)
def unbind(x, axis=0):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


@register_op("unstack", n_outputs=-1)
def unstack(x, axis=0):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


@register_op("topk", n_outputs=2)
def topk(x, k, axis=-1, largest=True, sorted=True):
    if axis != -1 and axis != x.ndim - 1:
        xt = jnp.moveaxis(x, axis, -1)
        vals, idx = topk.op.impl(xt, k, -1, largest, sorted)
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)
    if largest:
        vals, idx = lax.top_k(x, k)
    else:
        vals, idx = lax.top_k(-x, k)
        vals = -vals
    return vals, idx.astype(jnp.int64)


@register_op("sort")
def sort(x, axis=-1, descending=False):
    r = jnp.sort(x, axis=axis)
    return jnp.flip(r, axis=axis) if descending else r


@register_op("argsort", differentiable=False)
def argsort(x, axis=-1, descending=False):
    r = jnp.argsort(x, axis=axis)
    if descending:
        r = jnp.flip(r, axis=axis)
    return r.astype(jnp.int64)


@register_op("searchsorted", differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    r = jnp.searchsorted(sorted_sequence, values, side=side)
    return r.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("masked_select", differentiable=False)
def masked_select(x, mask):
    # dynamic output shape: eager-only (host round trip); inside jit use where()
    import numpy as np
    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


@register_op("masked_fill")
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@register_op("where")
def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.stack(jnp.nonzero(condition), axis=1)
    return jnp.where(condition, x, y)


@register_op("nonzero", differentiable=False)
def nonzero(x, as_tuple=False):
    import numpy as np
    nz = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i) for i in nz)
    return jnp.stack([jnp.asarray(i) for i in nz], axis=1) if nz else jnp.zeros((0, x.ndim), jnp.int64)


@register_op("unique", differentiable=False)
def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    import numpy as np
    res = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


@register_op("repeat_interleave")
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register_op("moveaxis")
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@register_op("swapaxes")
def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, axis1, axis2)


@register_op("as_complex")
def as_complex(x):
    return lax.complex(x[..., 0], x[..., 1])


@register_op("as_real")
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register_op("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op("diag")
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=offset)
        return jnp.where(mask, d, padding_value)
    return jnp.diag(x, k=offset)


@register_op("diag_embed")
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def emb(v):
        return jnp.diag(v, k=offset)
    out = jnp.apply_along_axis(emb, -1, x) if x.ndim > 1 else jnp.diag(x, k=offset)
    return out


@register_op("tril")
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@register_op("triu")
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@register_op("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register_op("one_hot", differentiable=False)
def one_hot(x, num_classes):
    import jax
    return jax.nn.one_hot(x, num_classes)


@register_op("pad")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    pad = list(pad)
    if len(pad) == 2 * x.ndim:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle style: pad applies to last len(pad)//2 dims (reversed pairs),
        # or spatial dims per data_format for 4D/5D
        n_spatial = len(pad) // 2
        width = [(0, 0)] * x.ndim
        if x.ndim in (4, 5) and data_format in ("NCHW", "NCDHW"):
            dims = list(range(2, 2 + n_spatial))
        elif x.ndim in (4, 5):
            dims = list(range(1, 1 + n_spatial))
        else:
            dims = list(range(x.ndim - n_spatial, x.ndim))
        for i, d in enumerate(reversed(dims)):
            width[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, width, mode="constant", constant_values=value)
    return jnp.pad(x, width, mode=jmode)


@register_op("crop")
def crop(x, shape, offsets=None):
    if offsets is None:
        offsets = [0] * x.ndim
    sl = tuple(builtins_slice(o, o + s) for o, s in zip(offsets, shape))
    return x[sl]


@register_op("tensordot")
def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


@register_op("scatter_nd",
             ref="python/paddle/tensor/manipulation.py:3885")
def scatter_nd(index, updates, shape):
    """Scatter-add updates into zeros(shape) at nd indices (duplicates
    sum, paddle semantics)."""
    depth = index.shape[-1]
    idx = tuple(index[..., i] for i in range(depth))
    out = jnp.zeros(tuple(shape), updates.dtype)
    return out.at[idx].add(updates)


@register_op("unfold_axis",
             ref="python/paddle/tensor/manipulation.py:6446 (paddle.unfold)")
def unfold_axis(x, axis, size, step):
    """Sliding windows of `size` every `step` along `axis` -> the window
    becomes a trailing dim (torch.Tensor.unfold semantics)."""
    axis = axis % x.ndim
    if step <= 0:
        raise ValueError(f"unfold: step must be positive, got {step}")
    if size > x.shape[axis]:
        raise ValueError(f"unfold: size {size} exceeds dim {x.shape[axis]} "
                         f"along axis {axis}")
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    win = jnp.arange(size)
    idx = starts[:, None] + win[None, :]                 # (n, size)
    out = jnp.take(x, idx, axis=axis)                    # windows at `axis`
    # paddle: windows stay at axis, window-size dim goes LAST
    return jnp.moveaxis(out, axis + 1, -1)


@register_op("as_strided",
             ref="paddle/phi/kernels/stride/as_strided_kernel.cc")
def as_strided(x, shape, stride, offset=0):
    """Strided view over x's flattened buffer. XLA has no aliasing views,
    so this materializes the gather — semantics (incl. overlapping
    windows) match the reference; the compiler fuses the gather into
    consumers where profitable."""
    flat = jnp.reshape(x, (-1,))
    idx = jnp.asarray(offset, jnp.int32)
    for s, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(s, dtype=jnp.int32) * int(st)
    return jnp.take(flat, idx.reshape(tuple(int(s) for s in shape)))


@register_op("view_dtype",
             ref="paddle/phi/kernels/stride/view_kernel.cc (bitcast view)")
def view_dtype(x, dtype):
    """Reinterpret the buffer as another dtype (bitcast). Same total
    byte count required; the trailing dim rescales by the size ratio."""
    import numpy as _np
    from paddle_tpu.framework.dtype import convert_dtype
    dt = jnp.dtype(convert_dtype(dtype))
    src = jnp.dtype(x.dtype)
    if dt.itemsize == src.itemsize:
        return lax.bitcast_convert_type(x, dt)
    if src.itemsize % dt.itemsize == 0:
        out = lax.bitcast_convert_type(x, dt)  # adds a trailing dim
        return out.reshape(x.shape[:-1] + (-1,))
    k = dt.itemsize // src.itemsize
    if x.shape[-1] % k:
        raise ValueError(
            f"view dtype {src}->{dt}: last dim {x.shape[-1]} not a "
            f"multiple of {k}")
    return lax.bitcast_convert_type(
        x.reshape(x.shape[:-1] + (x.shape[-1] // k, k)), dt)


@register_op("shape", differentiable=False,
             ref="paddle/phi/kernels/shape_kernel.cc")
def shape(x):
    return jnp.asarray(x.shape, jnp.int32)
