"""Comparison + logical ops (python/paddle/tensor/logic.py analog)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op
from paddle_tpu.framework.tensor import Tensor

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
    "isclose", "allclose", "equal_all", "is_empty",
]


def _cmp(name, fn):
    @register_op(name, differentiable=False)
    def _op(x, y):
        return fn(x, y)
    globals()[name] = _op
    return _op


_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)
_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("logical_and", jnp.logical_and)
_cmp("logical_or", jnp.logical_or)
_cmp("logical_xor", jnp.logical_xor)
_cmp("bitwise_and", jnp.bitwise_and)
_cmp("bitwise_or", jnp.bitwise_or)
_cmp("bitwise_xor", jnp.bitwise_xor)


@register_op("logical_not", differentiable=False)
def logical_not(x):
    return jnp.logical_not(x)


@register_op("bitwise_not", differentiable=False)
def bitwise_not(x):
    return jnp.bitwise_not(x)


@register_op("isclose", differentiable=False)
def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("allclose", differentiable=False)
def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("equal_all", differentiable=False)
def equal_all(x, y):
    return jnp.array_equal(x, y)


def is_empty(x):
    v = x.value if isinstance(x, Tensor) else x
    return Tensor(jnp.asarray(v.size == 0))
