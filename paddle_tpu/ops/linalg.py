"""Linear algebra ops (python/paddle/tensor/linalg.py + phi matmul/blas analogs).

matmul is THE op on TPU: it feeds the MXU. All matmuls go through one impl so
dtype policy (bf16 inputs / f32 accumulation via preferred_element_type) is
applied uniformly — the analog of the reference's blas wrapper funcs
(paddle/phi/kernels/funcs/blas/).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op

__all__ = [
    "matmul", "mm", "bmm", "dot", "mv", "t", "einsum", "norm", "dist",
    "cholesky", "qr", "svd", "inv", "pinv", "solve", "triangular_solve",
    "cholesky_solve", "lu", "matrix_power", "matrix_rank", "det", "slogdet",
    "eig", "eigh", "eigvals", "eigvalsh", "lstsq", "cond", "cov", "corrcoef",
    "cross", "histogram", "bincount", "multi_dot",
    "lu_unpack",
]


@register_op("matmul", ref="paddle/phi/ops/yaml/ops.yaml:matmul; kernel paddle/phi/kernels/impl/matmul_kernel_impl.h")
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    # f32 accumulation on MXU for low-precision inputs
    pet = None
    if jnp.dtype(x.dtype) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        pet = jnp.float32
    out = jnp.matmul(x, y, preferred_element_type=pet)
    return out.astype(x.dtype) if pet is not None else out


@register_op("mm")
def mm(x, y):
    return jnp.matmul(x, y)


@register_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y)


@register_op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register_op("mv")
def mv(x, vec):
    return jnp.matmul(x, vec)


@register_op("t")
def t(x):
    return x.T if x.ndim >= 2 else x


@register_op("einsum")
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


@register_op("norm")
def norm(x, p=None, axis=None, keepdim=False):
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    if axis is None and p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


@register_op("dist")
def dist(x, y, p=2):
    return jnp.linalg.norm(jnp.ravel(x - y), ord=p)


@register_op("cholesky")
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@register_op("qr", n_outputs=2)
def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


@register_op("svd", n_outputs=3)
def svd(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2)


@register_op("inv")
def inv(x):
    return jnp.linalg.inv(x)


@register_op("pinv")
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@register_op("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@register_op("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@register_op("cholesky_solve")
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@register_op("lu", n_outputs=3, differentiable=False)
def lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv.astype(jnp.int32) + 1, jnp.zeros((), jnp.int32)


@register_op("matrix_power")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@register_op("matrix_rank", differentiable=False)
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register_op("det")
def det(x):
    return jnp.linalg.det(x)


@register_op("slogdet", n_outputs=2)
def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


@register_op("eig", n_outputs=2, differentiable=False)
def eig(x):
    return jnp.linalg.eig(x)


@register_op("eigh", n_outputs=2)
def eigh(x, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@register_op("eigvals", differentiable=False)
def eigvals(x):
    return jnp.linalg.eigvals(x)


@register_op("eigvalsh")
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register_op("lstsq", n_outputs=4, differentiable=False)
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register_op("cond", differentiable=False)
def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@register_op("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@register_op("corrcoef")
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@register_op("cross")
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


@register_op("histogram", differentiable=False)
def histogram(x, bins=100, min=0, max=0):
    if min == 0 and max == 0:
        range_ = None
    else:
        range_ = (min, max)
    hist, _ = jnp.histogram(x, bins=bins, range=range_)
    return hist


@register_op("bincount", differentiable=False)
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


@register_op("multi_dot")
def multi_dot(xs):
    return jnp.linalg.multi_dot(list(xs))


@register_op("lu_unpack",
             ref="paddle/phi/kernels/lu_unpack_kernel.h")
def lu_unpack(lu_mat, pivots, unpack_ludata=True, unpack_pivots=True):
    """(P, L, U) from lu() output. pivots are 1-based sequential row
    swaps (LAPACK convention, as paddle.linalg.lu returns)."""
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(lu_mat[..., :, :k], -1) \
            + jnp.eye(m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[..., :k, :])
    if unpack_pivots:
        piv = pivots.astype(jnp.int32) - 1      # 0-based swap targets
        perm0 = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32),
                                 pivots.shape[:-1] + (m,))

        def swap(perm, i):
            j = piv[..., i]
            pi = jnp.take(perm, i, axis=-1)
            pj = jnp.take_along_axis(perm, j[..., None], axis=-1)[..., 0]
            perm = jnp.where(jnp.arange(m) == i, pj[..., None], perm)
            perm = jnp.where(jnp.arange(m) == j[..., None],
                             pi[..., None], perm)
            return perm, None

        perm, _ = jax.lax.scan(swap, perm0,
                               jnp.arange(pivots.shape[-1]))
        # rows of P: P @ A applies the permutation; perm[i] = source row
        P = jax.nn.one_hot(perm, m, axis=-1, dtype=lu_mat.dtype)
        P = jnp.swapaxes(P, -1, -2)
    return P, L, U
