"""Elementwise + binary math ops.

Analog of the reference's elementwise/activation phi kernels
(paddle/phi/kernels/{cpu,gpu}/*_kernel.cc, ops.yaml schemas) and the Python
surface python/paddle/tensor/math.py. Each op is a pure-JAX impl registered in
the op table; XLA fuses chains of these into single TPU kernels (the reference
needed CINN + hand-written fused kernels for the same effect).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.registry import register_op

__all__: list = []


def _export(name):
    __all__.append(name)


def _unary(name, fn, ref="", differentiable=True):
    @register_op(name, ref=ref, differentiable=differentiable)
    def _op(x):
        return fn(x)
    _op.__name__ = name
    _export(name)
    globals()[name] = _op
    return _op


def _binary(name, fn, ref="", differentiable=True):
    @register_op(name, ref=ref, differentiable=differentiable)
    def _op(x, y):
        return fn(x, y)
    _op.__name__ = name
    _export(name)
    globals()[name] = _op
    return _op


# ---- unary ----------------------------------------------------------------
_unary("abs", jnp.abs, ref="paddle/phi/ops/yaml/ops.yaml:abs")
_unary("neg", jnp.negative)
_unary("exp", jnp.exp)
_unary("expm1", jnp.expm1)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log10", jnp.log10)
_unary("log1p", jnp.log1p)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lax.rsqrt)
_unary("square", jnp.square)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("asin", jnp.arcsin)
_unary("acos", jnp.arccos)
_unary("atan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("asinh", jnp.arcsinh)
_unary("acosh", jnp.arccosh)
_unary("atanh", jnp.arctanh)
_unary("erf", jax.scipy.special.erf)
_unary("erfinv", jax.scipy.special.erfinv)
_unary("floor", jnp.floor, differentiable=False)
_unary("ceil", jnp.ceil, differentiable=False)
_unary("round", jnp.round, differentiable=False)
_unary("trunc", jnp.trunc, differentiable=False)
_unary("frac", lambda x: x - jnp.trunc(x))
_unary("sign", jnp.sign, differentiable=False)
_unary("reciprocal", jnp.reciprocal)
_unary("sigmoid", jax.nn.sigmoid)
_unary("logit", jax.scipy.special.logit)
_unary("isnan", jnp.isnan, differentiable=False)
_unary("isinf", jnp.isinf, differentiable=False)
_unary("isfinite", jnp.isfinite, differentiable=False)
_unary("digamma", jax.scipy.special.digamma)
_unary("lgamma", jax.scipy.special.gammaln)
_unary("i0", lambda x: jax.scipy.special.i0(x))
_unary("conj", jnp.conj)
_unary("real", jnp.real)
_unary("imag", jnp.imag)
_unary("angle", jnp.angle)
_unary("deg2rad", jnp.deg2rad)
_unary("rad2deg", jnp.rad2deg)

# ---- binary ---------------------------------------------------------------
_binary("add", jnp.add, ref="paddle/phi/ops/yaml/ops.yaml:add")
_binary("subtract", jnp.subtract)
_binary("multiply", jnp.multiply)
_binary("divide", jnp.divide)
_binary("floor_divide", jnp.floor_divide, differentiable=False)
_binary("mod", jnp.mod, differentiable=False)
_binary("remainder", jnp.remainder, differentiable=False)
_binary("maximum", jnp.maximum)
_binary("minimum", jnp.minimum)
_binary("fmax", jnp.fmax)
_binary("fmin", jnp.fmin)
_binary("atan2", jnp.arctan2)
_binary("hypot", jnp.hypot)
_binary("logaddexp", jnp.logaddexp)
_binary("nextafter", jnp.nextafter, differentiable=False)
_binary("copysign", jnp.copysign)
_binary("heaviside", jnp.heaviside, differentiable=False)
_binary("gcd", jnp.gcd, differentiable=False)
_binary("lcm", jnp.lcm, differentiable=False)
_binary("inner", jnp.inner)
_binary("outer", jnp.outer)
_binary("kron", jnp.kron)


@register_op("pow", ref="paddle/phi/ops/yaml/ops.yaml:pow")
def pow(x, y):
    return jnp.power(x, y)
_export("pow")


@register_op("scale", ref="paddle/phi/ops/yaml/ops.yaml:scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale
_export("scale")


@register_op("clip", ref="paddle/phi/ops/yaml/ops.yaml:clip")
def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)
_export("clip")


@register_op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)
_export("lerp")


@register_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)
_export("stanh")


@register_op("multiply_scalar", differentiable=True)
def multiply_scalar(x, s):
    return x * s
_export("multiply_scalar")


@register_op("cumsum")
def cumsum(x, axis=None):
    if axis is None:
        return jnp.cumsum(jnp.ravel(x))
    return jnp.cumsum(x, axis=axis)
_export("cumsum")


@register_op("cumprod")
def cumprod(x, dim=None):
    if dim is None:
        return jnp.cumprod(jnp.ravel(x))
    return jnp.cumprod(x, axis=dim)
_export("cumprod")


def _cum_extreme(x, axis, is_max):
    """Running max/min with the index of the extremum (paddle cummax/cummin
    parity: returns (values, indices)); differentiable in the values."""
    ax = axis % x.ndim
    shape = [1] * x.ndim
    shape[ax] = x.shape[ax]
    idx = jnp.broadcast_to(
        jnp.reshape(jnp.arange(x.shape[ax]), shape), x.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = (bv >= av) if is_max else (bv <= av)
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    return lax.associative_scan(combine, (x, idx), axis=ax)


@register_op("cummax", n_outputs=2)
def cummax(x, axis=-1):
    return _cum_extreme(x, axis, is_max=True)
_export("cummax")


@register_op("cummin", n_outputs=2)
def cummin(x, axis=-1):
    return _cum_extreme(x, axis, is_max=False)
_export("cummin")


@register_op("diff")
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)
_export("diff")


@register_op("trapezoid")
def trapezoid(y, x=None, dx=1.0, axis=-1):
    if x is None:
        return jnp.trapezoid(y, dx=dx, axis=axis)
    return jnp.trapezoid(y, x=x, axis=axis)
_export("trapezoid")


@register_op("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)
_export("addmm")


@register_op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)
_export("nan_to_num")
