"""Fused RMSNorm as a single differentiable unit.

Reference analog: the fused norm kernels in paddle/phi/kernels/fusion/
(fused_rms_norm / rms_norm_kernel family) that paddle.incubate.nn.functional
exposes. On TPU the fusion itself is a routing decision: XLA already fuses
the elementwise chain, so the win is (a) one custom-vjp unit with a
hand-written backward that recomputes the cheap statistics instead of
saving them, and (b) a kernel boundary the pass framework
(paddle_tpu/passes) can target when pattern-matching user-written
compositions. A Pallas kernel can be slotted into ``_fwd_impl`` without
touching callers.

Semantics match nn.functional.rms_norm: statistics in f32, output in the
promoted dtype of (x.dtype-normalized x) * w.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["rms_norm_fused"]


def _stats(x, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    inv = lax.rsqrt(ms + eps)
    return xf, inv


def _fwd_impl(x, w, eps):
    xf, inv = _stats(x, eps)
    y = (xf * inv).astype(x.dtype)
    return y * w


@jax.custom_vjp
def rms_norm_fused(x, w, eps):
    return _fwd_impl(x, w, eps)


def _fwd(x, w, eps):
    # save primals only; the f32 statistics are recomputed in the backward
    # (cheaper than spilling an extra (rows,) f32 buffer through HBM)
    return _fwd_impl(x, w, eps), (x, w, eps)


def _bwd(res, g):
    x, w, eps = res
    xf, inv = _stats(x, eps)
    y = xf * inv  # f32 normalized
    gf = g.astype(jnp.float32)
    wf = jnp.asarray(w).astype(jnp.float32)
    dy = gf * wf
    # d/dx of y = x * rsqrt(mean(x^2)+eps):
    #   dx = inv * (dy - y * mean(dy * y, -1))
    dx = inv * (dy - y * jnp.mean(dy * y, axis=-1, keepdims=True))
    # the forward quantized the normalized activations to x.dtype before the
    # w-multiply; dw must see the same quantization
    dw = jnp.sum(gf * y.astype(x.dtype).astype(jnp.float32),
                 axis=tuple(range(g.ndim - 1)))
    return (dx.astype(x.dtype), dw.astype(jnp.asarray(w).dtype),
            jnp.zeros_like(jnp.asarray(eps, dtype=jnp.float32)))


rms_norm_fused.defvjp(_fwd, _bwd)
