"""Fused RMSNorm as a single differentiable unit.

Reference analog: the fused norm kernels in paddle/phi/kernels/fusion/
(fused_rms_norm / rms_norm_kernel family) that paddle.incubate.nn.functional
exposes. On TPU the fusion itself is a routing decision: XLA already fuses
the elementwise chain, so the win is (a) one custom-vjp unit with a
hand-written backward that recomputes the cheap statistics instead of
saving them, and (b) a kernel boundary the pass framework
(paddle_tpu/passes) can target when pattern-matching user-written
compositions. A Pallas kernel can be slotted into ``_fwd_impl`` without
touching callers.

Semantics match nn.functional.rms_norm: statistics in f32, output in the
promoted dtype of (x.dtype-normalized x) * w.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["rms_norm_fused", "rms_lax"]


def rms_lax(x, w, eps):
    """The canonical unfused composition — single source for the
    nn.functional fallback AND the pass-framework source pattern
    (passes/library._rms_pattern), so matcher and emitter stay in sync."""
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * lax.rsqrt(ms + eps)).astype(x.dtype)
    return out * w if w is not None else out


def _stats(x, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    inv = lax.rsqrt(ms + eps)
    return xf, inv


def _pallas_ok(x, w, eps) -> bool:
    from paddle_tpu.flags import flags
    if not flags.use_fused_rms_norm or not isinstance(eps, (int, float)):
        return False
    from paddle_tpu.ops.pallas import rms_norm as k
    return k.supported(jnp.shape(x), jnp.shape(w))


def _fwd_impl(x, w, eps):
    if _pallas_ok(x, w, eps):
        from paddle_tpu.ops.pallas import rms_norm as k
        return k.rms_fwd(x, w, eps)[0]
    return rms_lax(x, w, eps)


# eps is a static (nondiff) arg: as a traced operand it would be a Tracer
# inside jit, silently failing _pallas_ok's concreteness check and routing
# every compiled forward to the lax fallback
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_fused(x, w, eps):
    return _fwd_impl(x, w, eps)


def _fwd(x, w, eps):
    if _pallas_ok(x, w, eps):
        from paddle_tpu.ops.pallas import rms_norm as k
        out, inv = k.rms_fwd(x, w, eps)
        return out, (x, w, inv)
    # lax path: save primals only; the f32 statistics are recomputed in the
    # backward (cheaper than spilling an extra (rows,) f32 buffer via HBM)
    return _fwd_impl(x, w, eps), (x, w, None)


def _bwd(eps, res, g):
    x, w, inv_res = res
    if inv_res is not None:
        from paddle_tpu.ops.pallas import rms_norm as k
        dx, dw = k.rms_bwd(x, w, inv_res, g)
        return dx, dw
    xf, inv = _stats(x, eps)
    y = xf * inv  # f32 normalized
    gf = g.astype(jnp.float32)
    wf = jnp.asarray(w).astype(jnp.float32)
    dy = gf * wf
    # d/dx of y = x * rsqrt(mean(x^2)+eps):
    #   dx = inv * (dy - y * mean(dy * y, -1))
    dx = inv * (dy - y * jnp.mean(dy * y, axis=-1, keepdims=True))
    # the forward quantized the normalized activations to x.dtype before the
    # w-multiply; dw must see the same quantization
    dw = jnp.sum(gf * y.astype(x.dtype).astype(jnp.float32),
                 axis=tuple(range(g.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(jnp.asarray(w).dtype)


rms_norm_fused.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# fused GroupNorm (+SiLU) — reference: paddle/phi/kernels/fusion/gpu
# add_group_norm_silu / group_norm kernels (the SD-UNet serving path)
# ---------------------------------------------------------------------------

def group_norm_lax(x, w, b, groups, eps, act=None):
    """Canonical unfused composition (fallback + pass-pattern source)."""
    B, C = x.shape[0], x.shape[1]
    xf = x.astype(jnp.float32).reshape((B, groups, -1))
    m = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    xhat = ((xf - m) * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, C) + (1,) * (x.ndim - 2)
    y = xhat * w.reshape(shape).astype(jnp.float32) \
        + b.reshape(shape).astype(jnp.float32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    return y.astype(x.dtype)


def _gn_pallas_ok(x, groups, eps) -> bool:
    from paddle_tpu.flags import flags
    if not flags.use_fused_group_norm or not isinstance(eps, (int, float)):
        return False
    from paddle_tpu.ops.pallas import group_norm as k
    return k.supported(jnp.shape(x), groups)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def group_norm_fused(x, w, b, groups, eps, act=None):
    if _gn_pallas_ok(x, groups, eps):
        from paddle_tpu.ops.pallas import group_norm as k
        return k.gn_fwd(x, w, b, groups, eps, act)[0]
    return group_norm_lax(x, w, b, groups, eps, act)


def _gn_fwd(x, w, b, groups, eps, act):
    if _gn_pallas_ok(x, groups, eps):
        from paddle_tpu.ops.pallas import group_norm as k
        out, mean, rstd = k.gn_fwd(x, w, b, groups, eps, act)
        return out, (x, w, b, mean, rstd)
    return group_norm_lax(x, w, b, groups, eps, act), (x, w, b, None, None)


def _gn_bwd(groups, eps, act, res, g):
    x, w, b, mean, rstd = res
    if mean is not None:
        from paddle_tpu.ops.pallas import group_norm as k
        return k.gn_bwd(x, w, b, mean, rstd, g, groups, act)
    # lax fallback: same math, batched
    B, C = x.shape[0], x.shape[1]
    cg = C // groups
    xf = x.astype(jnp.float32).reshape((B, groups, -1))
    m = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    r = lax.rsqrt(var + eps)
    xhat = ((xf - m) * r).reshape(x.shape)
    shape = (1, C) + (1,) * (x.ndim - 2)
    wf = w.reshape(shape).astype(jnp.float32)
    gf = g.astype(jnp.float32)
    if act == "silu":
        from paddle_tpu.ops.pallas.group_norm import _silu_bwd
        z = xhat * wf + b.reshape(shape).astype(jnp.float32)
        dz = _silu_bwd(z, gf)
    else:
        dz = gf
    red_axes = (0,) + tuple(range(2, x.ndim))
    dw = jnp.sum(dz * xhat, axis=red_axes).astype(w.dtype)
    db = jnp.sum(dz, axis=red_axes).astype(b.dtype)
    dxhat = (dz * wf).reshape((B, groups, -1))
    mu1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    xh = xhat.reshape((B, groups, -1))
    mu2 = jnp.mean(dxhat * xh, axis=-1, keepdims=True)
    dx = (r * (dxhat - mu1 - xh * mu2)).reshape(x.shape).astype(x.dtype)
    return dx, dw, db


group_norm_fused.defvjp(_gn_fwd, _gn_bwd)


from paddle_tpu.ops.registry import register_op


@register_op("group_norm_silu",
             ref="paddle/phi/kernels/fusion/gpu add_group_norm_silu "
                 "(capability analog)")
def group_norm_silu_op(x, weight, bias, groups, epsilon=1e-5, act="silu"):
    return group_norm_fused(x, weight, bias, groups, epsilon, act)
