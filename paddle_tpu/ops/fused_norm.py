"""Fused RMSNorm as a single differentiable unit.

Reference analog: the fused norm kernels in paddle/phi/kernels/fusion/
(fused_rms_norm / rms_norm_kernel family) that paddle.incubate.nn.functional
exposes. On TPU the fusion itself is a routing decision: XLA already fuses
the elementwise chain, so the win is (a) one custom-vjp unit with a
hand-written backward that recomputes the cheap statistics instead of
saving them, and (b) a kernel boundary the pass framework
(paddle_tpu/passes) can target when pattern-matching user-written
compositions. A Pallas kernel can be slotted into ``_fwd_impl`` without
touching callers.

Semantics match nn.functional.rms_norm: statistics in f32, output in the
promoted dtype of (x.dtype-normalized x) * w.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["rms_norm_fused", "rms_lax"]


def rms_lax(x, w, eps):
    """The canonical unfused composition — single source for the
    nn.functional fallback AND the pass-framework source pattern
    (passes/library._rms_pattern), so matcher and emitter stay in sync."""
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * lax.rsqrt(ms + eps)).astype(x.dtype)
    return out * w if w is not None else out


def _stats(x, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    inv = lax.rsqrt(ms + eps)
    return xf, inv


def _pallas_ok(x, w, eps) -> bool:
    from paddle_tpu.flags import flags
    if not flags.use_fused_rms_norm or not isinstance(eps, (int, float)):
        return False
    from paddle_tpu.ops.pallas import rms_norm as k
    return k.supported(jnp.shape(x), jnp.shape(w))


def _fwd_impl(x, w, eps):
    if _pallas_ok(x, w, eps):
        from paddle_tpu.ops.pallas import rms_norm as k
        return k.rms_fwd(x, w, eps)[0]
    return rms_lax(x, w, eps)


# eps is a static (nondiff) arg: as a traced operand it would be a Tracer
# inside jit, silently failing _pallas_ok's concreteness check and routing
# every compiled forward to the lax fallback
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_fused(x, w, eps):
    return _fwd_impl(x, w, eps)


def _fwd(x, w, eps):
    if _pallas_ok(x, w, eps):
        from paddle_tpu.ops.pallas import rms_norm as k
        out, inv = k.rms_fwd(x, w, eps)
        return out, (x, w, inv)
    # lax path: save primals only; the f32 statistics are recomputed in the
    # backward (cheaper than spilling an extra (rows,) f32 buffer via HBM)
    return _fwd_impl(x, w, eps), (x, w, None)


def _bwd(eps, res, g):
    x, w, inv_res = res
    if inv_res is not None:
        from paddle_tpu.ops.pallas import rms_norm as k
        dx, dw = k.rms_bwd(x, w, inv_res, g)
        return dx, dw
    xf, inv = _stats(x, eps)
    y = xf * inv  # f32 normalized
    gf = g.astype(jnp.float32)
    wf = jnp.asarray(w).astype(jnp.float32)
    dy = gf * wf
    # d/dx of y = x * rsqrt(mean(x^2)+eps):
    #   dx = inv * (dy - y * mean(dy * y, -1))
    dx = inv * (dy - y * jnp.mean(dy * y, axis=-1, keepdims=True))
    # the forward quantized the normalized activations to x.dtype before the
    # w-multiply; dw must see the same quantization
    dw = jnp.sum(gf * y.astype(x.dtype).astype(jnp.float32),
                 axis=tuple(range(g.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(jnp.asarray(w).dtype)


rms_norm_fused.defvjp(_fwd, _bwd)
