"""paddle_tpu.device — device management namespace (P12 analog).

paddle.device.cuda.* maps to the TPU runtime where a real equivalent
exists (memory stats via jax device memory profile, synchronize, device
properties); stream/graph APIs are no-ops with documented reasons (XLA
owns scheduling).
"""

from __future__ import annotations

from typing import Optional

import jax

from paddle_tpu.framework.device import (  # noqa: F401
    CPUPlace, Place, TPUPlace, current_place, device_count, get_device,
    is_compiled_with_tpu, set_device, synchronize,
)

from paddle_tpu.framework.monitor import (  # noqa: F401
    device_memory_stats, max_memory_allocated, memory_allocated,
    memory_reserved,
)

__all__ = ["memory_allocated", "max_memory_allocated", "memory_reserved",
           "device_memory_stats",
           "set_device", "get_device", "device_count", "synchronize",
           "get_available_device", "get_available_custom_device",
           "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_xpu", "is_compiled_with_tpu", "cuda", "tpu",
           "Stream", "Event", "current_stream", "stream_guard"]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform not in ("cpu", "gpu", "tpu")]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


class Stream:
    """XLA owns stream scheduling; kept for API parity (device/cuda/streams
    analog). Work enqueued 'on' a Stream is just async dispatch."""

    def __init__(self, device=None, priority=None):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, other):
        pass

    def record_event(self, event=None):
        ev = event or Event()
        ev.record(self)
        return ev

    def wait_event(self, event):
        pass


class Event:
    """Timing events (device/cuda Event analog). ``record`` drains the XLA
    dispatch queue and stamps HOST wall-clock time, so ``elapsed_time`` is
    a real device-inclusive measurement between two recorded points (not a
    per-stream device timestamp — XLA owns streams)."""

    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._t = None

    def record(self, stream=None):
        import time
        synchronize()
        self._t = time.perf_counter()

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end: "Event") -> float:
        if self._t is None or end._t is None:
            raise RuntimeError(
                "Event.elapsed_time: both events must be record()ed first")
        return (end._t - self._t) * 1000.0


_CURRENT_STREAM = Stream()


def current_stream(device=None) -> Stream:
    return _CURRENT_STREAM


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


class _DeviceNamespace:
    """Shared surface for paddle.device.cuda / paddle.device.tpu."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count() -> int:
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return _CURRENT_STREAM

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    @staticmethod
    def memory_stats(device: Optional[int] = None) -> dict:
        d = jax.devices()[device or 0]
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        return stats

    @classmethod
    def max_memory_allocated(cls, device=None) -> int:
        return int(cls.memory_stats(device).get("peak_bytes_in_use", 0))

    @classmethod
    def memory_allocated(cls, device=None) -> int:
        return int(cls.memory_stats(device).get("bytes_in_use", 0))

    @classmethod
    def max_memory_reserved(cls, device=None) -> int:
        return int(cls.memory_stats(device).get("bytes_limit", 0))

    @classmethod
    def memory_reserved(cls, device=None) -> int:
        return int(cls.memory_stats(device).get("bytes_reserved",
                                                cls.memory_allocated(device)))

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def get_device_properties(device=None):
        d = jax.devices()[device or 0]
        class _Props:
            name = str(d.device_kind)
            platform = d.platform
        return _Props()

    @staticmethod
    def get_device_name(device=None) -> str:
        return str(jax.devices()[device or 0].device_kind)


cuda = _DeviceNamespace()
tpu = _DeviceNamespace()
