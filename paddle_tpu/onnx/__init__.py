"""paddle_tpu.onnx (python/paddle/onnx/export.py analog).

The reference is a thin wrapper over the external paddle2onnx package; the
TPU-native serving path is paddle.static.save_inference_model (compiled
XLA executables), so ONNX export delegates to jax2onnx-style converters
when installed and raises a clear error otherwise.
"""

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export requires an external converter (the reference wraps "
        "paddle2onnx the same way); use paddle_tpu.static.save_inference_model "
        "or paddle_tpu.jit.save for the TPU-native serving path")
