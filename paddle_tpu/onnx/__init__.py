"""paddle_tpu.onnx (python/paddle/onnx/export.py analog) — in-tree.

Unlike the reference (a thin wrapper over the external paddle2onnx wheel),
export here is self-contained: jaxpr trace -> inline/decompose passes ->
ONNX node mapping -> hand-rolled protobuf serialization (onnx/proto.py).
onnx/runtime.py executes the exported bytes with numpy for verification.
Covers feed-forward/conv model families; unsupported primitives raise
with the primitive named.
"""

from paddle_tpu.onnx.export import export, to_model_bytes  # noqa: F401
from paddle_tpu.onnx.runtime import parse_model, run_model  # noqa: F401

__all__ = ["export", "to_model_bytes", "parse_model", "run_model"]
