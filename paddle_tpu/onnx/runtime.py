"""Pure-numpy evaluator for the exported ONNX subset.

No onnxruntime exists in this environment, so verification is in-tree: the
tolerant wire reader (onnx/proto.py) decodes the ModelProto and this
module executes the graph with numpy ops, covering exactly the node set
the exporter emits. Used by the export tests to prove the serialized
bytes are a faithful, runnable model — not just well-formed protobuf.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from paddle_tpu.onnx.proto import decode

__all__ = ["run_model", "parse_model"]

_NP_DTYPE = {1: np.float32, 2: np.uint8, 3: np.int8, 5: np.int16,
             6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
             11: np.float64, 16: np.float32}


def _tensor(data: bytes) -> np.ndarray:
    f = decode(data)
    dims = [int(d) for d in f.get(1, [])]
    dt = _NP_DTYPE[int(f[2][0])]
    raw = f.get(9, [b""])[0]
    return np.frombuffer(raw, dtype=dt).reshape(dims).copy()


def _attrs(node_fields) -> Dict[str, object]:
    out = {}
    for raw in node_fields.get(5, []):
        a = decode(raw)
        name = a[1][0].decode()
        atype = int(a.get(20, [0])[0])
        if atype == 1:
            out[name] = float(a[2][0])
        elif atype == 2:
            out[name] = int(_signed(a[3][0]))
        elif atype == 3:          # STRING: AttributeProto.s (field 4)
            out[name] = a[4][0]
        elif atype == 7:
            out[name] = [int(_signed(v)) for v in a.get(8, [])]
        elif atype == 4:
            out[name] = a[4][0]
    return out


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_model(data: bytes) -> dict:
    model = decode(data)
    graph = decode(model[7][0])
    nodes = []
    for raw in graph.get(1, []):
        f = decode(raw)
        nodes.append(dict(
            op=f[4][0].decode(),
            inputs=[s.decode() for s in f.get(1, [])],
            outputs=[s.decode() for s in f.get(2, [])],
            attrs=_attrs(f)))
    inits = {}
    for raw in graph.get(5, []):
        f = decode(raw)
        inits[f[8][0].decode()] = _tensor(raw)
    def _names(field):
        return [decode(raw)[1][0].decode() for raw in graph.get(field, [])]
    return dict(
        ir_version=int(model.get(1, [0])[0]),
        producer=model.get(2, [b""])[0].decode(),
        opset=int(decode(model[8][0]).get(2, [0])[0]),
        nodes=nodes, initializers=inits,
        inputs=_names(11), outputs=_names(12))


def _pool2d(x, k, s, pads, mode):
    n, c, h, w = x.shape
    ph0, pw0, ph1, pw1 = pads
    fill = -np.inf if mode == "max" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)),
                constant_values=fill)
    oh = (xp.shape[2] - k[0]) // s[0] + 1
    ow = (xp.shape[3] - k[1]) // s[1] + 1
    out = np.empty((n, c, oh, ow), dtype=x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * s[0]:i * s[0] + k[0], j * s[1]:j * s[1] + k[1]]
            out[:, :, i, j] = win.max((2, 3)) if mode == "max" \
                else win.mean((2, 3))
    return out


def _conv2d(x, w, b, strides, pads, dilations, group):
    n, cin, h, wd = x.shape
    cout, cin_g, kh, kw = w.shape
    if dilations != [1, 1] and tuple(dilations) != (1, 1):
        kh_d = kh + (kh - 1) * (dilations[0] - 1)
        kw_d = kw + (kw - 1) * (dilations[1] - 1)
        wd_dil = np.zeros((cout, cin_g, kh_d, kw_d), w.dtype)
        wd_dil[:, :, ::dilations[0], ::dilations[1]] = w
        w, kh, kw = wd_dil, kh_d, kw_d
    ph0, pw0, ph1, pw1 = pads
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    oh = (xp.shape[2] - kh) // strides[0] + 1
    ow = (xp.shape[3] - kw) // strides[1] + 1
    out = np.zeros((n, cout, oh, ow), np.float64)
    cout_g = cout // group
    for gi in range(group):
        xs = xp[:, gi * cin_g:(gi + 1) * cin_g]
        wg = w[gi * cout_g:(gi + 1) * cout_g]
        # im2col
        cols = np.empty((n, cin_g * kh * kw, oh * ow), np.float64)
        idx = 0
        for ci in range(cin_g):
            for ki in range(kh):
                for kj in range(kw):
                    patch = xs[:, ci, ki:ki + oh * strides[0]:strides[0],
                               kj:kj + ow * strides[1]:strides[1]]
                    cols[:, idx] = patch.reshape(n, -1)
                    idx += 1
        wmat = wg.reshape(cout_g, -1).astype(np.float64)
        out[:, gi * cout_g:(gi + 1) * cout_g] = (
            wmat @ cols).reshape(n, cout_g, oh, ow)
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out.astype(x.dtype)


def run_model(data: bytes, inputs: List[np.ndarray]) -> List[np.ndarray]:
    m = parse_model(data)
    env: Dict[str, np.ndarray] = dict(m["initializers"])
    for name, arr in zip(m["inputs"], inputs):
        env[name] = np.asarray(arr)

    for nd in m["nodes"]:
        op = nd["op"]
        a = nd["attrs"]
        x = [env[i] for i in nd["inputs"]]
        if op == "Add":
            r = x[0] + x[1]
        elif op == "Sub":
            r = x[0] - x[1]
        elif op == "Mul":
            r = x[0] * x[1]
        elif op == "Div":
            r = x[0] / x[1]
        elif op == "MatMul":
            r = x[0] @ x[1]
        elif op == "Max":
            r = np.maximum(x[0], x[1])
        elif op == "Min":
            r = np.minimum(x[0], x[1])
        elif op == "Neg":
            r = -x[0]
        elif op == "Exp":
            r = np.exp(x[0])
        elif op == "Log":
            r = np.log(x[0])
        elif op == "Tanh":
            r = np.tanh(x[0])
        elif op == "Sqrt":
            r = np.sqrt(x[0])
        elif op == "Abs":
            r = np.abs(x[0])
        elif op == "Pow":
            r = np.power(x[0], x[1])
        elif op == "Sigmoid":
            r = 1.0 / (1.0 + np.exp(-x[0]))
        elif op == "Erf":
            from math import erf
            r = np.vectorize(erf)(x[0]).astype(x[0].dtype)
        elif op == "Identity":
            r = x[0]
        elif op == "Reshape":
            r = x[0].reshape([int(v) for v in x[1]])
        elif op == "Transpose":
            r = np.transpose(x[0], a["perm"])
        elif op == "Expand":
            r = np.broadcast_to(x[0], [int(v) for v in x[1]]).copy()
        elif op == "Cast":
            r = x[0].astype(_NP_DTYPE[a["to"]])
        elif op == "Where":
            r = np.where(x[0], x[1], x[2])
        elif op == "ReduceSum":
            axes = tuple(int(v) for v in x[1]) if len(x) > 1 else None
            r = np.sum(x[0], axis=axes, keepdims=bool(a.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin", "ReduceProd"):
            fn = {"ReduceMax": np.max, "ReduceMin": np.min,
                  "ReduceProd": np.prod}[op]
            r = fn(x[0], axis=tuple(a["axes"]),
                   keepdims=bool(a.get("keepdims", 1)))
        elif op == "Concat":
            r = np.concatenate(x, axis=a["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (x[1], x[2], x[3], x[4])
            sl = [slice(None)] * x[0].ndim
            for st, en, ax, sp in zip(starts, ends, axes, steps):
                sl[int(ax)] = slice(int(st), int(en), int(sp))
            r = x[0][tuple(sl)]
        elif op == "Conv":
            b = x[2] if len(x) > 2 else None
            r = _conv2d(x[0], x[1], b, a["strides"], a["pads"],
                        a["dilations"], a.get("group", 1))
        elif op == "MaxPool":
            r = _pool2d(x[0], a["kernel_shape"], a["strides"], a["pads"],
                        "max")
        elif op == "AveragePool":
            r = _pool2d(x[0], a["kernel_shape"], a["strides"], a["pads"],
                        "avg")
        elif op == "ArgMax":
            r = np.argmax(x[0], axis=a["axis"])
        elif op in ("Sin", "Cos", "Floor", "Ceil", "Sign", "Not"):
            fn = {"Sin": np.sin, "Cos": np.cos, "Floor": np.floor,
                  "Ceil": np.ceil, "Sign": np.sign,
                  "Not": np.logical_not}[op]
            r = fn(x[0])
        elif op == "Einsum":
            eq = a["equation"]
            eq = eq.decode() if isinstance(eq, bytes) else eq
            r = np.einsum(eq, *x)
        elif op == "Gather":
            r = np.take(x[0], x[1].astype(np.int64),
                        axis=int(a.get("axis", 0)))
        elif op in ("Equal", "Less", "Greater", "LessOrEqual",
                    "GreaterOrEqual"):
            fn = {"Equal": np.equal, "Less": np.less,
                  "Greater": np.greater, "LessOrEqual": np.less_equal,
                  "GreaterOrEqual": np.greater_equal}[op]
            r = fn(x[0], x[1])
        else:
            raise NotImplementedError(f"runtime: op {op}")
        env[nd["outputs"][0]] = r

    return [env[n] for n in m["outputs"]]
