"""ONNX export: trace a Layer to jaxpr, lower, and serialize ModelProto.

Reference analog: python/paddle/onnx/export.py — which shells out to the
external paddle2onnx wheel. Here the full pipeline is in-tree: the model is
traced to a jaxpr (the same trace jit uses), call-like equations (pjit /
custom_vjp bodies) are inlined, composite prims are decomposed by the pass
framework (passes/library.decomposition_rules), and the remaining base
prims map 1:1 onto ONNX ops, serialized with the dependency-free wire
writer in onnx/proto.py.

Covers the feed-forward/conv families (Linear/Conv/Pool/Norm/activation/
softmax — LeNet, MLPs, VGG-style nets) AND, since round 4, the attention
families: models trace under ``passes.decompose_fused`` so flash
attention / fused norms / the chunked lm-head CE lower to base prims,
general ``dot_general`` contractions map to ONNX Einsum, and embedding
``gather`` maps to ONNX Gather — BERT-base and Llama-style decoders
export with runtime-verified parity (tests/test_onnx_export.py). Ops
outside the mapping raise with the offending primitive named.
onnx/runtime.py can execute the exported bytes with numpy for
verification.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.onnx.proto import Msg

__all__ = ["export", "to_model_bytes"]

_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5, "int32": 6,
          "int64": 7, "bool": 9, "float16": 10, "float64": 11,
          "bfloat16": 16}


def _dt(dtype) -> int:
    name = str(dtype)
    if name in _DTYPE:
        return _DTYPE[name]
    # substring fallback, longest names first so 'bfloat16' wins over
    # 'float16' (BFLOAT16=16 vs FLOAT16=10)
    for k in sorted(_DTYPE, key=len, reverse=True):
        if k in name:
            return _DTYPE[k]
    raise ValueError(f"no ONNX dtype for {dtype}")


def _tensor_proto(name: str, arr: np.ndarray) -> Msg:
    t = Msg()
    for d in arr.shape:
        t.int64(1, d)
    t.int64(2, _dt(arr.dtype))
    t.string(8, name)
    t.bytes_(9, np.ascontiguousarray(arr).tobytes())
    return t


def _value_info(name: str, shape, dtype) -> Msg:
    dim_msgs = Msg()
    tt = Msg()
    tt.int64(1, _dt(dtype))
    shp = Msg()
    for d in shape:
        shp.msg(1, Msg().int64(1, int(d)))
    tt.msg(2, shp)
    tp = Msg()
    tp.msg(1, tt)
    del dim_msgs
    return Msg().string(1, name).msg(2, tp)


def _attr_i(name: str, v: int) -> Msg:
    return Msg().string(1, name).int64(3, int(v)).int64(20, 2)


def _attr_f(name: str, v: float) -> Msg:
    return Msg().string(1, name).float32(2, float(v)).int64(20, 1)


def _attr_ints(name: str, vs) -> Msg:
    m = Msg().string(1, name)
    for v in vs:
        m.int64(8, int(v))
    m.int64(20, 7)
    return m


def _attr_s(name: str, v: str) -> Msg:
    return Msg().string(1, name).string(4, v).int64(20, 3)


def _einsum_equation(dn, lhs_ndim: int, rhs_ndim: int) -> str:
    """dot_general dimension_numbers -> einsum equation, with the jax
    output layout (batch dims, then lhs free, then rhs free)."""
    import string as _string

    ((lc, rc), (lb, rb)) = dn
    letters = iter(_string.ascii_lowercase)
    l = [None] * lhs_ndim
    r = [None] * rhs_ndim
    for i, j in zip(lb, rb):
        c = next(letters)
        l[i] = c
        r[j] = c
    for i, j in zip(lc, rc):
        c = next(letters)
        l[i] = c
        r[j] = c
    for i in range(lhs_ndim):
        if l[i] is None:
            l[i] = next(letters)
    for j in range(rhs_ndim):
        if r[j] is None:
            r[j] = next(letters)
    out = ([l[i] for i in lb]
           + [l[i] for i in range(lhs_ndim) if i not in lb and i not in lc]
           + [r[j] for j in range(rhs_ndim) if j not in rb and j not in rc])
    return f"{''.join(l)},{''.join(r)}->{''.join(out)}"


class _Graph:
    def __init__(self):
        self.nodes: List[Msg] = []
        self.initializers: List[Msg] = []
        self.names: Dict[int, str] = {}  # id(var) -> name
        self.counter = 0
        self._const_cache: Dict[bytes, str] = {}

    def name_of(self, var) -> str:
        key = id(var)
        if key not in self.names:
            self.counter += 1
            self.names[key] = f"t{self.counter}"
        return self.names[key]

    def const(self, arr: np.ndarray, hint: str = "c") -> str:
        arr = np.asarray(arr)
        cache_key = arr.tobytes() + str(arr.dtype).encode() + str(arr.shape).encode()
        if cache_key in self._const_cache:
            return self._const_cache[cache_key]
        self.counter += 1
        name = f"{hint}{self.counter}"
        self.initializers.append(_tensor_proto(name, arr))
        self._const_cache[cache_key] = name
        return name

    def node(self, op_type: str, inputs: List[str], outputs: List[str],
             attrs: List[Msg] = ()):
        n = Msg()
        for i in inputs:
            n.string(1, i)
        for o in outputs:
            n.string(2, o)
        n.string(3, f"{op_type}_{len(self.nodes)}")
        n.string(4, op_type)
        for a in attrs:
            n.msg(5, a)
        self.nodes.append(n)

    def atom(self, a) -> str:
        """Var -> assigned name; Literal -> constant initializer."""
        import jax.extend.core as jex

        if isinstance(a, jex.Literal):
            val = np.asarray(a.val)
            if val.dtype == np.dtype("bfloat16") if hasattr(val, "dtype") else False:
                val = val.astype(np.float32)
            return self.const(val)
        return self.name_of(a)


def _alias_eqn(src, dst):
    """A real identity equation src -> dst (mul by one / and with True),
    keeping the jaxpr well-formed when a call output is a passthrough."""
    import jax
    import jax.extend.core as jex
    import jax.numpy as jnp

    aval = dst.aval
    if np.dtype(aval.dtype) == np.bool_:
        fn = lambda x: jnp.logical_and(x, np.bool_(True))  # noqa: E731
    else:
        one = np.ones((), dtype=aval.dtype)
        fn = lambda x: x * one  # noqa: E731
    traced = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct(aval.shape, aval.dtype))
    ae = traced.jaxpr.eqns[0]
    new_in = [src if isinstance(v, jex.Var) else v for v in ae.invars]
    return ae.replace(invars=new_in, outvars=[dst])


def _inline_calls(closed):
    """Splice pjit / custom_vjp/jvp / closed_call bodies into the top-level
    equation list so the mapper only sees base primitives."""
    import jax.extend.core as jex

    jaxpr = closed.jaxpr
    consts = list(closed.consts)
    constvars = list(jaxpr.constvars)
    changed = True
    eqns = list(jaxpr.eqns)
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        out = []
        for eqn in eqns:
            sub = None
            n_skip = 0
            p = eqn.primitive.name
            if p in ("jit", "pjit", "closed_call", "core_call", "remat",
                     "checkpoint"):
                sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            elif p in ("custom_vjp_call", "custom_jvp_call",
                       "custom_vjp_call_jaxpr"):
                sub = eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
                n_skip = int(eqn.params.get("num_consts", 0))
            if sub is None:
                out.append(eqn)
                continue
            changed = True
            if isinstance(sub, jex.ClosedJaxpr):
                sub_jaxpr, sub_consts = sub.jaxpr, list(sub.consts)
            else:
                sub_jaxpr, sub_consts = sub, []
            sub_map = {}
            for v, c in zip(sub_jaxpr.constvars, sub_consts):
                constvars.append(v)
                consts.append(c)
            for v, a in zip(sub_jaxpr.invars, eqn.invars[n_skip:]):
                sub_map[v] = a
            produced = set()
            for se in sub_jaxpr.eqns:
                produced.update(v for v in se.outvars
                                if isinstance(v, jex.Var))
            # map body-produced outvars to the call's outvars; outputs that
            # pass an input (or literal) through need an explicit alias eqn
            # AFTER the body — mapping them would clobber the invar binding
            # and make body eqns read the not-yet-defined output var
            alias_pairs = []
            for v, a in zip(sub_jaxpr.outvars, eqn.outvars):
                if isinstance(v, jex.Var) and v in produced \
                        and v not in sub_map:
                    sub_map[v] = a
                else:
                    src = sub_map.get(v, v) if isinstance(v, jex.Var) else v
                    alias_pairs.append((src, a))

            def s(x):
                return sub_map.get(x, x) if isinstance(x, jex.Var) else x

            for se in sub_jaxpr.eqns:
                out.append(se.replace(invars=[s(v) for v in se.invars],
                                      outvars=[s(v) for v in se.outvars]))
            for src, a in alias_pairs:
                out.append(_alias_eqn(src, a))
        eqns = out
    new = jex.Jaxpr(constvars, jaxpr.invars, jaxpr.outvars, eqns,
                    debug_info=jaxpr.debug_info)
    return jex.ClosedJaxpr(new, consts)


# --------------------------------------------------------------------------
# primitive -> ONNX node mapping
# --------------------------------------------------------------------------

_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div", "max": "Max",
    "min": "Min", "neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
    "sqrt": "Sqrt", "abs": "Abs", "erf": "Erf", "pow": "Pow",
    "floor": "Floor", "ceil": "Ceil", "sign": "Sign", "sin": "Sin",
    "cos": "Cos", "stop_gradient": "Identity", "copy": "Identity",
    "squeeze": None, "not": "Not", "and": "And", "or": "Or",
    "eq": "Equal", "lt": "Less", "gt": "Greater",
    "le": "LessOrEqual", "ge": "GreaterOrEqual",
}


def _map_eqn(g: _Graph, eqn) -> None:
    p = eqn.primitive.name
    ins = [g.atom(a) for a in eqn.invars]
    outs = [g.name_of(o) for o in eqn.outvars]
    params = eqn.params

    if p in _SIMPLE and _SIMPLE[p]:
        g.node(_SIMPLE[p], ins, outs)
    elif p in ("reshape", "squeeze", "expand_dims"):
        shape = [int(d) for d in eqn.outvars[0].aval.shape]
        g.node("Reshape", [ins[0], g.const(np.asarray(shape, np.int64),
                                           "shape")], outs)
    elif p == "transpose":
        g.node("Transpose", ins, outs,
               [_attr_ints("perm", params["permutation"])])
    elif p == "broadcast_in_dim":
        in_shape = eqn.invars[0].aval.shape
        out_shape = [int(d) for d in eqn.outvars[0].aval.shape]
        bdims = params["broadcast_dimensions"]
        mid = [1] * len(out_shape)
        for src_dim, dst_dim in enumerate(bdims):
            mid[dst_dim] = int(in_shape[src_dim])
        cur = ins[0]
        if list(mid) != list(in_shape):
            r = f"{outs[0]}_rs"
            g.node("Reshape", [cur, g.const(np.asarray(mid, np.int64),
                                            "shape")], [r])
            cur = r
        g.node("Expand", [cur, g.const(np.asarray(out_shape, np.int64),
                                       "shape")], outs)
    elif p in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod"):
        op = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
              "reduce_min": "ReduceMin", "reduce_prod": "ReduceProd"}[p]
        axes = list(params["axes"])
        # opset 13: ReduceSum takes axes as input; Max/Min still attribute
        if op == "ReduceSum":
            g.node(op, [ins[0], g.const(np.asarray(axes, np.int64), "axes")],
                   outs, [_attr_i("keepdims", 0)])
        else:
            g.node(op, ins, outs,
                   [_attr_ints("axes", axes), _attr_i("keepdims", 0)])
    elif p == "convert_element_type":
        g.node("Cast", ins, outs,
               [_attr_i("to", _dt(params["new_dtype"]))])
    elif p == "select_n":
        if len(ins) != 3:
            raise NotImplementedError("select_n with >2 cases")
        # jax: cases[which]; which==True -> cases[1]. ONNX Where(c, X, Y)=X@true
        g.node("Where", [ins[0], ins[2], ins[1]], outs)
    elif p == "integer_pow":
        y = int(params["y"])
        g.node("Pow", [ins[0], g.const(np.asarray(
            y, _np_dtype(eqn.invars[0].aval.dtype)))], outs)
    elif p == "dot_general":
        ((lc, rc), (lb, rb)) = params["dimension_numbers"]
        lhs_ndim = len(eqn.invars[0].aval.shape)
        rhs_ndim = len(eqn.invars[1].aval.shape)
        if (not lb and not rb and tuple(lc) == (lhs_ndim - 1,)
                and tuple(rc) == (0,)):
            g.node("MatMul", ins, outs)
        else:
            # general contraction (attention q·kᵀ, batched matmuls, ...)
            g.node("Einsum", ins, outs, [_attr_s(
                "equation", _einsum_equation(params["dimension_numbers"],
                                             lhs_ndim, rhs_ndim))])
    elif p == "gather":
        dn = params["dimension_numbers"]
        op_shape = eqn.invars[0].aval.shape
        idx_shape = eqn.invars[1].aval.shape
        ss = tuple(params["slice_sizes"])
        # the embedding-lookup pattern (jnp.take along axis 0): indices
        # (..., 1) pick whole rows of a (V, ...) table
        n_batch = len(idx_shape) - 1
        if (tuple(dn.start_index_map) == (0,)
                and tuple(dn.collapsed_slice_dims) == (0,)
                and idx_shape[-1] == 1
                and ss == (1,) + tuple(op_shape[1:])
                and tuple(dn.offset_dims) == tuple(
                    range(n_batch, n_batch + len(op_shape) - 1))):
            flat = f"{outs[0]}_idx"
            g.node("Reshape", [ins[1], g.const(np.asarray(
                [int(d) for d in idx_shape[:-1]], np.int64), "ishape")],
                [flat])
            g.node("Gather", [ins[0], flat], outs, [_attr_i("axis", 0)])
        else:
            raise NotImplementedError(
                f"gather pattern {dn} slice_sizes={ss}")
    elif p == "erfc":
        tmp = f"{outs[0]}_erf"
        g.node("Erf", ins, [tmp])
        g.node("Sub", [g.const(np.asarray(
            1, _np_dtype(eqn.invars[0].aval.dtype))), tmp], outs)
    elif p == "conv_general_dilated":
        dn = params["dimension_numbers"]
        if dn.lhs_spec != (0, 1, 2, 3) or dn.rhs_spec != (0, 1, 2, 3) or \
                dn.out_spec != (0, 1, 2, 3):
            raise NotImplementedError(f"conv layout {dn}")
        pads = params["padding"]
        g.node("Conv", ins, outs, [
            _attr_ints("strides", params["window_strides"]),
            _attr_ints("dilations", params["rhs_dilation"]),
            _attr_ints("pads", [pads[0][0], pads[1][0],
                                pads[0][1], pads[1][1]]),
            _attr_i("group", params["feature_group_count"]),
        ])
    elif p in ("reduce_window_max", "reduce_window_sum"):
        wd = params["window_dimensions"]
        ws = params["window_strides"]
        pads = params["padding"]
        if len(wd) != 4 or wd[0] != 1 or wd[1] != 1:
            raise NotImplementedError(f"pool window {wd}")
        attrs = [_attr_ints("kernel_shape", wd[2:]),
                 _attr_ints("strides", ws[2:]),
                 _attr_ints("pads", [pads[2][0], pads[3][0],
                                     pads[2][1], pads[3][1]])]
        if p == "reduce_window_max":
            g.node("MaxPool", ins, outs, attrs)
        else:
            tmp = f"{outs[0]}_avg"
            g.node("AveragePool", ins, [tmp],
                   attrs + [_attr_i("count_include_pad", 1)])
            k = float(wd[2] * wd[3])
            g.node("Mul", [tmp, g.const(np.asarray(
                k, _np_dtype(eqn.invars[0].aval.dtype)))], outs)
    elif p == "concatenate":
        g.node("Concat", ins, outs, [_attr_i("axis", params["dimension"])])
    elif p == "slice":
        starts = list(params["start_indices"])
        ends = list(params["limit_indices"])
        steps = list(params["strides"] or [1] * len(starts))
        axes = list(range(len(starts)))
        g.node("Slice", [ins[0],
                         g.const(np.asarray(starts, np.int64), "st"),
                         g.const(np.asarray(ends, np.int64), "en"),
                         g.const(np.asarray(axes, np.int64), "ax"),
                         g.const(np.asarray(steps, np.int64), "sp")], outs)
    elif p == "rsqrt":
        tmp = f"{outs[0]}_sq"
        g.node("Sqrt", ins, [tmp])
        g.node("Div", [g.const(np.asarray(
            1, _np_dtype(eqn.invars[0].aval.dtype))), tmp], outs)
    elif p == "logistic":
        g.node("Sigmoid", ins, outs)
    elif p == "square":
        g.node("Mul", [ins[0], ins[0]], outs)
    elif p == "argmax":
        g.node("ArgMax", ins, outs, [
            _attr_i("axis", params["axes"][0]), _attr_i("keepdims", 0)])
    elif p == "iota":
        aval = eqn.outvars[0].aval
        rng = np.arange(aval.shape[params["dimension"]],
                        dtype=_np_dtype(aval.dtype))
        shape = [1] * len(aval.shape)
        shape[params["dimension"]] = -1
        arr = np.broadcast_to(rng.reshape(shape), aval.shape)
        g.node("Identity", [g.const(np.ascontiguousarray(arr), "iota")], outs)
    else:
        raise NotImplementedError(
            f"ONNX export: no mapping for primitive {p!r} "
            f"(params={list(params)})")


def _np_dtype(dt):
    name = str(dt)
    if name == "bfloat16":
        return np.float32
    return np.dtype(name)


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

def to_model_bytes(layer, example_inputs, opset_version: int = 13) -> bytes:
    """Trace `layer` on example inputs and serialize an ONNX ModelProto."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.autograd import tape
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.nn.utils import functional_call
    from paddle_tpu.passes import decomposition_rules, rewrite_jaxpr

    from paddle_tpu.nn.generation import mode_restore, mode_snapshot
    snap = mode_snapshot(layer)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        state = dict(layer.state_dict())
        for name, b in layer.named_buffers():
            state.setdefault(name, b)
        names = list(state.keys())
        vals = [state[n]._value for n in names]
        xs = [np.asarray(x.numpy() if isinstance(x, Tensor) else x)
              for x in example_inputs]

        def fn(param_vals, *inputs):
            with tape.no_grad():
                out, _ = functional_call(
                    layer, dict(zip(names, param_vals)),
                    tuple(Tensor(i) for i in inputs))
            outs = out if isinstance(out, (tuple, list)) else (out,)
            return [o._value for o in outs]

        # fused/Pallas-routed ops trace as their canonical lax
        # compositions (passes.decompose_fused) — flash attention,
        # fused norms, and the chunked lm-head CE would otherwise emit
        # opaque pallas_call / scan equations no ONNX op maps to
        from paddle_tpu.passes import decompose_fused
        with decompose_fused():
            closed = jax.make_jaxpr(fn)(vals, *[jnp.asarray(x) for x in xs])
        closed = _inline_calls(closed)
        closed = rewrite_jaxpr(closed, decomposition_rules(), recurse=False)
        closed = _inline_calls(closed)
    finally:
        # per-sublayer restore (no blanket .train(): it would clobber
        # submodules the user froze with sub.eval())
        mode_restore(snap)

    g = _Graph()
    jaxpr = closed.jaxpr
    n_params = len(vals)
    # params + consts -> initializers; remaining invars -> graph inputs
    for var, val, pname in zip(jaxpr.invars[:n_params], vals, names):
        arr = np.asarray(val)
        if arr.dtype == jnp.bfloat16:
            arr = arr.astype(np.float32)
        g.names[id(var)] = pname
        g.initializers.append(_tensor_proto(pname, arr))
    for var, c in zip(jaxpr.constvars, closed.consts):
        arr = np.asarray(c)
        g.initializers.append(_tensor_proto(g.name_of(var), arr))
    graph_inputs = []
    for i, var in enumerate(jaxpr.invars[n_params:]):
        g.names[id(var)] = f"input_{i}"
        graph_inputs.append(_value_info(f"input_{i}", var.aval.shape,
                                        var.aval.dtype))
    for eqn in jaxpr.eqns:
        _map_eqn(g, eqn)
    graph_outputs = []
    import jax.extend.core as jex
    for i, var in enumerate(jaxpr.outvars):
        if isinstance(var, jex.Literal):
            nm = g.const(np.asarray(var.val), "out")
        else:
            nm = g.name_of(var)
        out_name = f"output_{i}"
        g.node("Identity", [nm], [out_name])
        graph_outputs.append(_value_info(out_name, var.aval.shape,
                                         var.aval.dtype))

    graph = Msg()
    for n in g.nodes:
        graph.msg(1, n)
    graph.string(2, type(layer).__name__)
    for init in g.initializers:
        graph.msg(5, init)
    for vi in graph_inputs:
        graph.msg(11, vi)
    for vo in graph_outputs:
        graph.msg(12, vo)

    model = Msg()
    model.int64(1, 8)  # ir_version
    model.string(2, "paddle_tpu")
    model.string(3, "0.2")
    model.msg(7, graph)
    model.msg(8, Msg().string(1, "").int64(2, opset_version))
    return bytes(model)


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs) -> str:
    """paddle.onnx.export analog: writes ``{path}.onnx`` and returns the
    file path. ``input_spec``: InputSpec list or example Tensors/arrays."""
    from paddle_tpu.static import InputSpec

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec (InputSpec list "
                         "or example tensors)")
    examples = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            examples.append(np.asarray(spec.example().numpy()))
        else:
            examples.append(spec)
    data = to_model_bytes(layer, examples, opset_version=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(data)
    return out_path
