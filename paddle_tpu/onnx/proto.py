"""Minimal protobuf wire-format writer/reader for the ONNX subset.

The environment has no ``onnx`` package, and depending on one would be the
reference's approach (paddle2onnx is an external wheel). Protobuf's wire
format is simple — varint keys, length-delimited submessages — so the
exporter writes ModelProto bytes directly. Field numbers follow the public
onnx.proto schema (onnx/onnx.proto in the ONNX repo):

  ModelProto:   ir_version=1 producer_name=2 producer_version=3 graph=7
                opset_import=8
  OperatorSetId: domain=1 version=2
  GraphProto:   node=1 name=2 initializer=5 doc_string=10 input=11
                output=12 value_info=13
  NodeProto:    input=1 output=2 name=3 op_type=4 attribute=5
  AttributeProto: name=1 f=2 i=3 s=4 t=5 floats=7 ints=8 type=20
  TensorProto:  dims=1 data_type=2 name=8 raw_data=9
  ValueInfoProto: name=1 type=2 ; TypeProto.tensor_type=1
  TypeProto.Tensor: elem_type=1 shape=2
  TensorShapeProto: dim=1 ; Dimension: dim_value=1 dim_param=2

A matching tolerant reader (field tree) supports the round-trip tests and
the numpy mini-runtime without any external dependency.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

__all__ = ["Msg", "varint", "encode", "decode"]


def varint(value: int) -> bytes:
    if value < 0:
        value += 1 << 64  # two's complement, protobuf int64 convention
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Msg:
    """Append-only protobuf message builder."""

    def __init__(self):
        self._buf = bytearray()

    def _key(self, field: int, wire: int):
        self._buf += varint((field << 3) | wire)

    def int64(self, field: int, value: int) -> "Msg":
        self._key(field, 0)
        self._buf += varint(int(value))
        return self

    def float32(self, field: int, value: float) -> "Msg":
        self._key(field, 5)
        self._buf += struct.pack("<f", float(value))
        return self

    def bytes_(self, field: int, value: bytes) -> "Msg":
        self._key(field, 2)
        self._buf += varint(len(value))
        self._buf += value
        return self

    def string(self, field: int, value: str) -> "Msg":
        return self.bytes_(field, value.encode("utf-8"))

    def msg(self, field: int, sub: "Msg") -> "Msg":
        return self.bytes_(field, bytes(sub._buf))

    def packed_int64(self, field: int, values) -> "Msg":
        payload = b"".join(varint(int(v)) for v in values)
        return self.bytes_(field, payload)

    def __bytes__(self):
        return bytes(self._buf)


def encode(m: Msg) -> bytes:
    return bytes(m)


FieldTree = Dict[int, List[Union[int, float, bytes]]]


def decode(data: bytes) -> FieldTree:
    """Parse one message level into {field: [raw values]}; submessages stay
    bytes (decode them recursively as needed)."""
    out: FieldTree = {}
    i = 0
    n = len(data)
    while i < n:
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(data, i)
        elif wire == 5:
            (v,) = struct.unpack_from("<f", data, i)
            i += 4
        elif wire == 1:
            (v,) = struct.unpack_from("<d", data, i)
            i += 8
        elif wire == 2:
            ln, i = _read_varint(data, i)
            v = bytes(data[i:i + ln])
            i += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def _read_varint(data: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    value = 0
    while True:
        b = data[i]
        i += 1
        value |= (b & 0x7F) << shift
        if not (b & 0x80):
            return value, i
        shift += 7
