"""paddle_tpu.linalg namespace (python/paddle/linalg.py analog) —
re-exports the linalg op surface registered in ops/linalg.py."""

from paddle_tpu.ops import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, inv, lstsq, lu, matmul, matrix_power, matrix_rank, multi_dot,
    norm, pinv, qr, slogdet, solve, svd, triangular_solve,
)

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "inv", "lstsq", "lu", "matmul",
    "matrix_power", "matrix_rank", "multi_dot", "norm", "pinv", "qr",
    "slogdet", "solve", "svd", "triangular_solve",
]
