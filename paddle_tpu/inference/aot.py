"""AOT export/load of compiled executables (StableHLO bytes).

Analog of the reference's save_inference_model → AnalysisPredictor flow
(paddle/fluid/inference/api/analysis_predictor.h): the "IR program" here is
jax.export's serialized StableHLO module. ``load_compiled`` rebuilds a
callable WITHOUT re-tracing any Python — a fresh process never imports the
model code, it just feeds the deserialized executable.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional, Sequence

import jax
from jax import export as _jexport

__all__ = ["save_compiled", "load_compiled"]

_MAGIC = b"PTPU-AOT1\n"


def save_compiled(fn: Callable, example_args: Sequence, path: str,
                  donate_argnums=()) -> str:
    """Trace+lower ``fn`` at the example args' shapes/dtypes and write the
    serialized StableHLO executable to ``path`` (save_inference_model
    analog). The export is shape-polymorphism-free: static shapes are the
    TPU deployment contract. The write is crash-safe (temp + atomic
    rename — a killed exporter never leaves a half-written module under
    the final name). Returns the sha256 hexdigest of the INTENDED file
    bytes, computed before the write hits disk, so bundle manifests can
    refuse any later on-disk corruption (inference/bundle.py)."""
    exp = _jexport.export(jax.jit(fn, donate_argnums=donate_argnums))(
        *example_args)
    blob = exp.serialize()
    # raw StableHLO bytes after the magic — NOT pickle: loading a model
    # artifact must never execute arbitrary code from the file
    from paddle_tpu.runtime.resilience import atomic_write_bytes
    payload = _MAGIC + bytes(blob)
    digest = hashlib.sha256(payload).hexdigest()
    atomic_write_bytes(path, payload)
    return digest


def load_compiled(path: str, expected_sha256: Optional[str] = None
                  ) -> Callable:
    """Load an AOT-exported executable; returns a callable. No Python model
    code runs — the deserialized module is invoked directly. With
    ``expected_sha256`` (a bundle-manifest digest) the file bytes are
    verified first and a mismatch — a flipped bit in the baked weight
    constants, a truncated module — raises a typed
    ``CorruptBundleError`` instead of serving wrong numerics."""
    with open(path, "rb") as f:
        raw = f.read()
    if expected_sha256 is not None:
        got = hashlib.sha256(raw).hexdigest()
        if got != expected_sha256:
            from paddle_tpu.runtime.resilience import CorruptBundleError
            raise CorruptBundleError(
                f"{path}: sha256 {got[:16]}… does not match the bundle "
                f"manifest's {expected_sha256[:16]}… — refusing to serve "
                f"a corrupt module ({len(raw)} bytes on disk)")
    magic, blob = raw[:len(_MAGIC)], raw[len(_MAGIC):]
    if magic != _MAGIC:
        raise ValueError(f"{path}: not a paddle_tpu AOT export")
    exp = _jexport.deserialize(bytearray(blob))
    return lambda *args: exp.call(*args)
