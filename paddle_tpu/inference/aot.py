"""AOT export/load of compiled executables (StableHLO bytes).

Analog of the reference's save_inference_model → AnalysisPredictor flow
(paddle/fluid/inference/api/analysis_predictor.h): the "IR program" here is
jax.export's serialized StableHLO module. ``load_compiled`` rebuilds a
callable WITHOUT re-tracing any Python — a fresh process never imports the
model code, it just feeds the deserialized executable.

Two on-disk formats, distinguished by magic:

- ``PTPU-AOT1``: magic + raw StableHLO bytes (the original format);
- ``PTPU-AOT2``: magic + 4-byte big-endian length + that many bytes of
  JSON entry metadata + raw StableHLO bytes. The embedded dict is the
  entry's SELF-DESCRIPTION (what program this is, its statics — e.g. a
  chunk entry's ``chunk``/``admit_ring``/``spec_chunk``), readable via
  :func:`read_meta` without touching bundle.json and without
  deserializing the module — a stray ``.aot`` file stays identifiable
  even separated from its bundle.

Both loaders accept both formats; format 1 simply has no metadata.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
from jax import export as _jexport

__all__ = ["save_compiled", "load_compiled", "read_meta"]

_MAGIC = b"PTPU-AOT1\n"
_MAGIC2 = b"PTPU-AOT2\n"


def save_compiled(fn: Callable, example_args: Sequence, path: str,
                  donate_argnums=(),
                  meta: Optional[Dict[str, Any]] = None) -> str:
    """Trace+lower ``fn`` at the example args' shapes/dtypes and write the
    serialized StableHLO executable to ``path`` (save_inference_model
    analog). The export is shape-polymorphism-free: static shapes are the
    TPU deployment contract. ``meta`` (JSON-serializable dict) embeds an
    entry self-description readable back via :func:`read_meta`. The write
    is crash-safe (temp + atomic rename — a killed exporter never leaves
    a half-written module under the final name). Returns the sha256
    hexdigest of the INTENDED file bytes, computed before the write hits
    disk, so bundle manifests can refuse any later on-disk corruption
    (inference/bundle.py)."""
    exp = _jexport.export(jax.jit(fn, donate_argnums=donate_argnums))(
        *example_args)
    blob = exp.serialize()
    # raw StableHLO bytes after the magic (+ length-prefixed JSON meta in
    # format 2) — NOT pickle: loading a model artifact must never execute
    # arbitrary code from the file
    from paddle_tpu.runtime.resilience import atomic_write_bytes
    if meta is None:
        payload = _MAGIC + bytes(blob)
    else:
        mj = json.dumps(meta, sort_keys=True).encode()
        payload = _MAGIC2 + len(mj).to_bytes(4, "big") + mj + bytes(blob)
    digest = hashlib.sha256(payload).hexdigest()
    atomic_write_bytes(path, payload)
    return digest


def _split(raw: bytes, path: str
           ) -> Tuple[Optional[Dict[str, Any]], bytes]:
    """(embedded meta or None, StableHLO bytes) for either format."""
    if raw[:len(_MAGIC2)] == _MAGIC2:
        off = len(_MAGIC2)
        n = int.from_bytes(raw[off:off + 4], "big")
        head, blob = raw[off + 4:off + 4 + n], raw[off + 4 + n:]
        if len(head) != n:
            raise ValueError(
                f"{path}: truncated AOT entry metadata ({len(head)} of "
                f"{n} declared bytes)")
        return json.loads(head.decode()), blob
    if raw[:len(_MAGIC)] == _MAGIC:
        return None, raw[len(_MAGIC):]
    raise ValueError(f"{path}: not a paddle_tpu AOT export")


def read_meta(path: str) -> Optional[Dict[str, Any]]:
    """The embedded entry metadata of an AOT export, WITHOUT reading or
    deserializing the module bytes (the metadata block leads the file).
    ``None`` for a format-1 file (no embedded meta); ``ValueError`` for a
    file that is not an AOT export at all."""
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC2) + 4)
        if head[:len(_MAGIC2)] == _MAGIC2:
            n = int.from_bytes(head[len(_MAGIC2):], "big")
            raw = f.read(n)
            if len(raw) != n:
                raise ValueError(
                    f"{path}: truncated AOT entry metadata ({len(raw)} "
                    f"of {n} declared bytes)")
            return json.loads(raw.decode())
    if head[:len(_MAGIC)] == _MAGIC:
        return None
    raise ValueError(f"{path}: not a paddle_tpu AOT export")


def load_compiled(path: str, expected_sha256: Optional[str] = None
                  ) -> Callable:
    """Load an AOT-exported executable; returns a callable. No Python model
    code runs — the deserialized module is invoked directly. With
    ``expected_sha256`` (a bundle-manifest digest) the file bytes are
    verified first and a mismatch — a flipped bit in the baked weight
    constants, a truncated module — raises a typed
    ``CorruptBundleError`` instead of serving wrong numerics."""
    with open(path, "rb") as f:
        raw = f.read()
    if expected_sha256 is not None:
        got = hashlib.sha256(raw).hexdigest()
        if got != expected_sha256:
            from paddle_tpu.runtime.resilience import CorruptBundleError
            raise CorruptBundleError(
                f"{path}: sha256 {got[:16]}… does not match the bundle "
                f"manifest's {expected_sha256[:16]}… — refusing to serve "
                f"a corrupt module ({len(raw)} bytes on disk)")
    _, blob = _split(raw, path)
    exp = _jexport.deserialize(bytearray(blob))
    return lambda *args: exp.call(*args)
