"""AOT export/load of compiled executables (StableHLO bytes).

Analog of the reference's save_inference_model → AnalysisPredictor flow
(paddle/fluid/inference/api/analysis_predictor.h): the "IR program" here is
jax.export's serialized StableHLO module. ``load_compiled`` rebuilds a
callable WITHOUT re-tracing any Python — a fresh process never imports the
model code, it just feeds the deserialized executable.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
from jax import export as _jexport

__all__ = ["save_compiled", "load_compiled"]

_MAGIC = b"PTPU-AOT1\n"


def save_compiled(fn: Callable, example_args: Sequence, path: str,
                  donate_argnums=()) -> None:
    """Trace+lower ``fn`` at the example args' shapes/dtypes and write the
    serialized StableHLO executable to ``path`` (save_inference_model
    analog). The export is shape-polymorphism-free: static shapes are the
    TPU deployment contract."""
    exp = _jexport.export(jax.jit(fn, donate_argnums=donate_argnums))(
        *example_args)
    blob = exp.serialize()
    # raw StableHLO bytes after the magic — NOT pickle: loading a model
    # artifact must never execute arbitrary code from the file
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(bytes(blob))


def load_compiled(path: str) -> Callable:
    """Load an AOT-exported executable; returns a callable. No Python model
    code runs — the deserialized module is invoked directly."""
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a paddle_tpu AOT export")
        blob = f.read()
    exp = _jexport.deserialize(bytearray(blob))
    return lambda *args: exp.call(*args)
