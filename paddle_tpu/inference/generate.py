"""KV-cache autoregressive decoding for LlamaForCausalLM.

Capability analog of the reference's decode stack —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
(block-table KV cache attention) and the fused generation ops — in the
TPU-native form: a PURE functional forward with a statically-shaped KV
cache — token-major ``(B, max_len, KV, D)`` for MHA, head-major
``(B, KV, max_len, D)`` for GQA (the decode-kernel layout); stacked over
layers by default, or one buffer per layer via
``flags.decode_cache_layout='per_layer'`` (measured equal-or-slower on
v5e; kept as a tuning knob) — so prefill and every decode step are each
ONE cached-compile XLA program (no recompiles across steps; static shapes
are what the MXU wants). Block tables are unnecessary: XLA owns memory, and
a padded dense cache + position mask is the layout it tiles best.

Decode attention: MHA runs XLA's masked dense read (a bandwidth-bound
matvec it fuses well); GQA routes through the Pallas decode-attention
kernel (ops/pallas/decode_attention.py — no repeated-KV
materialization). The Pallas flash kernel covers chunked prefill
(bottom-right-aligned causal, sq != sk).

Positions may be a traced scalar (the classic lockstep decode) OR a
per-row ``(B,)`` vector: speculative decoding accepts a variable number
of draft tokens per row per round, so each row owns its cache write
offset, causal mask bound, and rope phase (``_cache_update`` vmaps the
dynamic-update-slice over the batch in that case).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, _rope_tables

__all__ = ["LlamaDecoder", "DecodeState"]


@dataclasses.dataclass
class DecodeState:
    """The exported/re-enterable carry of the fused decode loop.

    Everything the loop needs to resume is a plain array (exportable as
    AOT entry inputs, scatter-updatable row by row by the serving
    engine's admission path): next-token ``logits``, both KV-cache
    buffers, PER-ROW cache positions, PER-ROW raw uint32 RNG keys (each
    row's sample stream depends only on its own key — admitting a new
    request into a neighbouring row can't shift it), the done mask and
    per-row eos ids (``-1`` = no eos for that row) and temperatures.
    ``decode_chunk`` advances the state by T tokens in ONE dispatch;
    chaining chunks is bit-exact with run-to-completion for greedy.

    A SPECULATIVE carry (``init_decode_state(draft_model=...)``)
    additionally holds the draft model's KV caches (``dkc``/``dvc``), a
    per-row pending token ``tok`` (the last emitted-but-not-yet-cached
    token; ``-1`` = "no pending token, pick from ``logits``" — the state
    of a freshly admitted row) and per-row CUMULATIVE acceptance stats
    (``spec_rounds``/``spec_accepted``, reset at admission). In that mode
    ``logits`` are the verify logits of the pending token's position —
    finite (the serving engine's corruption guard still works) but NOT
    pick-ready; the ``tok`` sentinel governs the next pick. ``nv`` is an
    OUTPUT of a speculative chunk: the per-row count of valid tokens in
    the returned ``(B, T+K)`` buffer — ``T..T+K`` of them, the per-row
    overflow being the accepted draft tail of the chunk's last round.
    """

    logits: Any           # (B, V) f32 — logits the next pick samples from
    kc: Any               # target KV caches (stacked array or per-layer
    vc: Any               #   tuple; see _empty_cache)
    pos: Any              # (B,) i32 — per-row next cache write position
    keys: Any             # (B, 2) u32 — per-row RNG keys
    done: Any             # (B,) bool — frozen rows (eos hit / slot free)
    eos: Any              # (B,) i32 — per-row eos id, -1 = none
    temp: Any             # (B,) f32 — per-row sampling temperature
    dkc: Any = None       # draft caches (speculative chunks)
    dvc: Any = None
    tok: Any = None       # (B,) i32 — pending token, -1 = pick from logits
    spec_rounds: Any = None    # (B,) i32 — cumulative verify rounds
    spec_accepted: Any = None  # (B,) i32 — cumulative accepted drafts
    nv: Any = None        # (B,) i32 — valid tokens in the last chunk's buf
    adapter_idx: Any = None    # (B,) i32 — per-row LoRA adapter index into
    #                            the stacked (N+1, ...) delta arrays;
    #                            0 = base-only (None = no adapters at all,
    #                            keeping non-LoRA traces identical)
    spec_on: Any = None   # (B,) bool — per-row speculative enable: False
    #                       rows decode verify-free (a=0, target pick) in
    #                       the SAME speculative chunk program (None = all
    #                       rows speculate, the pre-multiplex behaviour)
    spec: Any = None      # host-side: {"ekey", "K"} engine routing meta
    steps_done: int = 0   # host-side: loop steps executed so far


def _rope_at(x, pos, cfg, p):
    """Rotate (B, S, H, D) by positions ``pos + [0..S)``: a dynamic slice
    of the tables precomputed at init from the training-path frequency
    function (_rope_tables), so decode can never diverge from training if
    rope scaling changes — and no per-step exp/pow work. ``pos`` may be a
    scalar or a per-row (B,) vector (speculative rows advance unevenly)."""
    S = x.shape[1]
    d2 = cfg.head_dim // 2
    if jnp.ndim(pos) == 1:
        idx = pos[:, None] + jnp.arange(S)                  # (B, S)
        cos = jnp.take(p["rope.cos"], idx, axis=0).astype(x.dtype)
        sin = jnp.take(p["rope.sin"], idx, axis=0).astype(x.dtype)
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    else:
        cos = jax.lax.dynamic_slice(p["rope.cos"], (pos, 0),
                                    (S, d2)).astype(x.dtype)
        sin = jax.lax.dynamic_slice(p["rope.sin"], (pos, 0),
                                    (S, d2)).astype(x.dtype)
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)


def _mm(x, p, name, sharded=False, aidx=None):
    """x @ weight, transparently using the int8 weight-only path when the
    decoder quantized this matrix (weight stays int8 in HBM — half the
    weight bandwidth, which bounds small-batch decode; reference analog:
    weight_only_linear, paddle/phi/kernels/fusion/gpu/). On TPU the
    dequant happens INSIDE the Pallas matmul tile (ops/pallas/int8_matmul)
    — XLA's astype-then-dot materializes the bf16 weight and loses the
    bandwidth win (measured slower than bf16). Under a mesh (``sharded``)
    the Pallas tile is skipped: the hand-written kernel has no GSPMD
    partitioning rule, so the dequant-matmul falls back to the XLA form,
    which shards like any dot.

    ``aidx`` (B,) i32 multiplexes per-row LoRA deltas when the params
    carry stacked ``lora.{name}.A`` (N+1, d_in, r) / ``.B`` (N+1, r,
    d_out) arrays: each row gathers ITS adapter's pair and adds
    ``(x @ A[idx]) @ B[idx]`` to the base product — row 0 is all-zero, so
    base rows pay only the rank-r epsilon and every tenant mix stays one
    dispatch. The delta applies identically over the int8 base (fp16/fp32
    adapters over a quantized trunk: the AWQ observation that the weight
    STREAM is the decode cost — rank-r stacks barely add to it)."""
    q = p.get(name + ":int8")
    if q is not None:
        scale = p[name + ":scale"]
        lead = x.shape[:-1]
        x2 = x.reshape((-1, x.shape[-1]))
        from paddle_tpu.ops.pallas import int8_matmul as i8
        if (not sharded and jax.default_backend() == "tpu"
                and i8.supported(x2, q)):
            out = i8.int8_matmul(x2, q, scale)
        else:
            out = (x2 @ q.astype(x.dtype)) * scale.astype(x.dtype)
        out = out.reshape(lead + (q.shape[1],))
    else:
        out = x @ p[name]
    if aidx is not None:
        A = p.get("lora." + name + ".A")
        if A is not None:
            Bm = p["lora." + name + ".B"]
            Ai = jnp.take(A, aidx, axis=0)          # (B, d_in, r)
            Bi = jnp.take(Bm, aidx, axis=0)         # (B, r, d_out)
            xa = x.astype(Ai.dtype)
            if x.ndim == 3:                         # (B, S, d_in)
                d = jnp.einsum("bsd,bdr->bsr", xa, Ai)
                d = jnp.einsum("bsr,bro->bso", d, Bi)
            else:                                   # (B, d_in)
                d = jnp.einsum("bd,bdr->br", xa, Ai)
                d = jnp.einsum("br,bro->bo", d, Bi)
            out = out + d.astype(out.dtype)
    return out


def _cache_layer(kc, li):
    """ONE layer's buffer out of a stacked cache: plain slice for an
    array, per-leaf slice for a quantized ``{"q", "s"}`` buffer."""
    from paddle_tpu.quantization.kv_cache import is_quantized_kv
    if is_quantized_kv(kc):
        return {"q": kc["q"][li], "s": kc["s"][li]}
    return kc[li]


def _cache_layer_set(kc, kc_l, li):
    """Write one layer's updated buffer back into a stacked cache."""
    from paddle_tpu.quantization.kv_cache import is_quantized_kv
    if is_quantized_kv(kc):
        return {"q": jax.lax.dynamic_update_slice(
                    kc["q"], kc_l["q"][None], (li, 0, 0, 0, 0)),
                "s": jax.lax.dynamic_update_slice(
                    kc["s"], kc_l["s"][None], (li, 0, 0, 0, 0))}
    return jax.lax.dynamic_update_slice(kc, kc_l[None], (li, 0, 0, 0, 0))


def _cache_update(buf, t, pos, head_major, sharded=False):
    """Write t into ONE layer's cache buffer at [pos, pos+S). Scalar pos:
    a single dynamic-update-slice. Per-row (B,) pos: the same DUS vmapped
    over the batch (lowers to scatter — each row lands at its own
    offset, the speculative-decode requirement). A quantized buffer
    (``int8wk``) quantizes the incoming rows by per-row absmax and
    updates the int8 and scale leaves with the SAME index math (the
    scale keeps a last dim of 1, so ranks line up).

    ``sharded`` may be the live ``DecodeSharding`` (not just a bool): the
    per-row branch then lowers through ``shard_map`` — dp splits the
    batch, tp splits the head axis, and the per-row DUS touches only its
    own row's shard, so the LOCAL body is exactly the single-device body
    and no collective is ever needed. That is the trusted sharded
    lowering of the speculative uneven cache advance (the former
    ``SpeculativeMeshError``); axes the guard drops (non-dividing dims)
    replicate, and the body still computes identical values per replica."""
    from paddle_tpu.quantization.kv_cache import (is_quantized_kv,
                                                  quantize_kv_rows)
    if is_quantized_kv(buf):
        qt = quantize_kv_rows(t)
        return {"q": _cache_update(buf["q"], qt["q"], pos, head_major,
                                   sharded),
                "s": _cache_update(buf["s"], qt["s"], pos, head_major,
                                   sharded)}
    if jnp.ndim(pos) == 1:
        if head_major:     # buf (B, KV, L, D), t (B, KV, S, D)
            f = lambda c, u, p0: jax.lax.dynamic_update_slice(  # noqa: E731
                c, u, (0, p0, 0))
        else:              # buf (B, L, KV, D), t (B, S, KV, D)
            f = lambda c, u, p0: jax.lax.dynamic_update_slice(  # noqa: E731
                c, u, (p0, 0, 0))
        upd = jax.vmap(f)
        srd = sharded if (sharded and not isinstance(sharded, bool)) \
            else None
        if srd is not None:
            try:
                from jax.experimental.shard_map import shard_map
                ent = srd.state_entries("kc", buf.ndim, head_major)
                bspec = srd.guarded(buf.shape, ent)
                tspec = srd.guarded(t.shape, ent)
                pspec = srd.guarded(pos.shape,
                                    srd.state_entries("pos", 1))
                return shard_map(
                    upd, mesh=srd.jax_mesh,
                    in_specs=(bspec, tspec, pspec), out_specs=bspec,
                    check_rep=False)(buf, t, pos)
            except Exception:
                pass       # plain vmap below: GSPMD scatters it instead
        return upd(buf, t, pos)
    at = (0, 0, pos, 0) if head_major else (0, pos, 0, 0)
    return jax.lax.dynamic_update_slice(buf, t, at)


def _row_scatter(dst, src, idx):
    """Scatter whole batch rows ``src[j] -> dst[idx[j]]`` on the cache
    batch axis (``ndim - 4``: 0 for a per-layer 4-D buffer, 1 for a
    stacked 5-D one), recursing over per-layer tuples and quantized
    ``{"q", "s"}`` leaves. ``idx`` entries >= dst's batch size DROP
    (``mode="drop"``) — the admission-ring convention maps empty ring
    rows to that sentinel (NEVER pass raw -1: negative scatter indices
    wrap). Used both to stage admission-prefill rows into the ring and
    to splice ring rows into the live carry inside the chunk program."""
    from paddle_tpu.quantization.kv_cache import is_quantized_kv
    if is_quantized_kv(dst):
        return {"q": _row_scatter(dst["q"], src["q"], idx),
                "s": _row_scatter(dst["s"], src["s"], idx)}
    if isinstance(dst, tuple):
        return tuple(_row_scatter(d, s, idx) for d, s in zip(dst, src))
    ax = dst.ndim - 4
    if ax <= 0:
        return dst.at[idx].set(src, mode="drop")
    return dst.at[:, idx].set(src, mode="drop")


def _block_forward(p, cfg: LlamaConfig, li: int, h, kc, vc, pos, max_len,
                   sharded=False, aidx=None):
    """One decoder block over h (B, S, H) writing K/V into the cache at
    [pos, pos+S); attention reads the whole cache masked to < pos+S with
    causal alignment to the bottom-right (query i attends to <= pos+i).
    ``pos``: scalar or per-row (B,) vector. ``sharded`` (trace-time
    static): the decoder runs under a GSPMD mesh — hand-written Pallas
    kernels (no partitioning rules) give way to the XLA forms, which
    shard via sharding propagation. ``aidx`` (B,) i32 routes per-row LoRA
    deltas through every projection (see ``_mm``)."""
    B, S, _ = h.shape
    H, KV, D = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    pre = f"model.layers.{li}."

    def rms(x, w):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        return (x.astype(jnp.float32) * jax.lax.rsqrt(
            var + cfg.rms_norm_eps)).astype(x.dtype) * w

    x = rms(h, p[pre + "input_layernorm.weight"])
    qkv = _mm(x, p, pre + "self_attn.qkv.weight", sharded, aidx)
    q = qkv[..., :H * D].reshape(B, S, H, D)
    k = qkv[..., H * D:H * D + KV * D].reshape(B, S, KV, D)
    v = qkv[..., H * D + KV * D:].reshape(B, S, KV, D)
    q = _rope_at(q, pos, cfg, p)
    k = _rope_at(k, pos, cfg, p)

    rep = H // KV
    head_major = rep > 1   # GQA: (B, KV, L, D) tiles feed the Pallas
    #                        kernel; MHA keeps token-major (B, L, KV, D),
    #                        which XLA's fused matvec prefers (measured)
    kt = jnp.swapaxes(k, 1, 2) if head_major else k
    vt = jnp.swapaxes(v, 1, 2) if head_major else v
    if isinstance(kc, tuple):
        # per-layer cache buffers: an update on THIS layer's array only
        kc_l = _cache_update(kc[li], kt, pos, head_major, sharded)
        vc_l = _cache_update(vc[li], vt, pos, head_major, sharded)
        kc = tuple(kc_l if i == li else c for i, c in enumerate(kc))
        vc = tuple(vc_l if i == li else c for i, c in enumerate(vc))
    else:
        kc_l = _cache_update(_cache_layer(kc, li), kt, pos, head_major,
                             sharded)
        vc_l = _cache_update(_cache_layer(vc, li), vt, pos, head_major,
                             sharded)
        kc = _cache_layer_set(kc, kc_l, li)
        vc = _cache_layer_set(vc, vc_l, li)

    from paddle_tpu.flags import flags as _flags
    from paddle_tpu.ops.pallas import decode_attention as _da
    from paddle_tpu.quantization.kv_cache import (dequantize_kv,
                                                  is_quantized_kv)
    quant_kv = is_quantized_kv(kc_l)
    use_kernel = (head_major and S == 1 and jnp.ndim(pos) <= 1
                  and not sharded
                  and _flags.use_decode_attention
                  and (jax.default_backend() == "tpu"
                       or _flags.decode_attention_interpret)
                  and _da.supported(q[:, 0],
                                    kc_l["q"] if quant_kv else kc_l))
    # per-row qpos: scalar pos broadcasts as (1,1,S,1), vector as (B,1,S,1)
    qpos = (jnp.reshape(pos, (-1, 1, 1, 1))
            + jnp.arange(S)[None, None, :, None])
    if use_kernel:
        # one-kernel GQA cache attention (block_multi_head_attention
        # capability): no repeated-KV materialization, online softmax,
        # compute skipped past the valid prefix; ``pos`` may be per-row
        # (the chunked serving path, where rows sit at different cache
        # offsets). Int8 caches (int8wk) stream int8 tiles and dequant
        # in VMEM against their per-row scales. Measured (v5e, B=8
        # D=64): 8-way GQA L=4096 0.24 ms vs 0.88 ms XLA; 4-way L=8192
        # 0.60 ms vs 2.06 ms; ~1B GQA4 end-to-end 2.98 vs 7.08 ms/tok.
        if quant_kv:
            out = _da.decode_attention(
                q[:, 0], kc_l["q"], vc_l["q"], pos + 1,
                k_scale=kc_l["s"], v_scale=vc_l["s"]).reshape(B, S, H * D)
        else:
            out = _da.decode_attention(q[:, 0], kc_l, vc_l,
                                       pos + 1).reshape(B, S, H * D)
    elif head_major:
        kk = jnp.repeat(dequantize_kv(kc_l, q.dtype), rep, axis=1)
        vv = jnp.repeat(dequantize_kv(vc_l, q.dtype), rep, axis=1)
        scores = jnp.einsum("bqhd,bhkd->bhqk", q, kk) / jnp.sqrt(
            jnp.float32(D)).astype(q.dtype)
        kpos = jnp.arange(max_len)[None, None, None, :]
        mask = kpos <= qpos                       # bottom-right causal
        scores = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bhkd->bqhd", attn, vv).reshape(B, S, H * D)
    else:
        kk = dequantize_kv(kc_l, q.dtype)         # (B, max_len, KV, D)
        vv = dequantize_kv(vc_l, q.dtype)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / jnp.sqrt(
            jnp.float32(D)).astype(q.dtype)
        kpos = jnp.arange(max_len)[None, None, None, :]
        mask = kpos <= qpos                       # bottom-right causal
        scores = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", attn, vv).reshape(B, S, H * D)
    h = h + _mm(out, p, pre + "self_attn.o_proj.weight", sharded, aidx)

    x = rms(h, p[pre + "post_attention_layernorm.weight"])
    gu = _mm(x, p, pre + "mlp.gate_up.weight", sharded, aidx)
    F_ = gu.shape[-1] // 2
    a = jax.nn.silu(gu[..., :F_]) * gu[..., F_:]
    return h + _mm(a, p, pre + "mlp.down_proj.weight", sharded, aidx), \
        kc, vc


def _forward_cached(p, cfg: LlamaConfig, ids, kc, vc, pos, max_len,
                    return_all: bool = False, sharded: bool = False,
                    aidx=None):
    """ids (B, S) -> logits (B, V) of the LAST position — or of ALL S
    positions (B, S, V) with ``return_all=True`` (speculative verify
    scores every drafted position in one batched forward) — plus the
    updated caches. ``pos``: scalar or per-row (B,) vector. ``aidx``
    (B,) i32: per-row LoRA adapter index (projections only — the head
    stays base)."""
    h = p["model.embed_tokens.weight"][ids]
    for li in range(cfg.num_hidden_layers):
        h, kc, vc = _block_forward(p, cfg, li, h, kc, vc, pos, max_len,
                                   sharded, aidx)
    var = jnp.mean(jnp.square(h.astype(jnp.float32)), -1, keepdims=True)
    h = (h.astype(jnp.float32) * jax.lax.rsqrt(var + cfg.rms_norm_eps)
         ).astype(h.dtype) * p["model.norm.weight"]
    hh = h if return_all else h[:, -1]
    if "head:int8" in p:
        logits = _mm(hh, p, "head", sharded).astype(jnp.float32)
    else:
        head = (p["model.embed_tokens.weight"].T if cfg.tie_word_embeddings
                else p["lm_head.weight"])
        logits = (hh @ head).astype(jnp.float32)
    return logits, kc, vc


def _build_params(model: LlamaForCausalLM, max_len: int,
                  weight_dtype: Optional[str]):
    """Snapshot + decode-shape a model's weights: fused qkv / gate_up
    matmuls, optional int8 weight-only quantization, precomputed rope
    tables for the whole cache window. Shared by the target decoder and
    any separate-weights draft model (speculative decoding)."""
    raw = {name: t.value for name, t in model.state_dict().items()}
    # fuse qkv and gate/up per layer (one matmul each; fewer kernels)
    for li in range(model.config.num_hidden_layers):
        pre = f"model.layers.{li}."
        raw[pre + "self_attn.qkv.weight"] = jnp.concatenate(
            [raw.pop(pre + "self_attn.q_proj.weight"),
             raw.pop(pre + "self_attn.k_proj.weight"),
             raw.pop(pre + "self_attn.v_proj.weight")], axis=1)
        raw[pre + "mlp.gate_up.weight"] = jnp.concatenate(
            [raw.pop(pre + "mlp.gate_proj.weight"),
             raw.pop(pre + "mlp.up_proj.weight")], axis=1)
    p = {}
    for name, v in raw.items():
        if (weight_dtype == "int8" and v.ndim == 2
                and ("self_attn." in name or "mlp." in name)):
            from paddle_tpu.quantization import weight_quantize
            from paddle_tpu.framework.tensor import Tensor
            q, scale = weight_quantize(Tensor(v))
            p[name + ":int8"] = q.value
            p[name + ":scale"] = scale.value
            continue
        p[name] = v
    # the lm head (tied: transposed embedding) is the single biggest
    # matrix in the step — quantize a dedicated copy of it too
    if weight_dtype == "int8":
        from paddle_tpu.quantization import weight_quantize
        from paddle_tpu.framework.tensor import Tensor
        head = (p["model.embed_tokens.weight"].T
                if model.config.tie_word_embeddings
                else p.pop("lm_head.weight"))
        q, scale = weight_quantize(Tensor(head))
        p["head:int8"] = q.value
        p["head:scale"] = scale.value
    # precomputed rope tables for the whole cache window
    cos, sin = _rope_tables(max_len, model.config.head_dim,
                            model.config.rope_theta,
                            jnp.dtype(model.config.dtype), offset=0)
    p["rope.cos"], p["rope.sin"] = cos, sin
    return p


def _spec_round(p, dp, cfg, dcfg, tok, pos, key, done, kc, vc, dkc, dvc,
                eos_id, temperature, max_len, *, K: int, do_sample: bool,
                use_eos: bool, top_k, top_p, sharded=False):
    """One draft-propose / target-verify / accept round (Leviathan et
    al., arXiv:2211.17192) as a pure trace-level function, so the SAME
    code runs inside the fused while-loop program AND as the per-round
    fallback's jitted step (that identity is what makes fused-vs-fallback
    token parity bit-exact).

    ``pos`` is PER-ROW (B,): acceptance is data-dependent, so rows
    advance by different amounts and each owns its cache offset. The
    draft runs K+1 single-token forwards from its own cache (the +1
    keeps the draft cache complete when every proposal is accepted); the
    target scores all K+1 positions in ONE batched cached forward.
    Acceptance: greedy = exact match against the target argmax;
    sampling = the rejection rule u < min(1, p(d)/q(d)) over the
    FILTERED (temperature/top-k/top-p) target/draft distributions, with
    the first rejection resampled from norm(max(p - q, 0)) — preserving
    the target distribution exactly. Rows that were done (eos) flush eos
    at the full K+1 rate so the output buffer fills like the non-
    speculative program's.

    Returns (emit (B, K+1), accepted (B,), next_tok (B,), key, done,
    kc, vc, dkc, dvc): emit slot j < a holds the accepted draft
    d_{j+1}, slot a the target's correction/bonus token; slots > a are
    padding the caller drops. Cache rows past each row's committed
    length are stale but masked, and the next round overwrites them
    before they could ever unmask.
    """
    B = tok.shape[0]
    if do_sample:
        key, sub = jax.random.split(key)
        rk = jax.random.split(sub, 3)
        dkeys = jax.random.split(rk[0], K)      # draft proposal keys
        u = jax.random.uniform(rk[1], (B, K))   # acceptance uniforms
        ckey = rk[2]                            # correction/bonus key

    def dbody(carry, j):
        cur, dkc, dvc = carry
        lg, dkc, dvc = _forward_cached(dp, dcfg, cur[:, None], dkc, dvc,
                                       pos + j, max_len, sharded=sharded)
        if do_sample:
            kj = jax.lax.dynamic_index_in_dim(
                dkeys, jnp.minimum(j, K - 1), keepdims=False)
            flt = _filter_logits(lg, temperature, top_k, top_p)
            nxt = jax.random.categorical(kj, flt,
                                         axis=-1).astype(jnp.int32)
            return (nxt, dkc, dvc), (nxt, flt)
        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
        return (nxt, dkc, dvc), nxt

    (_, dkc, dvc), ys = jax.lax.scan(dbody, (tok, dkc, dvc),
                                     jnp.arange(K + 1))
    props = jnp.moveaxis((ys[0] if do_sample else ys)[:K], 0, 1)  # (B, K)
    seq = jnp.concatenate([tok[:, None], props], axis=1)       # (B, K+1)
    all_lg, kc, vc = _forward_cached(p, cfg, seq, kc, vc, pos, max_len,
                                     return_all=True,
                                     sharded=sharded)          # (B,K+1,V)
    if do_sample:
        pprob = jax.nn.softmax(
            _filter_logits(all_lg, temperature, top_k, top_p), axis=-1)
        qprob = jax.nn.softmax(jnp.moveaxis(ys[1][:K], 0, 1), axis=-1)
        pd = jnp.take_along_axis(pprob[:, :K], props[..., None],
                                 axis=-1)[..., 0]
        qd = jnp.take_along_axis(qprob, props[..., None], axis=-1)[..., 0]
        accept = u * qd < pd       # u < min(1, p/q) without the divide
        a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
        pa = jnp.take_along_axis(pprob, a[:, None, None], axis=1)[:, 0]
        qa = jnp.take_along_axis(
            qprob, jnp.minimum(a, K - 1)[:, None, None], axis=1)[:, 0]
        resid = jnp.maximum(pa - qa, 0.0)
        rs = jnp.sum(resid, axis=-1, keepdims=True)
        # all-accepted rows draw the bonus token from p_K itself; a
        # degenerate all-zero residual (p <= q everywhere) falls back to p
        resid = jnp.where(rs > 0, resid / jnp.where(rs > 0, rs, 1.0), pa)
        dist = jnp.where((a == K)[:, None], pa, resid)
        corr = jax.random.categorical(ckey, jnp.log(dist),
                                      axis=-1).astype(jnp.int32)
    else:
        tgt = jnp.argmax(all_lg, -1).astype(jnp.int32)         # (B, K+1)
        match = props == tgt[:, :K]
        a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        corr = jnp.take_along_axis(tgt, a[:, None], axis=1)[:, 0]
    jidx = jnp.arange(K + 1)[None, :]
    ext = jnp.concatenate([props, jnp.zeros((B, 1), jnp.int32)], axis=1)
    emit = jnp.where(jidx < a[:, None], ext,
                     jnp.where(jidx == a[:, None], corr[:, None], 0))
    if use_eos:
        a = jnp.where(done, K, a)    # finished rows flush eos full-rate
        emit = jnp.where(done[:, None], eos_id, emit)
        valid = jidx <= a[:, None]
        hit = jnp.logical_and(emit == eos_id, valid)
        after = (jnp.cumsum(hit.astype(jnp.int32), axis=1)
                 - hit.astype(jnp.int32)) > 0
        emit = jnp.where(jnp.logical_and(after, valid), eos_id, emit)
        done = jnp.logical_or(done, jnp.any(hit, axis=1))
    tok_next = jnp.take_along_axis(emit, a[:, None], axis=1)[:, 0]
    return emit, a, tok_next, key, done, kc, vc, dkc, dvc


def _spec_round_rows(p, dp, cfg, dcfg, tok, pos, keys, done, kc, vc, dkc,
                     dvc, eos, temp, max_len, *, K: int, do_sample: bool,
                     top_k, top_p, sharded=False, aidx=None, spec_on=None):
    """``_spec_round`` under the CHUNKED-SERVING carry contract: PER-ROW
    RNG keys (each row splits its OWN (2,) raw uint32 key per round, so
    its sample stream is invariant to batch neighbours — the admission
    contract ``chunk_decode`` already honours), per-row eos ids (``-1``
    = none; rows already done flush their eos fill at the full K+1 rate)
    and per-row temperatures. Same Leviathan accept/reject math as
    ``_spec_round`` — greedy rounds are bit-identical, which is what the
    chunk-slicing-invariance tests ride on.

    ``aidx`` routes per-row LoRA deltas through the TARGET forwards only
    (verify + the committed pick); the draft stays base — a mismatched
    draft can only cost acceptance length, never correctness, because
    every emitted token is accept/reject-verified against the adapter-
    routed target. ``spec_on`` (B,) bool demotes False rows to verify-
    free decode INSIDE the same program: their acceptance is forced to 0
    BEFORE the correction draw and the correction distribution is the
    target's own position-0 law (``pa``), so a sampled spec-off row draws
    from exactly the filtered target distribution and a greedy spec-off
    row emits exactly the plain-decode argmax.

    Returns ``(emit (B, K+1), a (B,), tok_next (B,), lg_a (B, V), keys,
    done, kc, vc, dkc, dvc)``; ``lg_a`` is the verify logits at each
    row's accepted position — the freshest finite logits the carry can
    hold (NOT pick-ready: ``tok_next`` is the pending pick)."""
    B = tok.shape[0]
    fill = jnp.where(eos >= 0, eos, 0)
    if do_sample:
        kk = jax.vmap(jax.random.split)(keys)               # (B, 2, 2)
        keys_next, sub = kk[:, 0], kk[:, 1]
        rk = jax.vmap(lambda k: jax.random.split(k, 3))(sub)
        dkeys = jax.vmap(lambda k: jax.random.split(k, K))(rk[:, 0])
        u = jax.vmap(lambda k: jax.random.uniform(k, (K,)))(rk[:, 1])
        ckey = rk[:, 2]                                     # (B, 2)
    else:
        keys_next = keys

    def dbody(carry, j):
        cur, dkc, dvc = carry
        lg, dkc, dvc = _forward_cached(dp, dcfg, cur[:, None], dkc, dvc,
                                       pos + j, max_len, sharded=sharded)
        if do_sample:
            kj = jax.lax.dynamic_index_in_dim(
                dkeys, jnp.minimum(j, K - 1), axis=1, keepdims=False)
            flt = _filter_logits(lg, temp[:, None], top_k, top_p)
            nxt = jax.vmap(jax.random.categorical)(
                kj, flt).astype(jnp.int32)
            return (nxt, dkc, dvc), (nxt, flt)
        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
        return (nxt, dkc, dvc), nxt

    (_, dkc, dvc), ys = jax.lax.scan(dbody, (tok, dkc, dvc),
                                     jnp.arange(K + 1))
    props = jnp.moveaxis((ys[0] if do_sample else ys)[:K], 0, 1)  # (B, K)
    seq = jnp.concatenate([tok[:, None], props], axis=1)       # (B, K+1)
    all_lg, kc, vc = _forward_cached(p, cfg, seq, kc, vc, pos, max_len,
                                     return_all=True,
                                     sharded=sharded, aidx=aidx)  # B,K+1,V
    if do_sample:
        pprob = jax.nn.softmax(
            _filter_logits(all_lg, temp[:, None, None], top_k, top_p),
            axis=-1)
        qprob = jax.nn.softmax(jnp.moveaxis(ys[1][:K], 0, 1), axis=-1)
        pd = jnp.take_along_axis(pprob[:, :K], props[..., None],
                                 axis=-1)[..., 0]
        qd = jnp.take_along_axis(qprob, props[..., None], axis=-1)[..., 0]
        accept = u * qd < pd
        a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
        if spec_on is not None:
            a = jnp.where(spec_on, a, 0)   # BEFORE the pa/qa gathers: the
            #   spec-off correction must come from the position-0 law
        pa = jnp.take_along_axis(pprob, a[:, None, None], axis=1)[:, 0]
        qa = jnp.take_along_axis(
            qprob, jnp.minimum(a, K - 1)[:, None, None], axis=1)[:, 0]
        resid = jnp.maximum(pa - qa, 0.0)
        rs = jnp.sum(resid, axis=-1, keepdims=True)
        resid = jnp.where(rs > 0, resid / jnp.where(rs > 0, rs, 1.0), pa)
        dist = jnp.where((a == K)[:, None], pa, resid)
        if spec_on is not None:
            # spec-off rows sample the target distribution itself, not
            # the rejection residual — the verify-free decode law
            dist = jnp.where(spec_on[:, None], dist, pa)
        corr = jax.vmap(jax.random.categorical)(
            ckey, jnp.log(dist)).astype(jnp.int32)
    else:
        tgt = jnp.argmax(all_lg, -1).astype(jnp.int32)         # (B, K+1)
        match = props == tgt[:, :K]
        a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        if spec_on is not None:
            a = jnp.where(spec_on, a, 0)
        corr = jnp.take_along_axis(tgt, a[:, None], axis=1)[:, 0]
    jidx = jnp.arange(K + 1)[None, :]
    ext = jnp.concatenate([props, jnp.zeros((B, 1), jnp.int32)], axis=1)
    emit = jnp.where(jidx < a[:, None], ext,
                     jnp.where(jidx == a[:, None], corr[:, None], 0))
    a = jnp.where(done, K, a)        # finished rows flush fill full-rate
    emit = jnp.where(done[:, None], fill[:, None], emit)
    valid = jidx <= a[:, None]
    hit = jnp.logical_and(emit == eos[:, None], valid)  # -1 never matches
    after = (jnp.cumsum(hit.astype(jnp.int32), axis=1)
             - hit.astype(jnp.int32)) > 0
    emit = jnp.where(jnp.logical_and(after, valid), fill[:, None], emit)
    done = jnp.logical_or(done, jnp.any(hit, axis=1))
    tok_next = jnp.take_along_axis(emit, a[:, None], axis=1)[:, 0]
    lg_a = jnp.take_along_axis(all_lg, a[:, None, None], axis=1)[:, 0]
    return emit, a, tok_next, lg_a, keys_next, done, kc, vc, dkc, dvc


class LlamaDecoder:
    """Compile-once greedy/sampling decoder with a static KV cache.

    Two executables per generate: ``prefill`` (fixed prompt length, pad to
    reuse) and ``fused_decode`` — the ENTIRE token loop (argmax or
    temperature/top-k/top-p sampling, per-step key splits, per-row eos
    freezing) as one ``lax.scan`` program, so a ``generate`` of N tokens
    is 2 device dispatches regardless of mode, with zero retraces across
    calls/seeds/eos ids/temperatures (temperature is a runtime input).
    With a ``draft_model`` (a smaller LlamaForCausalLM or a ``'skip:N'``
    layer-skip view of the target), ``generate`` runs SPECULATIVE
    decoding: the draft proposes K tokens per round from its own cache,
    the target verifies all K+1 positions in one batched forward, and
    accept/reject + per-row cache advance + eos freezing all live inside
    one ``lax.while_loop`` program — prefill(target) + prefill(draft) +
    ONE decode dispatch. ``dispatch_count`` counts executions so both
    one-dispatch properties are assertable in tests; the per-token
    ``step`` / per-round speculative fallback remain behind the
    ``decode_fallback`` flag.

    Resilience (runtime/resilience.py): every device dispatch retries
    transient backend errors (UNAVAILABLE and friends) with exponential
    backoff, and ``generate`` walks a DEGRADATION LADDER — fused
    speculative -> fused plain -> per-token fallback — stepping down
    automatically when a level keeps failing (``FLAGS_resilience_*``).
    Each retry/degradation is a typed event; the record rides on the
    returned array (``GenerateResult.resilience``) and on
    ``self.last_resilience``.
    """

    def __init__(self, model: LlamaForCausalLM, max_len: int = 512,
                 weight_dtype: Optional[str] = None, mesh=None,
                 partition_rules=None, quant: Optional[str] = None):
        """``quant`` picks the decode dtype recipe
        (quantization/kv_cache.resolve_decode_quant; default also via
        ``FLAGS_decode_quant`` / ``PADDLE_TPU_DECODE_QUANT``):

        - ``"int8w"`` — per-output-channel absmax int8 weight-only
          quantization of the decoder/MLP matmuls (embedding and norms
          stay in the activation dtype); the legacy
          ``weight_dtype="int8"`` argument is an alias. On TPU the
          dequant runs inside the Pallas matmul tile
          (ops/pallas/int8_matmul), so the quantized matrices stream
          int8 from HBM — halving the weight bandwidth that bounds
          small-batch decode (reference weight_only_linear capability).
        - ``"int8wk"`` — int8w PLUS an int8 KV cache: every written K/V
          row quantizes by per-row absmax (scales live beside the int8
          rows in the ``DecodeState`` carry) and dequantizes on load
          inside the scan body's attention — or inside the Pallas
          decode-attention tile — so neither the weights nor the cache
          ever materialize an fp copy in HBM. Refused typed on a mesh
          (``QuantizedKVMeshError``); ``int8w`` serves on a mesh via
          the XLA dequant form.

        Decode steps are kernel-count-sensitive (the scan body runs ~1ms
        of tiny ops on a 134M model): q/k/v and gate/up are concatenated
        at init into single fused matmuls (q_proj|k_proj|v_proj ->
        'self_attn.qkv', gate|up -> 'mlp.gate_up'), and the rope tables
        are precomputed once for max_len instead of per step.

        ``mesh``: a ``ProcessMesh`` / ``jax.sharding.Mesh`` /
        ``"dp:2,tp:4"`` spec — the decoder then runs TENSOR-PARALLEL over
        the ``tp`` axis and batch-parallel over ``dp``
        (inference/sharding.DecodeSharding): params are sharded by regex
        partition rules (``partition_rules`` overrides
        ``DEFAULT_DECODE_RULES``), the ``DecodeState`` carry — KV caches
        on the head axis, per-row pos/keys/done on dp — lives sharded on
        device across chunk re-entry, and every jitted entry pins its
        carry outputs to the same placements (sharding-preserving jit).
        Greedy and per-row-keyed sampled TOKENS are bit-exact with the
        single-device path — including SPECULATIVE decode, whose per-row
        uneven cache advance lowers through ``shard_map``
        (``_cache_update``); only speculative BUNDLE EXPORT from a
        mesh-built decoder still refuses typed
        (``SpeculativeMeshError``)."""
        from paddle_tpu.quantization.kv_cache import resolve_decode_quant
        self.quant = resolve_decode_quant(quant, weight_dtype)
        # legacy surface (bundle meta, draft-param reuse): any quantized
        # recipe quantizes the weights int8
        self.weight_dtype = "int8" if self.quant else None
        self.quant_kv = self.quant == "int8wk"
        self.cfg = model.config
        self.max_len = max_len
        self.sharding = None
        if mesh is not None:
            from paddle_tpu.inference.sharding import DecodeSharding
            self.sharding = (mesh if isinstance(mesh, DecodeSharding)
                             else DecodeSharding(mesh,
                                                 rules=partition_rules))
        elif partition_rules is not None:
            raise ValueError("partition_rules requires a mesh")
        if self.quant_kv and self.sharding is not None:
            from paddle_tpu.inference.sharding import QuantizedKVMeshError
            raise QuantizedKVMeshError(
                "quant='int8wk' does not run on a mesh yet: the int8 KV "
                "carry's scale buffers have no partition rules; use "
                "quant='int8w' (weight-only) on a mesh, or drop mesh=")
        self.params = _build_params(model, max_len, self.weight_dtype)
        if self.sharding is not None:
            self.params = self.sharding.shard_params(self.params)
        cfg = self.cfg
        # trace-time statics the closures below capture: the LIVE
        # DecodeSharding when the programs run under GSPMD (falsy
        # off-mesh — every `if not sharded` check still reads naturally,
        # and _cache_update can reach the mesh for its shard_map
        # lowering), and the cache layout's head axis
        shd = self.sharding if self.sharding is not None else False
        head_major = cfg.num_attention_heads != cfg.num_key_value_heads
        self._head_major = head_major
        srd = self.sharding

        def pin_carry(logits, kc, vc, pos, keys, done):
            """Sharding-preserving jit: carry outputs keep the carry
            inputs' placements, so re-entry never decays to replicated
            (no-op off-mesh)."""
            if not shd:
                return logits, kc, vc, pos, keys, done
            return srd.constrain_carry(logits, kc, vc, pos, keys, done,
                                       head_major)
        self.trace_count = 0     # python side effect: bumps only on (re)trace
        self.dispatch_count = 0  # one per device program execution
        self._spec_engines = {}  # draft-model state for speculative decode
        self.last_spec_stats = None
        self.last_resilience = None  # retry/degradation record of the last
        #                              generate (also on the result array)
        self._events = []        # typed events of the in-flight generate

        def pin_fwd(logits, kc, vc):
            if not shd:
                return logits, kc, vc
            return (srd.constrain(logits, "logits", head_major),
                    srd.constrain(kc, "kc", head_major),
                    srd.constrain(vc, "vc", head_major))

        def prefill(p, ids, kc, vc):
            self.trace_count += 1
            return pin_fwd(*_forward_cached(p, cfg, ids, kc, vc, 0,
                                            max_len, sharded=shd))

        def step(p, ids, kc, vc, pos):
            self.trace_count += 1
            return pin_fwd(*_forward_cached(p, cfg, ids, kc, vc, pos,
                                            max_len, sharded=shd))

        def fused_decode(p, logits0, kc, vc, pos0, key0, done0, eos_id,
                         temperature, steps: int, do_sample: bool,
                         use_eos: bool, top_k, top_p):
            """The whole token loop — sampling and EOS handling included —
            as ONE device program (lax.scan): over a network-tunneled chip,
            per-token host dispatches dominate, so this collapses N tokens
            to a single dispatch for EVERY decode mode. The jax.random key
            threads through the carry and splits once per step (identical
            stream to the per-token fallback); ``done0`` rows that hit
            ``eos_id`` freeze to eos, and the host trims post-eos columns
            after the fact (``_trim_after_eos``). Temperature is a RUNTIME
            scalar input (one compiled program / one AOT entry serves any
            temperature); top-k/top-p change program structure and stay
            static."""
            self.trace_count += 1

            def pick(logits, key, done):
                if do_sample:
                    key, sub = jax.random.split(key)
                    tok = _sample_from(logits, sub, temperature, top_k,
                                       top_p).astype(jnp.int32)
                else:
                    tok = jnp.argmax(logits, -1).astype(jnp.int32)
                if use_eos:
                    tok = jnp.where(done, eos_id, tok)
                    done = jnp.logical_or(done, tok == eos_id)
                return tok, key, done

            def body(carry, _):
                logits, kc, vc, pos, key, done = carry
                tok, key, done = pick(logits, key, done)
                logits, kc, vc = _forward_cached(p, cfg, tok[:, None], kc,
                                                 vc, pos, max_len,
                                                 sharded=shd)
                return (logits, kc, vc, pos + 1, key, done), tok

            (logits, _, _, _, key, done), toks = jax.lax.scan(
                body, (logits0, kc, vc, pos0, key0, done0), None,
                length=steps)
            last, _, _ = pick(logits, key, done)
            return jnp.concatenate([jnp.moveaxis(toks, 0, 1),
                                    last[:, None]], axis=1)

        def chunk_decode(p, logits0, kc, vc, pos0, keys0, done0, eos,
                         temperature, aidx, steps: int, do_sample: bool,
                         top_k, top_p):
            """T steps of the fused token loop as ONE re-enterable
            dispatch: the carry comes in and goes back out as plain
            arrays (DecodeState), so a serving engine can admit new
            requests into freed rows BETWEEN chunks instead of holding
            dead slots until the slowest row finishes (Orca-style
            iteration-level batching). Per-row everything: positions
            (rows admitted at different times sit at different cache
            offsets), eos ids (-1 = none), temperatures, and RNG keys —
            each row splits its OWN key per step, so a row's sample
            stream is invariant to its batch neighbours. Greedy chunks
            chained over N steps are bit-exact with the run-to-completion
            fused path (same pick-then-forward stream). ``aidx`` (B,) i32
            or None: per-row LoRA adapter routing — read-only here, like
            eos/temperature (admission rewrites it via the ring/scatter
            paths)."""
            self.trace_count += 1

            def pick(logits, keys, done):
                if do_sample:
                    kk = jax.vmap(jax.random.split)(keys)       # (B,2,2)
                    keys, subs = kk[:, 0], kk[:, 1]
                    flt = _filter_logits(logits, temperature[:, None],
                                         top_k, top_p)
                    tok = jax.vmap(jax.random.categorical)(
                        subs, flt).astype(jnp.int32)
                else:
                    tok = jnp.argmax(logits, -1).astype(jnp.int32)
                tok = jnp.where(done, jnp.where(eos >= 0, eos, 0), tok)
                done = jnp.logical_or(done, tok == eos)
                return tok, keys, done

            def body(carry, _):
                logits, kc, vc, pos, keys, done = carry
                tok, keys, done = pick(logits, keys, done)
                logits, kc, vc = _forward_cached(p, cfg, tok[:, None], kc,
                                                 vc, pos, max_len,
                                                 sharded=shd, aidx=aidx)
                # rows past their budget keep stepping until the chunk
                # boundary; clamping pins their (discarded) writes to the
                # last cache slot instead of running off the buffer
                pos = jnp.minimum(pos + 1, max_len - 1)
                return (logits, kc, vc, pos, keys, done), tok

            (logits, kc, vc, pos, keys, done), toks = jax.lax.scan(
                body, (logits0, kc, vc, pos0, keys0, done0), None,
                length=steps)
            # the re-entry contract: the carry leaves this program with
            # the SAME placements it arrived with (sharding-preserving
            # jit) — chaining chunks never gathers the state to host
            logits, kc, vc, pos, keys, done = pin_carry(
                logits, kc, vc, pos, keys, done)
            return (jnp.moveaxis(toks, 0, 1), logits, kc, vc, pos, keys,
                    done)

        def admit_prefill(p, ids, kc, vc, true_len, pos0, aidx=None):
            """Length-bucketed admission prefill: ``ids`` is a batch of
            requests right-padded to one prompt bucket (one compiled
            program per (batch, bucket), not per distinct prompt length).
            ``true_len`` and ``pos0`` are PER-ROW ``(B,)`` vectors: each
            row's tokens land in the cache at ``[pos0, pos0+S)`` and its
            returned logits are those of position ``true_len - 1`` of the
            bucket — causal masking makes the padded tail invisible to
            them, and decode overwrites the tail's cache rows before they
            could ever unmask — so the admitted row decodes bit-exactly
            like an unpadded solo generate. ``pos0 > 0`` is the prefix-
            cache SUFFIX prefill (serving/prefix_cache.py): ``kc``/``vc``
            arrive preloaded with the cached prefix's KV rows ``[0,
            pos0)`` and only the uncached suffix is computed; several
            same-bucket admissions batch into one dispatch (per-row
            offsets keep their prefixes independent). ``aidx`` (B,) i32
            or None: each admitted row's prompt prefills through ITS
            adapter's deltas, so the cached prefix KV matches what a
            dense per-tenant model would have produced."""
            self.trace_count += 1
            logits_all, kc, vc = _forward_cached(p, cfg, ids, kc, vc,
                                                 pos0, max_len,
                                                 return_all=True,
                                                 sharded=shd, aidx=aidx)
            logits = jnp.take_along_axis(
                logits_all, (true_len - 1)[:, None, None], axis=1)[:, 0]
            return pin_fwd(logits, kc, vc)

        def ring_admit_prefill(p, ids, kc, vc, true_len, pos0,
                               ring_logits, ring_kc, ring_vc, ring_idx,
                               aidx=None):
            """``admit_prefill`` that STAGES its results into the
            device-resident admission ring instead of returning them to
            host: the freshly prefilled rows scatter into ring rows
            ``ring_idx`` (host-chosen free slots) inside the SAME
            dispatch, and the next chunk program splices them into the
            live carry mid-chunk. Admission thus costs exactly its one
            counted prefill dispatch — the host-side ``_admit_row``
            scatter round-trip is gone."""
            self.trace_count += 1
            logits_all, kc, vc = _forward_cached(p, cfg, ids, kc, vc,
                                                 pos0, max_len,
                                                 return_all=True,
                                                 sharded=shd, aidx=aidx)
            logits = jnp.take_along_axis(
                logits_all, (true_len - 1)[:, None, None], axis=1)[:, 0]
            ring_logits = ring_logits.at[ring_idx].set(logits,
                                                       mode="drop")
            ring_kc = _row_scatter(ring_kc, kc, ring_idx)
            ring_vc = _row_scatter(ring_vc, vc, ring_idx)
            return pin_fwd(ring_logits, ring_kc, ring_vc)

        def ring_chunk_decode(p, logits0, kc, vc, pos0, keys0, done0,
                              eos0, temp0, aidx0, ring_logits, ring_kc,
                              ring_vc, ring_slot, ring_pos, ring_keys,
                              ring_eos, ring_temp, ring_aidx,
                              steps: int, do_sample: bool,
                              top_k, top_p):
            """``chunk_decode`` with a DEVICE-SIDE slot-refill prologue:
            before the T-step scan, ring rows staged by
            ``ring_admit_prefill`` scatter into the carry at their
            destination slots (``ring_slot``; empty ring rows carry the
            B sentinel and drop). Admitting mid-stream therefore never
            adds a dispatch boundary — steady state is ONE fused
            dispatch per chunk per replica regardless of admission rate.
            ``ring_slot=None`` (with every ring operand None) skips the
            prologue and is trace-identical to the plain chunk. Because
            admission can rewrite per-row eos/temp, BOTH are part of the
            returned carry here (the plain program treats them as
            read-only inputs). ``aidx0``/``ring_aidx``: per-row LoRA
            adapter indices — part of the returned carry for the same
            reason (admission rewrites a freed slot's tenant)."""
            self.trace_count += 1
            B = logits0.shape[0]
            logits, pos, keys, done = logits0, pos0, keys0, done0
            eos, temp, aidx = eos0, temp0, aidx0
            if ring_slot is not None:
                tgt = jnp.where(ring_slot >= 0, ring_slot, B)
                logits = logits.at[tgt].set(ring_logits, mode="drop")
                kc = _row_scatter(kc, ring_kc, tgt)
                vc = _row_scatter(vc, ring_vc, tgt)
                pos = pos.at[tgt].set(ring_pos, mode="drop")
                keys = keys.at[tgt].set(ring_keys, mode="drop")
                done = done.at[tgt].set(False, mode="drop")
                eos = eos.at[tgt].set(ring_eos, mode="drop")
                temp = temp.at[tgt].set(ring_temp, mode="drop")
                if aidx is not None:
                    aidx = aidx.at[tgt].set(ring_aidx, mode="drop")

            def pick(logits, keys, done):
                if do_sample:
                    kk = jax.vmap(jax.random.split)(keys)       # (B,2,2)
                    keys, subs = kk[:, 0], kk[:, 1]
                    flt = _filter_logits(logits, temp[:, None],
                                         top_k, top_p)
                    tok = jax.vmap(jax.random.categorical)(
                        subs, flt).astype(jnp.int32)
                else:
                    tok = jnp.argmax(logits, -1).astype(jnp.int32)
                tok = jnp.where(done, jnp.where(eos >= 0, eos, 0), tok)
                done = jnp.logical_or(done, tok == eos)
                return tok, keys, done

            def body(carry, _):
                logits, kc, vc, pos, keys, done = carry
                tok, keys, done = pick(logits, keys, done)
                logits, kc, vc = _forward_cached(p, cfg, tok[:, None], kc,
                                                 vc, pos, max_len,
                                                 sharded=shd, aidx=aidx)
                pos = jnp.minimum(pos + 1, max_len - 1)
                return (logits, kc, vc, pos, keys, done), tok

            (logits, kc, vc, pos, keys, done), toks = jax.lax.scan(
                body, (logits, kc, vc, pos, keys, done), None,
                length=steps)
            logits, kc, vc, pos, keys, done = pin_carry(
                logits, kc, vc, pos, keys, done)
            if shd:
                eos = srd.constrain(eos, "eos", head_major)
                temp = srd.constrain(temp, "temp", head_major)
                if aidx is not None:
                    aidx = srd.constrain(aidx, "adapter_idx", head_major)
            return (jnp.moveaxis(toks, 0, 1), logits, kc, vc, pos, keys,
                    done, eos, temp, aidx)

        self._prefill = self._counted(jax.jit(prefill), "decode.prefill")
        self._step = self._counted(jax.jit(step), "decode.step")
        self._fused_decode = self._counted(jax.jit(
            fused_decode,
            static_argnames=("steps", "do_sample", "use_eos", "top_k",
                             "top_p")), "decode.fused")
        self._chunk_decode = self._counted(jax.jit(
            chunk_decode,
            static_argnames=("steps", "do_sample", "top_k", "top_p")),
            "decode.chunk")
        # the same trace fn jitted under its own fault site: the serving
        # degradation ladder's per-token rung must stay dispatchable when
        # a plan is killing "decode.chunk"
        self._chunk_step = self._counted(jax.jit(
            chunk_decode,
            static_argnames=("steps", "do_sample", "top_k", "top_p")),
            "decode.chunk_step")
        self._admit_prefill = self._counted(jax.jit(admit_prefill),
                                            "decode.admit_prefill")
        # ring-admission variants: same fault sites as their plain
        # counterparts — the serving ladder, fault plans and the obs
        # span-vs-dispatch accounting see ONE logical site per role
        self._ring_chunk_decode = self._counted(jax.jit(
            ring_chunk_decode,
            static_argnames=("steps", "do_sample", "top_k", "top_p")),
            "decode.chunk")
        self._ring_chunk_step = self._counted(jax.jit(
            ring_chunk_decode,
            static_argnames=("steps", "do_sample", "top_k", "top_p")),
            "decode.chunk_step")
        self._ring_admit_prefill = self._counted(jax.jit(
            ring_admit_prefill), "decode.admit_prefill")

    def _counted(self, jitted, site="decode.dispatch"):
        """Count dispatches AND guard each one: the fault-injection hook
        fires first (an injected failure is a dispatch that never ran, so
        counters stay parity-comparable with the no-fault run), then the
        execution retries transient backend errors with backoff
        (resilient_call; FLAGS_resilience_retries/backoff_s). Retry
        events land in the in-flight generate's record.

        Observability (paddle_tpu/obs, FLAGS_obs_enabled): each executed
        dispatch records a span named after its fault site with the
        compiled program's cost_analysis/memory_analysis attached (one
        AOT lower+compile per site/signature, cached), and bumps the
        ``dispatches.<site>`` obs counter — so a trace's per-site span
        count is directly comparable with ``dispatch_count`` and the
        serving engine's asserted accounting. A dispatch that raises
        records an error span, which the accounting comparison excludes
        (the failed attempt never ran). Disabled: one boolean check."""
        import paddle_tpu.obs as obs
        from paddle_tpu.flags import flags as _flags
        from paddle_tpu.runtime.resilience import (fault_injector,
                                                   resilient_call)

        def attempt(args, kwargs):
            fault_injector.on_call(site)
            self.dispatch_count += 1
            if not obs.enabled():
                return jitted(*args, **kwargs)
            with obs.span(site, kind="dispatch") as sp:
                out = jitted(*args, **kwargs)
                if _flags.obs_cost_analysis:
                    cost = obs.dispatch_cost(
                        site, jitted, args, kwargs,
                        num_devices=(self.sharding.size if self.sharding
                                     else 1))
                    if cost:
                        sp.annotate(**cost)
            obs.metrics.counter(
                "dispatches." + site,
                "device dispatches executed at this site").inc()
            return out

        def call(*args, **kwargs):
            return resilient_call(attempt, args, kwargs, site=site,
                                  on_event=self._events.append)
        return call

    def _empty_cache(self, B, cfg: Optional[LlamaConfig] = None):
        cfg = self.cfg if cfg is None else cfg
        dt = jnp.dtype(cfg.dtype)
        from paddle_tpu.flags import flags
        if flags.decode_cache_layout not in ("stacked", "per_layer"):
            raise ValueError(
                f"decode_cache_layout must be 'stacked' or 'per_layer', "
                f"got {flags.decode_cache_layout!r}")
        head_major = cfg.num_attention_heads != cfg.num_key_value_heads

        def z(shape):
            if self.quant_kv:
                # int8 rows + per-row scale buffer (never on a mesh:
                # int8wk is refused typed at init)
                from paddle_tpu.quantization.kv_cache import quant_kv_zeros
                return quant_kv_zeros(shape, jnp)
            buf = jnp.zeros(shape, dt)
            if self.sharding is None:
                return buf
            # caches are BORN on the mesh — batch rows over dp, heads
            # over tp — and every downstream program pins them there:
            # the carry never exists gathered, not even at init
            return self.sharding.put_state_field("kc", buf, head_major)

        if head_major:
            per = (B, cfg.num_key_value_heads, self.max_len, cfg.head_dim)
        else:
            per = (B, self.max_len, cfg.num_key_value_heads, cfg.head_dim)
        if flags.decode_cache_layout == "stacked":
            shape = (cfg.num_hidden_layers,) + per
            return z(shape), z(shape)
        shape = per
        zeros = lambda: tuple(z(shape)  # noqa: E731
                              for _ in range(cfg.num_hidden_layers))
        return zeros(), zeros()

    # -- chunked resumable decode -----------------------------------------
    def init_decode_state(self, input_ids, eos_token_id=None,
                          temperature: float = 1.0, seed: int = 0,
                          draft_model=None,
                          num_speculative_tokens: Optional[int] = None,
                          draft_quant: Optional[str] = None,
                          adapter_idx=None,
                          speculative=None) -> DecodeState:
        """Prefill (one dispatch) and build the exportable loop carry for
        ``decode_chunk``. Whole-batch entry: every row starts from the
        same prompt tensor; the serving engine instead assembles mixed
        states row by row via its admission path. Per-row keys are
        ``split(PRNGKey(seed), B)`` — row i's sampled stream depends only
        on ``keys[i]``, never on its neighbours.

        With ``draft_model`` the carry is SPECULATIVE: it additionally
        holds the draft's prefilled caches (one extra counted dispatch),
        the per-row pending-token sentinel ``tok=-1`` and zeroed
        cumulative acceptance stats — ``decode_chunk`` then advances it
        by draft/verify/accept rounds instead of single steps.

        ``adapter_idx`` (B,) ints: per-row LoRA adapter routing (the
        params must carry ``lora.*`` stacks — see serving/lora); the
        PREFILL runs adapter-routed too, so each row's cached prompt KV
        matches its dense-merged tenant model. ``speculative`` (B,)
        bools (speculative carries only): rows set False decode
        verify-free inside the same speculative chunk program."""
        import jax.random as jrandom

        ids = jnp.asarray(np.asarray(input_ids))
        B, S = ids.shape
        aidx = None
        if adapter_idx is not None:
            aidx = jnp.asarray(np.asarray(adapter_idx), jnp.int32)
        kc, vc = self._empty_cache(B)
        if aidx is None:
            logits, kc, vc = self._prefill(self.params, ids, kc, vc)
        else:
            # adapter-routed prefill: the bucketed admission program with
            # every row at its full length (per-row aidx is its contract)
            logits, kc, vc = self._admit_prefill(
                self.params, ids, kc, vc,
                jnp.full((B,), S, jnp.int32), jnp.zeros((B,), jnp.int32),
                aidx)
        eos_n = _normalize_eos(eos_token_id)
        kw = {"adapter_idx": aidx}
        if speculative is not None and draft_model is None:
            raise ValueError("speculative=(B,) row mask requires a "
                             "draft_model")
        if draft_model is not None:
            from paddle_tpu.flags import flags
            K = int(num_speculative_tokens
                    if num_speculative_tokens is not None
                    else flags.decode_speculative_tokens)
            if K < 1:
                raise ValueError(
                    f"num_speculative_tokens must be >= 1, got {K}")
            eng = self._spec_engine(draft_model, draft_quant)
            dkc, dvc = self._empty_cache(B, eng["cfg"])
            _, dkc, dvc = eng["prefill"](eng["params"], ids, dkc, dvc)
            kw.update(dkc=dkc, dvc=dvc,
                      tok=jnp.full((B,), -1, jnp.int32),
                      spec_rounds=jnp.zeros((B,), jnp.int32),
                      spec_accepted=jnp.zeros((B,), jnp.int32),
                      spec={"ekey": eng["ekey"], "K": K})
            if speculative is not None:
                kw["spec_on"] = jnp.asarray(np.asarray(speculative),
                                            jnp.bool_)
        elif num_speculative_tokens is not None:
            raise ValueError("num_speculative_tokens requires a "
                             "draft_model")
        elif draft_quant is not None:
            raise ValueError("draft_quant requires a draft_model")
        state = DecodeState(
            logits=logits, kc=kc, vc=vc,
            pos=jnp.full((B,), S, jnp.int32),
            keys=jnp.asarray(jrandom.split(jrandom.PRNGKey(seed), B),
                             jnp.uint32),
            done=jnp.zeros((B,), jnp.bool_),
            eos=jnp.full((B,), -1 if eos_n is None else int(eos_n),
                         jnp.int32),
            temp=jnp.full((B,), float(temperature), jnp.float32), **kw)
        if self.sharding is not None:
            # per-row fields join the mesh (batch over dp); logits and
            # caches already came out of the prefill pinned
            state = self.sharding.put_state(state, self._head_major)
        return state

    def decode_chunk(self, state: DecodeState, num_tokens: int,
                     do_sample: bool = False, top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     K: Optional[int] = None):
        """Advance the loop carry by ``num_tokens`` steps in ONE device
        dispatch; returns ``(tokens (B, num_tokens), new_state)``.
        Chaining chunks totalling N steps emits the same greedy tokens,
        bit-exactly, as one run-to-completion ``generate`` of N — the
        property continuous batching rides on (a request's output can't
        depend on how admission sliced its decode into dispatches).

        A SPECULATIVE carry (``init_decode_state(draft_model=...)``)
        routes to the chunked speculative program instead:
        ``num_tokens`` counts verify ROUNDS (each committing 1..K+1
        tokens), the returned token buffer is
        ``(B, num_tokens*(K+1)+1)`` and the new state's ``nv`` holds
        each row's valid count, at least ``num_tokens`` (slice
        ``toks[i, :nv[i]]``; everything past ``num_tokens`` is
        acceptance overflow — the per-dispatch token yield that IS the
        speculative dispatch reduction). ``K`` overrides the carry's
        draft length for THIS chunk only (the adaptive-K serving hook:
        K is a static, so each distinct value compiles once and the
        engine steers between cached programs; greedy output stays
        bit-exact for any K schedule)."""
        if state.dkc is not None:
            eng = self._spec_engines[state.spec["ekey"]]
            K = int(state.spec["K"]) if K is None else int(K)
            (toks, nv, logits, kc, vc, dkc, dvc, pos, keys, done, eos,
             temp, tok, sr, sa, aidx, son) = eng["chunk"](
                self.params, eng["params"], state.logits, state.kc,
                state.vc, state.dkc, state.dvc, state.pos, state.keys,
                state.done, state.eos, state.temp, state.tok,
                state.spec_rounds, state.spec_accepted,
                state.adapter_idx, state.spec_on,
                None, None, None, None, None,      # no admission ring
                None, None, None, None, None, None, None,
                steps=int(num_tokens), K=K, do_sample=bool(do_sample),
                top_k=None if top_k is None else int(top_k),
                top_p=None if top_p is None else float(top_p))
            return toks, dataclasses.replace(
                state, logits=logits, kc=kc, vc=vc, dkc=dkc, dvc=dvc,
                pos=pos, keys=keys, done=done, eos=eos, temp=temp,
                tok=tok, spec_rounds=sr, spec_accepted=sa, nv=nv,
                adapter_idx=aidx, spec_on=son,
                steps_done=state.steps_done + int(num_tokens))
        toks, logits, kc, vc, pos, keys, done = self._chunk_decode(
            self.params, state.logits, state.kc, state.vc, state.pos,
            state.keys, state.done, state.eos, state.temp,
            state.adapter_idx,
            steps=int(num_tokens), do_sample=bool(do_sample),
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p))
        return toks, dataclasses.replace(
            state, logits=logits, kc=kc, vc=vc, pos=pos, keys=keys,
            done=done, steps_done=state.steps_done + int(num_tokens))

    def _generate_chunked(self, ids, max_new, eos_norm, do_sample,
                          temperature, top_k, top_p, seed, chunk_size):
        """Chunked resumable decode: prefill + ceil(max_new/T) chunk
        dispatches. Greedy is bit-exact with the one-dispatch fused path
        (identical pick/forward stream); sampling draws from PER-ROW key
        streams — distribution-preserving and row-independent (the
        admission contract), but a different stream than the fused
        path's single shared key. Retry/degradation events of EVERY
        chunk dispatch accumulate into the one generate record."""
        T = int(chunk_size)
        if T < 1:
            raise ValueError(f"chunk_size must be >= 1, got {T}")
        state = self.init_decode_state(ids, eos_token_id=eos_norm,
                                       temperature=temperature, seed=seed)
        out, got = [], 0
        while got < max_new:
            toks, state = self.decode_chunk(
                state, min(T, max_new - got), do_sample=do_sample,
                top_k=top_k, top_p=top_p)
            out.append(np.asarray(toks))
            got += out[-1].shape[1]
            if eos_norm is not None and bool(np.asarray(state.done).all()):
                break
        return np.concatenate(out, axis=1)

    def _generate_chunked_spec(self, ids, max_new, eos_norm, do_sample,
                               temperature, top_k, top_p, seed,
                               draft_model, draft_quant, K, chunk_size):
        """Chunked SPECULATIVE decode: prefill(target) + prefill(draft)
        + roughly ``ceil(max_new/(T*(1+a)))`` chunk dispatches at
        acceptance ``a`` — each dispatch runs T verify rounds and
        commits a per-row variable ``>= T`` tokens (``decode_chunk``'s
        ``nv`` contract), so the speculative K-fold dispatch reduction
        composes with chunk re-entry. Greedy tokens are bit-exact with
        the one-dispatch fused speculative path for every ``chunk_size``
        slicing (the chunk-slicing-invariance contract); sampling draws
        from PER-ROW key streams like the plain chunked path.
        Acceptance stats accumulate per row in the CARRY across chunk
        re-entries, so ``last_spec_stats`` reports the CUMULATIVE
        request totals — never stale, never last-chunk-only."""
        T = int(chunk_size)
        if T < 1:
            raise ValueError(f"chunk_size must be >= 1, got {T}")
        state = self.init_decode_state(
            ids, eos_token_id=eos_norm, temperature=temperature,
            seed=seed, draft_model=draft_model, num_speculative_tokens=K,
            draft_quant=draft_quant)
        B = ids.shape[0]
        buf = np.zeros((B, max_new), np.int32)
        got = np.zeros((B,), np.int64)
        while True:
            toks, state = self.decode_chunk(
                state, T, do_sample=do_sample, top_k=top_k, top_p=top_p)
            toks_h, nv_h = np.asarray(toks), np.asarray(state.nv)
            for b in range(B):
                n = min(int(nv_h[b]), int(max_new - got[b]))
                if n > 0:
                    buf[b, got[b]:got[b] + n] = toks_h[b, :n]
                    got[b] += n
            if bool((got >= max_new).all()):
                break
            done_h = np.asarray(state.done)
            if eos_norm is not None and bool(done_h.all()):
                # like the fused path's buffer, post-eos columns hold
                # the eos fill (the trim contract both paths share)
                for b in range(B):
                    buf[b, got[b]:] = int(eos_norm)
                break
            full = got >= max_new
            if bool(full.any()):
                # budget-filled rows freeze (like the engine retiring a
                # slot): they stop accumulating stat counters while
                # their batch neighbours finish
                state = dataclasses.replace(
                    state, done=jnp.logical_or(state.done,
                                               jnp.asarray(full)))
        self._record_spec_stats(
            int(np.asarray(state.spec_rounds).sum()),
            int(np.asarray(state.spec_accepted).sum()), K)
        return buf

    # -- speculative decoding ---------------------------------------------
    def _spec_engine(self, draft_model, draft_quant: Optional[str] = None):
        """Prepare (and cache) the draft side of speculative decoding.
        ``draft_model``: a LlamaForCausalLM with the same vocab (its
        weights are snapshotted exactly like the target's), or 'skip:N'
        — a layer-skip view that reuses the TARGET's first N layers plus
        its final norm/head as the draft, zero extra weights.
        ``draft_quant``: 'int8w' quantizes the DRAFT's weights only —
        the target keeps its own dtype, the verify pass stays exact, so
        a wrong draft only costs acceptance length, never correctness."""
        import dataclasses
        cfg, max_len = self.cfg, self.max_len
        if draft_quant not in (None, "int8w"):
            raise ValueError(
                f"draft_quant must be None or 'int8w', got {draft_quant!r}")
        if isinstance(draft_model, str):
            if not draft_model.startswith("skip:"):
                raise ValueError(
                    "draft_model must be a LlamaForCausalLM or 'skip:N' "
                    f"(layer-skip view of the target), got {draft_model!r}")
            if draft_quant is not None:
                raise ValueError(
                    "draft_quant does not compose with 'skip:N' drafts: "
                    "the layer-skip view reuses the TARGET's params, so "
                    "quantize the target (quant='int8w') instead")
            n = int(draft_model.split(":", 1)[1])
            if not 0 < n < cfg.num_hidden_layers:
                raise ValueError(
                    f"'skip:{n}' needs 0 < N < num_hidden_layers "
                    f"({cfg.num_hidden_layers})")
            ekey = ("skip", n)
        else:
            ekey = ("model", id(draft_model), draft_quant)
        eng = self._spec_engines.get(ekey)
        if eng is not None:
            return eng
        shd = self.sharding if self.sharding is not None else False
        srd, head_major = self.sharding, self._head_major
        if isinstance(draft_model, str):
            dcfg = dataclasses.replace(cfg, num_hidden_layers=n)
            dp = self.params
        else:
            dcfg = draft_model.config
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab_size {dcfg.vocab_size} != target "
                    f"vocab_size {cfg.vocab_size}")
            dp = _build_params(draft_model, max_len,
                               "int8" if draft_quant else self.weight_dtype)
            if srd is not None:
                dp = srd.shard_params(dp)

        def draft_prefill(dp_, ids, dkc, dvc):
            self.trace_count += 1
            return _forward_cached(dp_, dcfg, ids, dkc, dvc, 0, max_len,
                                   sharded=shd)

        def spec_round(p, dp_, tok, pos, key, done, kc, vc, dkc, dvc,
                       eos_id, temperature, K: int, do_sample: bool,
                       use_eos: bool, top_k, top_p):
            self.trace_count += 1
            return _spec_round(p, dp_, cfg, dcfg, tok, pos, key, done, kc,
                               vc, dkc, dvc, eos_id, temperature, max_len,
                               K=K, do_sample=do_sample, use_eos=use_eos,
                               top_k=top_k, top_p=top_p, sharded=shd)

        def spec_decode(p, dp_, logits0, kc, vc, dkc, dvc, pos0, key0,
                        done0, eos_id, temperature, max_new: int, K: int,
                        do_sample: bool, use_eos: bool, top_k, top_p):
            """Speculative decode as ONE device program: a lax.while_loop
            of draft-propose/verify/accept rounds, each round committing
            a variable 1..K+1 tokens per row (scattered into the output
            buffer at per-row offsets), until every row has its
            ``max_new`` tokens. Also returns (rounds, accepted) totals
            over live rows for acceptance-length reporting."""
            self.trace_count += 1
            B = logits0.shape[0]
            if do_sample:
                key0, sub0 = jax.random.split(key0)
                tok0 = _sample_from(logits0, sub0, temperature, top_k,
                                    top_p).astype(jnp.int32)
            else:
                tok0 = jnp.argmax(logits0, -1).astype(jnp.int32)
            done = done0
            if use_eos:
                tok0 = jnp.where(done, eos_id, tok0)
                done = jnp.logical_or(done, tok0 == eos_id)
            buf = jnp.zeros((B, max_new), jnp.int32).at[:, 0].set(tok0)
            pos = jnp.broadcast_to(pos0, (B,)).astype(jnp.int32)
            rows = jnp.arange(B)[:, None]
            jidx = jnp.arange(K + 1)[None, :]

            def cond(c):
                return jnp.any(c[1] - pos0 + 1 < max_new)

            def body(c):
                buf, pos, tok, key, done, kc, vc, dkc, dvc, sr, sa = c
                active = (pos - pos0 + 1) < max_new
                live = jnp.logical_and(active, jnp.logical_not(done))
                (emit, a, tok2, key, done2, kc, vc, dkc,
                 dvc) = _spec_round(p, dp_, cfg, dcfg, tok, pos, key,
                                    done, kc, vc, dkc, dvc, eos_id,
                                    temperature, max_len, K=K,
                                    do_sample=do_sample, use_eos=use_eos,
                                    top_k=top_k, top_p=top_p, sharded=shd)
                sr = sr + jnp.sum(live.astype(jnp.int32))
                sa = sa + jnp.sum(jnp.where(live, a, 0).astype(jnp.int32))
                idx = (pos - pos0 + 1)[:, None] + jidx
                valid = jnp.logical_and(jidx <= a[:, None],
                                        active[:, None])
                idx = jnp.where(valid, idx, max_new)  # OOB -> dropped
                buf = buf.at[rows, idx].set(emit, mode="drop")
                pos = jnp.where(active, pos + a + 1, pos)
                tok = jnp.where(active, tok2, tok)
                done = jnp.where(active, done2, done)
                return (buf, pos, tok, key, done, kc, vc, dkc, dvc,
                        sr, sa)

            z = jnp.asarray(0, jnp.int32)
            out = jax.lax.while_loop(
                cond, body,
                (buf, pos, tok0, key0, done, kc, vc, dkc, dvc, z, z))
            return out[0], out[9], out[10]

        def pin_spec_carry(logits, kc, vc, dkc, dvc, pos, keys, done,
                           eos, temp, tok, sr, sa, aidx=None, son=None):
            if srd is None:
                return (logits, kc, vc, dkc, dvc, pos, keys, done, eos,
                        temp, tok, sr, sa, aidx, son)
            c = lambda x, f: srd.constrain(x, f, head_major)  # noqa: E731
            return (c(logits, "logits"), c(kc, "kc"), c(vc, "vc"),
                    c(dkc, "dkc"), c(dvc, "dvc"), c(pos, "pos"),
                    c(keys, "keys"), c(done, "done"), c(eos, "eos"),
                    c(temp, "temp"), c(tok, "tok"), c(sr, "spec_rounds"),
                    c(sa, "spec_accepted"),
                    None if aidx is None else c(aidx, "adapter_idx"),
                    None if son is None else c(son, "spec_on"))

        def spec_chunk(p, dp_, logits0, kc, vc, dkc, dvc, pos0, keys0,
                       done0, eos0, temp0, tok0, sr0, sa0, aidx0, son0,
                       ring_logits, ring_kc, ring_vc, ring_dkc, ring_dvc,
                       ring_slot, ring_pos, ring_keys, ring_eos,
                       ring_temp, ring_aidx, ring_son,
                       steps: int, K: int, do_sample: bool,
                       top_k, top_p):
            """CHUNKED speculative decode: exactly ``steps=T``
            draft/verify/accept rounds (``_spec_round_rows`` — per-row
            keys/eos/temps, the serving carry contract) as one
            re-enterable dispatch. A plain chunk buys T tokens per row
            for T forwards; here the SAME T sequential rounds commit a
            variable 1..K+1 tokens per row each — ~``T*(1+a)`` tokens
            per dispatch at acceptance ``a``, which IS the K-fold
            dispatch reduction, kept intact across chunk boundaries.
            The output buffer is ``(B, T*(K+1)+1)`` (fresh-pick column
            plus T rounds) with a per-row valid count ``nv`` in
            ``[T, T*(K+1)+1]`` (harvest slices ``buf[i, :nv[i]]``;
            nothing is thrown away). Chunk-slicing
            invariance holds because the per-row ROUND sequence is
            continuous across chunk boundaries — no round is re-run, no
            committed token is dropped, so every T slicing replays the
            fused path's exact stream (greedy AND per-row-keyed
            sampled). The carry's pending token ``tok`` (-1 = pick
            fresh from ``logits``, the state of an admitted row) is
            what makes re-entry exact: unlike the plain chunk, the last
            committed token of a round is not yet in the caches when
            the chunk ends. Acceptance stats accumulate PER ROW in the
            carry (``sr``/``sa``), reset by admission — chunk re-entry
            can neither lose rounds nor double-report them. The ring
            prologue is the same device-side slot refill as the plain
            ring chunk (plus the draft caches and spec-field resets).
            ``aidx0``/``son0`` (+ their ring columns): per-row LoRA
            adapter routing and per-row speculative enable — both ride
            the carry so admission can retarget a freed slot's tenant or
            demote it to verify-free decode without a new program."""
            self.trace_count += 1
            T = int(steps)
            B = logits0.shape[0]
            logits, pos, keys, done = logits0, pos0, keys0, done0
            eos, temp, tok, sr, sa = eos0, temp0, tok0, sr0, sa0
            aidx, son = aidx0, son0
            if ring_slot is not None:
                tgt = jnp.where(ring_slot >= 0, ring_slot, B)
                logits = logits.at[tgt].set(ring_logits, mode="drop")
                kc = _row_scatter(kc, ring_kc, tgt)
                vc = _row_scatter(vc, ring_vc, tgt)
                dkc = _row_scatter(dkc, ring_dkc, tgt)
                dvc = _row_scatter(dvc, ring_dvc, tgt)
                pos = pos.at[tgt].set(ring_pos, mode="drop")
                keys = keys.at[tgt].set(ring_keys, mode="drop")
                done = done.at[tgt].set(False, mode="drop")
                eos = eos.at[tgt].set(ring_eos, mode="drop")
                temp = temp.at[tgt].set(ring_temp, mode="drop")
                tok = tok.at[tgt].set(-1, mode="drop")
                sr = sr.at[tgt].set(0, mode="drop")
                sa = sa.at[tgt].set(0, mode="drop")
                if aidx is not None:
                    aidx = aidx.at[tgt].set(ring_aidx, mode="drop")
                if son is not None:
                    son = son.at[tgt].set(ring_son, mode="drop")
            fill = jnp.where(eos >= 0, eos, 0)
            need = tok < 0           # no pending token: fresh pick
            if do_sample:
                kk = jax.vmap(jax.random.split)(keys)
                flt = _filter_logits(logits, temp[:, None], top_k, top_p)
                cand = jax.vmap(jax.random.categorical)(
                    kk[:, 1], flt).astype(jnp.int32)
                # only picked rows consume their key split
                keys = jnp.where(need[:, None], kk[:, 0], keys)
            else:
                cand = jnp.argmax(logits, -1).astype(jnp.int32)
            cand = jnp.where(done, fill, cand)
            done = jnp.where(need, jnp.logical_or(done, cand == eos),
                             done)
            tok = jnp.where(need, cand, tok)
            # fresh pick (1) + T rounds of at most K+1 commits each
            W = T * (K + 1) + 1
            buf = jnp.zeros((B, W), jnp.int32)
            buf = buf.at[:, 0].set(jnp.where(need, tok, 0))
            cnt = jnp.where(need, 1, 0).astype(jnp.int32)
            rows = jnp.arange(B)[:, None]
            jidx = jnp.arange(K + 1)[None, :]

            def body(_, c):
                (buf, cnt, logits, tok, pos, keys, done, kc, vc, dkc,
                 dvc, sr, sa) = c
                live = jnp.logical_not(done)
                if son is not None:
                    # spec-off rows advance 1/round verify-free: their
                    # rounds never enter the acceptance stats
                    live = jnp.logical_and(live, son)
                (emit, a, tok2, lg2, keys2, done2, kc, vc, dkc,
                 dvc) = _spec_round_rows(
                    p, dp_, cfg, dcfg, tok, pos, keys, done, kc, vc,
                    dkc, dvc, eos, temp, max_len, K=K,
                    do_sample=do_sample, top_k=top_k, top_p=top_p,
                    sharded=shd, aidx=aidx, spec_on=son)
                idx = cnt[:, None] + jidx
                valid = jidx <= a[:, None]
                idx = jnp.where(valid, idx, W)         # OOB -> dropped
                buf = buf.at[rows, idx].set(emit, mode="drop")
                sr = sr + jnp.where(live, 1, 0).astype(jnp.int32)
                sa = sa + jnp.where(live, a, 0).astype(jnp.int32)
                cnt = cnt + a + 1
                # rows past their budget keep their (discarded) writes
                # clamped where a full round still fits the cache
                pos = jnp.minimum(pos + a + 1, max_len - K - 1)
                return (buf, cnt, lg2, tok2, pos, keys2, done2, kc, vc,
                        dkc, dvc, sr, sa)

            (buf, cnt, logits, tok, pos, keys, done, kc, vc, dkc, dvc,
             sr, sa) = jax.lax.fori_loop(
                0, T, body, (buf, cnt, logits, tok, pos, keys, done, kc,
                             vc, dkc, dvc, sr, sa))
            (logits, kc, vc, dkc, dvc, pos, keys, done, eos, temp, tok,
             sr, sa, aidx, son) = pin_spec_carry(
                logits, kc, vc, dkc, dvc, pos, keys, done, eos, temp,
                tok, sr, sa, aidx, son)
            return (buf, cnt, logits, kc, vc, dkc, dvc, pos, keys, done,
                    eos, temp, tok, sr, sa, aidx, son)

        def spec_demote(p, logits0, kc, vc, tok, pos, aidx=None):
            """One-time speculative->chunked demotion of a live carry:
            the pending token (the one speculative re-entry would have
            verified) is committed to the target caches with a single
            masked forward, yielding PICK-READY logits and pos+1 — after
            which the plain chunk program serves the state and the draft
            caches are dropped. Rows with no pending token (tok < 0)
            keep their logits; their placeholder write at ``pos`` is
            overwritten by the next real write at the same offset before
            attention could unmask it."""
            self.trace_count += 1
            need = tok >= 0
            t = jnp.where(need, tok, 0)
            lg, kc, vc = _forward_cached(p, cfg, t[:, None], kc, vc, pos,
                                         max_len, sharded=shd, aidx=aidx)
            logits = jnp.where(need[:, None], lg, logits0)
            pos = jnp.where(need, jnp.minimum(pos + 1, max_len - 1), pos)
            if srd is not None:
                logits = srd.constrain(logits, "logits", head_major)
                kc = srd.constrain(kc, "kc", head_major)
                vc = srd.constrain(vc, "vc", head_major)
                pos = srd.constrain(pos, "pos", head_major)
            return logits, kc, vc, pos

        def ring_draft_prefill(dp_, ids, dkc, dvc, ring_dkc, ring_dvc,
                               ring_idx):
            """Draft-side admission prefill, staged straight into the
            ring's draft caches (one counted dispatch per admission
            group — the speculative analog of ``ring_admit_prefill``)."""
            self.trace_count += 1
            _, dkc, dvc = _forward_cached(dp_, dcfg, ids, dkc, dvc, 0,
                                          max_len, sharded=shd)
            ring_dkc = _row_scatter(ring_dkc, dkc, ring_idx)
            ring_dvc = _row_scatter(ring_dvc, dvc, ring_idx)
            if srd is not None:
                ring_dkc = srd.constrain(ring_dkc, "dkc", head_major)
                ring_dvc = srd.constrain(ring_dvc, "dvc", head_major)
            return ring_dkc, ring_dvc

        eng = {
            "cfg": dcfg, "params": dp, "ekey": ekey,
            "prefill": self._counted(jax.jit(draft_prefill),
                                     "spec.prefill"),
            "round": self._counted(jax.jit(spec_round, static_argnames=(
                "K", "do_sample", "use_eos", "top_k", "top_p")),
                "spec.round"),
            "decode": self._counted(jax.jit(spec_decode, static_argnames=(
                "max_new", "K", "do_sample", "use_eos", "top_k",
                "top_p")), "spec.decode"),
            # chunked speculative decode dispatches under the SAME fault
            # site as the plain chunk: to the serving ladder and fault
            # plans there is one "the chunk dispatch" site, whatever
            # program backs it
            "chunk": self._counted(jax.jit(spec_chunk, static_argnames=(
                "steps", "K", "do_sample", "top_k", "top_p")),
                "decode.chunk"),
            "chunk_step": self._counted(jax.jit(
                spec_chunk, static_argnames=(
                    "steps", "K", "do_sample", "top_k", "top_p")),
                "decode.chunk_step"),
            "demote": self._counted(jax.jit(spec_demote),
                                    "decode.spec_demote"),
            "ring_prefill": self._counted(jax.jit(ring_draft_prefill),
                                          "spec.prefill"),
        }
        self._spec_engines[ekey] = eng
        return eng

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 seed: int = 0, draft_model=None,
                 num_speculative_tokens: Optional[int] = None,
                 draft_quant: Optional[str] = None,
                 chunk_size: Optional[int] = None) -> np.ndarray:
        """Decode. input_ids: (B, S) ints. Returns (B, S + new).

        Greedy by default; ``do_sample=True`` draws from the
        temperature/top-k/top-p-filtered distribution (the reference
        generation-op sampling surface). EVERY mode — greedy, greedy+eos,
        sampled, sampled+eos — runs the whole token loop as one fused
        device dispatch (``fused_decode``). With ``draft_model`` (a
        smaller LlamaForCausalLM or ``'skip:N'``) the loop runs
        SPECULATIVELY: ``num_speculative_tokens`` (default
        ``flags.decode_speculative_tokens``) draft proposals per target
        verify, still one decode dispatch after the two prefills, with
        the target distribution preserved exactly (greedy: exact-match
        accept; sampling: Leviathan rejection rule).
        ``draft_quant='int8w'`` additionally quantizes the DRAFT
        model's weights to int8 (target untouched — the verify pass
        stays exact, so a worse draft only costs acceptance length). ``eos_token_id``
        accepts ``None`` or any negative id (the bundles' ``-1``
        convention) as "no eos". Set the ``decode_fallback`` flag or
        ``PADDLE_TPU_DECODE_FALLBACK=1`` to debug against the per-token
        (or per-speculative-round) host loop, which emits the same
        tokens for a fixed seed.

        ``chunk_size=T`` runs the SAME fused loop as a chain of
        re-enterable T-step dispatches (``init_decode_state`` /
        ``decode_chunk`` — the continuous-batching serving substrate,
        ``paddle_tpu/serving``): greedy output is bit-exact with the
        one-dispatch path; sampling switches to per-row key streams
        (``split(PRNGKey(seed), B)``) so each row's draw is independent
        of its batch neighbours — distribution-preserving, different
        stream. The resilience record accumulates the retry/degradation
        events of every chunk dispatch of the call.

        Dispatch failures walk the degradation ladder automatically
        (``FLAGS_resilience_auto_degrade``): speculative falls back to
        fused plain decode (chunked likewise), fused to the per-token
        loop. Greedy levels
        are bit-exact with each other, so degraded greedy output ==
        the no-fault output; sampled levels preserve the distribution
        but consume the RNG stream differently. The returned array
        carries the retry/degradation record (``.resilience``); a run
        whose every rung fails raises a typed ``DecodeFailedError``.
        """
        from paddle_tpu.flags import flags as _flags
        from paddle_tpu.runtime.resilience import (
            DecodeFailedError, DegradationEvent, GenerateResult,
            classify_error, record_event)

        eos_token_id = _normalize_eos(eos_token_id)
        ids = jnp.asarray(np.asarray(input_ids))
        B, S = ids.shape
        # admission hook: batch-conditional faults (the injected
        # OOM-above-batch-B class) fire here, BEFORE any device work —
        # steady-state RESOURCE_EXHAUSTED is fatal and propagates typed
        from paddle_tpu.runtime.resilience import fault_injector
        fault_injector.on_call("decode.generate", batch=B)
        if S + max_new_tokens > self.max_len:
            raise ValueError(f"prompt {S} + {max_new_tokens} new tokens "
                             f"exceeds max_len {self.max_len}")
        if max_new_tokens <= 0:
            return np.asarray(ids)
        fallback = decode_fallback_active()
        ladder = []
        if draft_model is not None:
            # speculative decode runs on a mesh now: the per-row uneven
            # cache advance lowers through shard_map (_cache_update) and
            # is parity-tested bit-exact on the virtual CPU mesh — the
            # former SpeculativeMeshError refusal survives only on the
            # bundle-export surface
            from paddle_tpu.flags import flags
            K = int(num_speculative_tokens
                    if num_speculative_tokens is not None
                    else flags.decode_speculative_tokens)
            if K < 1:
                raise ValueError(
                    f"num_speculative_tokens must be >= 1, got {K}")
            if S + max_new_tokens + K > self.max_len:
                raise ValueError(
                    f"speculative decode can overshoot the cache by up to "
                    f"K={K} slots: prompt {S} + {max_new_tokens} new + {K} "
                    f"exceeds max_len {self.max_len}; build the decoder "
                    f"with more slack")
            eng = self._spec_engine(draft_model, draft_quant)
            if chunk_size is not None and not fallback:
                ladder.append(("speculative",
                               lambda: self._generate_chunked_spec(
                                   ids, max_new_tokens, eos_token_id,
                                   do_sample, temperature, top_k, top_p,
                                   seed, draft_model, draft_quant, K,
                                   chunk_size)))
            else:
                gen = (self._generate_speculative_fallback if fallback
                       else self._generate_speculative)
                ladder.append(("speculative", lambda: gen(
                    ids, max_new_tokens, eos_token_id, do_sample,
                    temperature, top_k, top_p, seed, eng, K)))
        elif num_speculative_tokens is not None:
            raise ValueError("num_speculative_tokens requires a "
                             "draft_model")
        elif draft_quant is not None:
            raise ValueError("draft_quant requires a draft_model")
        if chunk_size is not None:
            if not fallback:
                ladder.append(("chunked", lambda: self._generate_chunked(
                    ids, max_new_tokens, eos_token_id, do_sample,
                    temperature, top_k, top_p, seed, chunk_size)))
        if not fallback:
            ladder.append(("fused", lambda: self._generate_fused(
                ids, max_new_tokens, eos_token_id, do_sample, temperature,
                top_k, top_p, seed)))
        ladder.append(("per_token", lambda: self._generate_per_token(
            ids, max_new_tokens, eos_token_id, do_sample, temperature,
            top_k, top_p, seed)))

        self._events = []
        self.last_resilience = None
        # cleared BEFORE the ladder runs: a speculative rung that fails
        # and degrades mid-request must not leave a previous generate's
        # acceptance stats looking like this one's (and a non-speculative
        # generate must never report any) — every dispatch of this call,
        # however many chunks it takes, reports into this one record
        self.last_spec_stats = None
        degradations = []
        toks, level = None, None
        for li, (name, run) in enumerate(ladder):
            try:
                toks = run()
                level = name
                break
            except Exception as e:
                if classify_error(e) != "transient":
                    raise     # fatal (programming/capacity error): as-is
                if (li == len(ladder) - 1
                        or not _flags.resilience_auto_degrade):
                    # the ladder is exhausted and the caller may die on
                    # this: dump the crash flight recorder (last spans +
                    # resilience timeline + metrics) BEFORE raising
                    import paddle_tpu.obs as obs
                    obs.record_crash(
                        "decode.ladder_exhausted", error=e,
                        extra={"site": "decode.generate",
                               "failed_level": name,
                               "degradations": [d.as_dict()
                                                for d in degradations]})
                    raise DecodeFailedError(
                        f"decode failed at ladder level {name!r} with no "
                        f"further fallback: {str(e)[:300]}",
                        events=list(self._events), last_error=e) from e
                ev = DegradationEvent(
                    site="decode.generate", from_level=name,
                    to_level=ladder[li + 1][0],
                    error_class=type(e).__name__, error=str(e)[:300])
                record_event(ev)
                self._events.append(ev)
                degradations.append(ev)
        toks = np.asarray(toks)
        if eos_token_id is not None:
            toks = _trim_after_eos(toks, int(eos_token_id))
        out = np.concatenate(
            [np.asarray(ids), toks.astype(np.asarray(ids).dtype)], axis=1)
        self.last_resilience = {
            "level": level,
            "requested_level": ladder[0][0],
            "retries": sum(1 for e in self._events
                           if getattr(e, "kind", "") == "retry"),
            "degradations": [e.as_dict() for e in degradations],
            "events": [e.as_dict() for e in self._events],
        }
        return GenerateResult.wrap(out, self.last_resilience)

    def _generate_fused(self, ids, max_new_tokens, eos_token_id, do_sample,
                        temperature, top_k, top_p, seed):
        """Fused plain decode: prefill + ONE scan-loop dispatch. Returns
        the untrimmed (B, max_new) token buffer."""
        import jax.random as jrandom

        B, S = ids.shape
        kc, vc = self._empty_cache(B)
        logits, kc, vc = self._prefill(self.params, ids, kc, vc)
        # raw uint32 key: same threefry stream as the fallback's typed key
        # (and a plain array, so AOT bundles export the identical function)
        key = jrandom.PRNGKey(seed)
        done = jnp.zeros((B,), jnp.bool_)
        eos = jnp.asarray(-1 if eos_token_id is None else int(eos_token_id),
                          jnp.int32)
        return self._fused_decode(
            self.params, logits, kc, vc, jnp.asarray(S, jnp.int32), key,
            done, eos, jnp.asarray(float(temperature), jnp.float32),
            steps=max_new_tokens - 1, do_sample=bool(do_sample),
            use_eos=eos_token_id is not None,
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p))

    def _generate_speculative(self, ids, max_new, eos_norm, do_sample,
                              temperature, top_k, top_p, seed, eng, K):
        """Fused speculative decode: prefill(target) + prefill(draft) +
        ONE while-loop dispatch. Records acceptance stats into
        ``last_spec_stats``."""
        import jax.random as jrandom

        B, _ = ids.shape
        kc, vc = self._empty_cache(B)
        dkc, dvc = self._empty_cache(B, eng["cfg"])
        logits, kc, vc = self._prefill(self.params, ids, kc, vc)
        _, dkc, dvc = eng["prefill"](eng["params"], ids, dkc, dvc)
        key = jrandom.PRNGKey(seed)
        done0 = jnp.zeros((B,), jnp.bool_)
        eos = jnp.asarray(-1 if eos_norm is None else int(eos_norm),
                          jnp.int32)
        buf, sr, sa = eng["decode"](
            self.params, eng["params"], logits, kc, vc, dkc, dvc,
            jnp.asarray(ids.shape[1], jnp.int32), key, done0, eos,
            jnp.asarray(float(temperature), jnp.float32),
            max_new=int(max_new), K=int(K), do_sample=bool(do_sample),
            use_eos=eos_norm is not None,
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p))
        self._record_spec_stats(int(sr), int(sa), K)
        return np.asarray(buf)

    def _generate_speculative_fallback(self, ids, max_new, eos_norm,
                                       do_sample, temperature, top_k,
                                       top_p, seed, eng, K):
        """Per-round host loop (the debugging escape hatch): one jitted
        ``_spec_round`` dispatch per draft-and-verify round plus a host
        sync each round — the parity reference the fused while-loop is
        tested against (identical key discipline and round function)."""
        import jax.random as jrandom

        B, S = ids.shape
        kc, vc = self._empty_cache(B)
        dkc, dvc = self._empty_cache(B, eng["cfg"])
        logits, kc, vc = self._prefill(self.params, ids, kc, vc)
        _, dkc, dvc = eng["prefill"](eng["params"], ids, dkc, dvc)
        key = jrandom.PRNGKey(seed)
        temp = jnp.asarray(float(temperature), jnp.float32)
        use_eos = eos_norm is not None
        eos = jnp.asarray(-1 if eos_norm is None else int(eos_norm),
                          jnp.int32)
        if do_sample:
            key, sub = jrandom.split(key)
            tok = jnp.asarray(_sample_logits(logits, sub, temp, top_k,
                                             top_p), jnp.int32)
        else:
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
        done = jnp.zeros((B,), jnp.bool_)
        if use_eos:
            tok = jnp.where(done, eos, tok)
            done = jnp.logical_or(done, tok == eos)
        buf = np.zeros((B, max_new), np.int32)
        buf[:, 0] = np.asarray(tok)
        count = np.ones((B,), np.int64)
        pos = jnp.full((B,), S, jnp.int32)
        sr = sa = 0
        tk = None if top_k is None else int(top_k)
        tp = None if top_p is None else float(top_p)
        while bool((count < max_new).any()):
            active = count < max_new
            live = active & ~np.asarray(done)
            emit, a, tok2, key, done2, kc, vc, dkc, dvc = eng["round"](
                self.params, eng["params"], tok, pos, key, done, kc, vc,
                dkc, dvc, eos, temp, K=int(K), do_sample=bool(do_sample),
                use_eos=use_eos, top_k=tk, top_p=tp)
            emit_h, a_h = np.asarray(emit), np.asarray(a)
            sr += int(live.sum())
            sa += int(a_h[live].sum())
            for b in range(B):
                if not active[b]:
                    continue
                n = min(int(a_h[b]) + 1, int(max_new - count[b]))
                buf[b, count[b]:count[b] + n] = emit_h[b, :n]
                count[b] += int(a_h[b]) + 1
            act_d = jnp.asarray(active)
            pos = jnp.where(act_d, pos + a + 1, pos)
            tok = jnp.where(act_d, tok2, tok)
            done = jnp.where(act_d, done2, done)
        self._record_spec_stats(sr, sa, K)
        return buf

    def _record_spec_stats(self, rounds: int, accepted: int, K: int):
        self.last_spec_stats = {
            "rounds": rounds,
            "accepted_drafts": accepted,
            # mean accepted draft tokens per verify step, over rows that
            # were live (not eos-done, budget not yet filled); emitted
            # tokens per verify step is this + 1 (the correction/bonus)
            "acceptance_len_mean": (accepted / rounds) if rounds
            else float(K),
            "num_speculative_tokens": K,
        }

    def _generate_per_token(self, ids, max_new_tokens, eos_token_id,
                            do_sample, temperature, top_k, top_p, seed):
        """Per-token host loop (the pre-fused path): one device dispatch
        per token plus a host sync each step. Kept as the
        ``decode_fallback`` debugging escape hatch, as the parity
        reference the fused path is tested against, and as the decode
        ladder's last rung. Returns the NEW tokens only (B, <=max_new) —
        the caller owns prompt concat and eos trimming."""
        import jax.random as jrandom

        B, S = ids.shape
        kc, vc = self._empty_cache(B)
        logits, kc, vc = self._prefill(self.params, ids, kc, vc)
        key = jrandom.key(seed)
        out = []
        pos = S
        done = np.zeros((B,), bool)
        for i in range(max_new_tokens):
            if do_sample:
                key, sub = jrandom.split(key)
                nxt = np.asarray(_sample_logits(logits, sub, temperature,
                                                top_k, top_p))
            else:
                nxt = np.asarray(jnp.argmax(logits, axis=-1))
            nxt = nxt.astype(np.asarray(ids).dtype)
            if eos_token_id is not None:
                # rows already finished stay pinned to eos (per-row
                # stopping; the reference pads post-eos positions likewise)
                nxt = np.where(done, eos_token_id, nxt)
                done |= nxt == eos_token_id
            out.append(jnp.asarray(nxt[:, None]))
            if (eos_token_id is not None and bool(done.all())) \
                    or i == max_new_tokens - 1:
                break  # no wasted forward for tokens nobody consumes
            # pos as a device scalar: a Python int would bake into the trace
            # and recompile every step
            logits, kc, vc = self._step(self.params, jnp.asarray(nxt[:, None]),
                                        kc, vc, jnp.asarray(pos, jnp.int32))
            pos += 1
        return np.asarray(jnp.concatenate(out, axis=1))


def decode_fallback_active() -> bool:
    """True when the per-token debugging path is requested, via the
    ``decode_fallback`` flag or the ``PADDLE_TPU_DECODE_FALLBACK`` env."""
    import os

    from paddle_tpu.flags import flags
    if flags.decode_fallback:
        return True
    return os.environ.get("PADDLE_TPU_DECODE_FALLBACK", "").strip().lower() \
        in ("1", "true", "yes", "on")


def _normalize_eos(eos_token_id) -> Optional[int]:
    """Uniform "no eos" convention across the decode surfaces: ``None``
    OR any negative id (the AOT bundles encode "none" as ``-1``, which no
    vocab token can match) both mean "decode to the full length"."""
    if eos_token_id is None:
        return None
    e = int(eos_token_id)
    return None if e < 0 else e


def _trim_after_eos(toks: np.ndarray, eos_token_id: int) -> np.ndarray:
    """Drop columns past the point where every row has emitted eos — the
    fused path pins finished rows to eos on device, so trimming here
    reproduces the per-token loop's early-stop output length exactly.
    A row whose FIRST emitted token is eos contributes length 1 (never
    0): the eos itself is part of the output, as in the host loop."""
    hit = toks == eos_token_id
    n = toks.shape[1]
    first = np.where(hit.any(axis=1), hit.argmax(axis=1), n - 1)
    return toks[:, :int(first.max()) + 1]


def _filter_logits(logits, temperature=1.0, top_k=None, top_p=None):
    """Temperature / top-k / top-p logit filtering over the LAST axis
    (any leading dims: (B, V) sampling, (B, K+1, V) speculative verify).
    ``temperature`` may be a traced runtime scalar; top-k/top-p change
    program structure and stay static. Returns filtered logits with
    excluded entries at -inf — the distribution BOTH sampling and the
    speculative accept/reject rule see (they must match exactly for the
    rejection rule to preserve the target distribution)."""
    lg = logits / jnp.maximum(jnp.asarray(temperature, logits.dtype), 1e-6)
    if top_k is not None:
        kth = jnp.sort(lg, axis=-1)[..., -int(top_k)][..., None]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    if top_p is not None:
        sorted_lg = jnp.flip(jnp.sort(lg, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest logit still inside the nucleus
        keep_n = jnp.sum(cum - probs < top_p, axis=-1)
        cutoff = jnp.take_along_axis(
            sorted_lg, jnp.maximum(keep_n - 1, 0)[..., None], axis=-1)
        lg = jnp.where(lg < cutoff, -jnp.inf, lg)
    return lg


def _sample_from(logits, key, temperature=1.0, top_k=None, top_p=None):
    """Temperature / top-k / top-p filtered categorical sample.
    (B, V) -> (B,). Pure trace-level function: runs inside the fused
    decode scan body and under the jitted `_sample_logits` wrapper."""
    return jax.random.categorical(
        key, _filter_logits(logits, temperature, top_k, top_p), axis=-1)


@functools.partial(jax.jit, static_argnames=("top_k", "top_p"))
def _sample_logits(logits, key, temperature=1.0, top_k=None, top_p=None):
    """Jitted `_sample_from` (the per-token host loops' sampling op).
    Temperature is a traced argument — no retrace across temperatures."""
    return _sample_from(logits, key, temperature, top_k, top_p)
