"""KV-cache autoregressive decoding for LlamaForCausalLM.

Capability analog of the reference's decode stack —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu
(block-table KV cache attention) and the fused generation ops — in the
TPU-native form: a PURE functional forward with a statically-shaped KV
cache — token-major ``(B, max_len, KV, D)`` for MHA, head-major
``(B, KV, max_len, D)`` for GQA (the decode-kernel layout); stacked over
layers by default, or one buffer per layer via
``flags.decode_cache_layout='per_layer'`` (measured equal-or-slower on
v5e; kept as a tuning knob) — so prefill and every decode step are each
ONE cached-compile XLA program (no recompiles across steps; static shapes
are what the MXU wants). Block tables are unnecessary: XLA owns memory, and
a padded dense cache + position mask is the layout it tiles best.

Decode attention: MHA runs XLA's masked dense read (a bandwidth-bound
matvec it fuses well); GQA routes through the Pallas decode-attention
kernel (ops/pallas/decode_attention.py — no repeated-KV
materialization). The Pallas flash kernel covers chunked prefill
(bottom-right-aligned causal, sq != sk).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, _rope_tables

__all__ = ["LlamaDecoder"]


def _rope_at(x, pos, cfg, p):
    """Rotate (B, S, H, D) by positions ``pos + [0..S)``: a dynamic slice
    of the tables precomputed at init from the training-path frequency
    function (_rope_tables), so decode can never diverge from training if
    rope scaling changes — and no per-step exp/pow work."""
    S = x.shape[1]
    d2 = cfg.head_dim // 2
    cos = jax.lax.dynamic_slice(p["rope.cos"], (pos, 0),
                                (S, d2)).astype(x.dtype)
    sin = jax.lax.dynamic_slice(p["rope.sin"], (pos, 0),
                                (S, d2)).astype(x.dtype)
    cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)


def _mm(x, p, name):
    """x @ weight, transparently using the int8 weight-only path when the
    decoder quantized this matrix (weight stays int8 in HBM — half the
    weight bandwidth, which bounds small-batch decode; reference analog:
    weight_only_linear, paddle/phi/kernels/fusion/gpu/). On TPU the
    dequant happens INSIDE the Pallas matmul tile (ops/pallas/int8_matmul)
    — XLA's astype-then-dot materializes the bf16 weight and loses the
    bandwidth win (measured slower than bf16)."""
    q = p.get(name + ":int8")
    if q is not None:
        scale = p[name + ":scale"]
        lead = x.shape[:-1]
        x2 = x.reshape((-1, x.shape[-1]))
        from paddle_tpu.ops.pallas import int8_matmul as i8
        if jax.default_backend() == "tpu" and i8.supported(x2, q):
            out = i8.int8_matmul(x2, q, scale)
        else:
            out = (x2 @ q.astype(x.dtype)) * scale.astype(x.dtype)
        return out.reshape(lead + (q.shape[1],))
    return x @ p[name]


def _block_forward(p, cfg: LlamaConfig, li: int, h, kc, vc, pos, max_len):
    """One decoder block over h (B, S, H) writing K/V into the cache at
    [pos, pos+S); attention reads the whole cache masked to < pos+S with
    causal alignment to the bottom-right (query i attends to <= pos+i)."""
    B, S, _ = h.shape
    H, KV, D = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    pre = f"model.layers.{li}."

    def rms(x, w):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        return (x.astype(jnp.float32) * jax.lax.rsqrt(
            var + cfg.rms_norm_eps)).astype(x.dtype) * w

    x = rms(h, p[pre + "input_layernorm.weight"])
    qkv = _mm(x, p, pre + "self_attn.qkv.weight")
    q = qkv[..., :H * D].reshape(B, S, H, D)
    k = qkv[..., H * D:H * D + KV * D].reshape(B, S, KV, D)
    v = qkv[..., H * D + KV * D:].reshape(B, S, KV, D)
    q = _rope_at(q, pos, cfg, p)
    k = _rope_at(k, pos, cfg, p)

    rep = H // KV
    head_major = rep > 1   # GQA: (B, KV, L, D) tiles feed the Pallas
    #                        kernel; MHA keeps token-major (B, L, KV, D),
    #                        which XLA's fused matvec prefers (measured)
    kt = jnp.swapaxes(k, 1, 2) if head_major else k
    vt = jnp.swapaxes(v, 1, 2) if head_major else v
    at = (0, 0, pos, 0) if head_major else (0, pos, 0, 0)
    if isinstance(kc, tuple):
        # per-layer cache buffers: a DUS on THIS layer's array only
        kc_l = jax.lax.dynamic_update_slice(kc[li], kt, at)
        vc_l = jax.lax.dynamic_update_slice(vc[li], vt, at)
        kc = tuple(kc_l if i == li else c for i, c in enumerate(kc))
        vc = tuple(vc_l if i == li else c for i, c in enumerate(vc))
    else:
        kc = jax.lax.dynamic_update_slice(kc, kt[None], (li,) + at)
        vc = jax.lax.dynamic_update_slice(vc, vt[None], (li,) + at)
        kc_l, vc_l = kc[li], vc[li]

    from paddle_tpu.flags import flags as _flags
    from paddle_tpu.ops.pallas import decode_attention as _da
    use_kernel = (head_major and S == 1 and _flags.use_decode_attention
                  and jax.default_backend() == "tpu"
                  and _da.supported(q[:, 0], kc_l))
    if use_kernel:
        # one-kernel GQA cache attention (block_multi_head_attention
        # capability): no repeated-KV materialization, online softmax,
        # compute skipped past the valid prefix. Measured (v5e, B=8
        # D=64): 8-way GQA L=4096 0.24 ms vs 0.88 ms XLA; 4-way L=8192
        # 0.60 ms vs 2.06 ms; ~1B GQA4 end-to-end 2.98 vs 7.08 ms/tok.
        out = _da.decode_attention(q[:, 0], kc_l, vc_l,
                                   pos + 1).reshape(B, S, H * D)
    elif head_major:
        kk = jnp.repeat(kc_l, rep, axis=1)
        vv = jnp.repeat(vc_l, rep, axis=1)
        scores = jnp.einsum("bqhd,bhkd->bhqk", q, kk) / jnp.sqrt(
            jnp.float32(D)).astype(q.dtype)
        kpos = jnp.arange(max_len)[None, None, None, :]
        qpos = pos + jnp.arange(S)[None, None, :, None]
        mask = kpos <= qpos                       # bottom-right causal
        scores = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bhkd->bqhd", attn, vv).reshape(B, S, H * D)
    else:
        kk, vv = kc_l, vc_l                       # (B, max_len, KV, D)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) / jnp.sqrt(
            jnp.float32(D)).astype(q.dtype)
        kpos = jnp.arange(max_len)[None, None, None, :]
        qpos = pos + jnp.arange(S)[None, None, :, None]
        mask = kpos <= qpos                       # bottom-right causal
        scores = jnp.where(mask, scores.astype(jnp.float32), -jnp.inf)
        attn = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", attn, vv).reshape(B, S, H * D)
    h = h + _mm(out, p, pre + "self_attn.o_proj.weight")

    x = rms(h, p[pre + "post_attention_layernorm.weight"])
    gu = _mm(x, p, pre + "mlp.gate_up.weight")
    F_ = gu.shape[-1] // 2
    a = jax.nn.silu(gu[..., :F_]) * gu[..., F_:]
    return h + _mm(a, p, pre + "mlp.down_proj.weight"), kc, vc


def _forward_cached(p, cfg: LlamaConfig, ids, kc, vc, pos, max_len):
    """ids (B, S) -> logits of the LAST position (B, V), updated caches."""
    h = p["model.embed_tokens.weight"][ids]
    for li in range(cfg.num_hidden_layers):
        h, kc, vc = _block_forward(p, cfg, li, h, kc, vc, pos, max_len)
    var = jnp.mean(jnp.square(h.astype(jnp.float32)), -1, keepdims=True)
    h = (h.astype(jnp.float32) * jax.lax.rsqrt(var + cfg.rms_norm_eps)
         ).astype(h.dtype) * p["model.norm.weight"]
    if "head:int8" in p:
        logits = _mm(h[:, -1], p, "head").astype(jnp.float32)
    else:
        head = (p["model.embed_tokens.weight"].T if cfg.tie_word_embeddings
                else p["lm_head.weight"])
        logits = (h[:, -1] @ head).astype(jnp.float32)   # (B, V)
    return logits, kc, vc


class LlamaDecoder:
    """Compile-once greedy/sampling decoder with a static KV cache.

    Two executables per generate: ``prefill`` (fixed prompt length, pad to
    reuse) and ``fused_decode`` — the ENTIRE token loop (argmax or
    temperature/top-k/top-p sampling, per-step key splits, per-row eos
    freezing) as one ``lax.scan`` program, so a ``generate`` of N tokens
    is 2 device dispatches regardless of mode, with zero retraces across
    calls/seeds/eos ids. ``dispatch_count`` counts executions so the
    one-dispatch property is assertable in tests; the per-token ``step``
    executable remains for the ``decode_fallback`` debugging flag.
    """

    def __init__(self, model: LlamaForCausalLM, max_len: int = 512,
                 weight_dtype: Optional[str] = None):
        """weight_dtype="int8": per-output-channel weight-only quantization
        of the decoder/MLP matmul weights (embedding and final norm stay in
        the activation dtype). On TPU the dequant runs inside the Pallas
        matmul tile (ops/pallas/int8_matmul), so the quantized matrices
        stream int8 from HBM — halving the weight bandwidth that bounds
        small-batch decode (reference weight_only_linear capability).

        Decode steps are kernel-count-sensitive (the scan body runs ~1ms
        of tiny ops on a 134M model): q/k/v and gate/up are concatenated
        at init into single fused matmuls (q_proj|k_proj|v_proj ->
        'self_attn.qkv', gate|up -> 'mlp.gate_up'), and the rope tables
        are precomputed once for max_len instead of per step."""
        if weight_dtype not in (None, "int8"):
            raise ValueError(f"weight_dtype must be None or 'int8', "
                             f"got {weight_dtype!r}")
        self.cfg = model.config
        self.max_len = max_len
        self.weight_dtype = weight_dtype
        raw = {name: t.value for name, t in model.state_dict().items()}
        # fuse qkv and gate/up per layer (one matmul each; fewer kernels)
        for li in range(model.config.num_hidden_layers):
            pre = f"model.layers.{li}."
            raw[pre + "self_attn.qkv.weight"] = jnp.concatenate(
                [raw.pop(pre + "self_attn.q_proj.weight"),
                 raw.pop(pre + "self_attn.k_proj.weight"),
                 raw.pop(pre + "self_attn.v_proj.weight")], axis=1)
            raw[pre + "mlp.gate_up.weight"] = jnp.concatenate(
                [raw.pop(pre + "mlp.gate_proj.weight"),
                 raw.pop(pre + "mlp.up_proj.weight")], axis=1)
        p = {}
        for name, v in raw.items():
            if (weight_dtype == "int8" and v.ndim == 2
                    and ("self_attn." in name or "mlp." in name)):
                from paddle_tpu.quantization import weight_quantize
                from paddle_tpu.framework.tensor import Tensor
                q, scale = weight_quantize(Tensor(v))
                p[name + ":int8"] = q.value
                p[name + ":scale"] = scale.value
                continue
            p[name] = v
        # the lm head (tied: transposed embedding) is the single biggest
        # matrix in the step — quantize a dedicated copy of it too
        if weight_dtype == "int8":
            from paddle_tpu.quantization import weight_quantize
            from paddle_tpu.framework.tensor import Tensor
            head = (p["model.embed_tokens.weight"].T
                    if model.config.tie_word_embeddings
                    else p.pop("lm_head.weight"))
            q, scale = weight_quantize(Tensor(head))
            p["head:int8"] = q.value
            p["head:scale"] = scale.value
        # precomputed rope tables for the whole cache window
        cos, sin = _rope_tables(max_len, model.config.head_dim,
                                model.config.rope_theta,
                                jnp.dtype(model.config.dtype), offset=0)
        p["rope.cos"], p["rope.sin"] = cos, sin
        self.params = p
        cfg = self.cfg
        self.trace_count = 0     # python side effect: bumps only on (re)trace
        self.dispatch_count = 0  # one per device program execution

        def prefill(p, ids, kc, vc):
            self.trace_count += 1
            return _forward_cached(p, cfg, ids, kc, vc, 0, max_len)

        def step(p, ids, kc, vc, pos):
            self.trace_count += 1
            return _forward_cached(p, cfg, ids, kc, vc, pos, max_len)

        def fused_decode(p, logits0, kc, vc, pos0, key0, done0, eos_id,
                         steps: int, do_sample: bool, use_eos: bool,
                         temperature: float, top_k, top_p):
            """The whole token loop — sampling and EOS handling included —
            as ONE device program (lax.scan): over a network-tunneled chip,
            per-token host dispatches dominate, so this collapses N tokens
            to a single dispatch for EVERY decode mode. The jax.random key
            threads through the carry and splits once per step (identical
            stream to the per-token fallback); ``done0`` rows that hit
            ``eos_id`` freeze to eos, and the host trims post-eos columns
            after the fact (``_trim_after_eos``)."""
            self.trace_count += 1

            def pick(logits, key, done):
                if do_sample:
                    key, sub = jax.random.split(key)
                    tok = _sample_from(logits, sub, temperature, top_k,
                                       top_p).astype(jnp.int32)
                else:
                    tok = jnp.argmax(logits, -1).astype(jnp.int32)
                if use_eos:
                    tok = jnp.where(done, eos_id, tok)
                    done = jnp.logical_or(done, tok == eos_id)
                return tok, key, done

            def body(carry, _):
                logits, kc, vc, pos, key, done = carry
                tok, key, done = pick(logits, key, done)
                logits, kc, vc = _forward_cached(p, cfg, tok[:, None], kc,
                                                 vc, pos, max_len)
                return (logits, kc, vc, pos + 1, key, done), tok

            (logits, _, _, _, key, done), toks = jax.lax.scan(
                body, (logits0, kc, vc, pos0, key0, done0), None,
                length=steps)
            last, _, _ = pick(logits, key, done)
            return jnp.concatenate([jnp.moveaxis(toks, 0, 1),
                                    last[:, None]], axis=1)

        def counted(jitted):
            def call(*args, **kwargs):
                self.dispatch_count += 1
                return jitted(*args, **kwargs)
            return call

        self._prefill = counted(jax.jit(prefill))
        self._step = counted(jax.jit(step))
        self._fused_decode = counted(jax.jit(
            fused_decode,
            static_argnames=("steps", "do_sample", "use_eos", "temperature",
                             "top_k", "top_p")))

    def _empty_cache(self, B):
        cfg = self.cfg
        dt = jnp.dtype(cfg.dtype)
        from paddle_tpu.flags import flags
        if flags.decode_cache_layout not in ("stacked", "per_layer"):
            raise ValueError(
                f"decode_cache_layout must be 'stacked' or 'per_layer', "
                f"got {flags.decode_cache_layout!r}")
        head_major = cfg.num_attention_heads != cfg.num_key_value_heads
        if head_major:
            per = (B, cfg.num_key_value_heads, self.max_len, cfg.head_dim)
        else:
            per = (B, self.max_len, cfg.num_key_value_heads, cfg.head_dim)
        if flags.decode_cache_layout == "stacked":
            shape = (cfg.num_hidden_layers,) + per
            return jnp.zeros(shape, dt), jnp.zeros(shape, dt)
        shape = per
        zeros = lambda: tuple(jnp.zeros(shape, dt)  # noqa: E731
                              for _ in range(cfg.num_hidden_layers))
        return zeros(), zeros()

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 seed: int = 0) -> np.ndarray:
        """Decode. input_ids: (B, S) ints. Returns (B, S + new).

        Greedy by default; ``do_sample=True`` draws from the
        temperature/top-k/top-p-filtered distribution (the reference
        generation-op sampling surface). EVERY mode — greedy, greedy+eos,
        sampled, sampled+eos — runs the whole token loop as one fused
        device dispatch (``fused_decode``); set the ``decode_fallback``
        flag or ``PADDLE_TPU_DECODE_FALLBACK=1`` to debug against the
        per-token host loop, which emits the same tokens for a fixed seed.
        """
        import jax.random as jrandom

        ids = jnp.asarray(np.asarray(input_ids))
        B, S = ids.shape
        if S + max_new_tokens > self.max_len:
            raise ValueError(f"prompt {S} + {max_new_tokens} new tokens "
                             f"exceeds max_len {self.max_len}")
        if max_new_tokens <= 0:
            return np.asarray(ids)
        if decode_fallback_active():
            return self._generate_per_token(ids, max_new_tokens,
                                            eos_token_id, do_sample,
                                            temperature, top_k, top_p, seed)
        kc, vc = self._empty_cache(B)
        logits, kc, vc = self._prefill(self.params, ids, kc, vc)
        # raw uint32 key: same threefry stream as the fallback's typed key
        # (and a plain array, so AOT bundles export the identical function)
        key = jrandom.PRNGKey(seed)
        done = jnp.zeros((B,), jnp.bool_)
        eos = jnp.asarray(0 if eos_token_id is None else int(eos_token_id),
                          jnp.int32)
        toks = self._fused_decode(
            self.params, logits, kc, vc, jnp.asarray(S, jnp.int32), key,
            done, eos, steps=max_new_tokens - 1, do_sample=bool(do_sample),
            use_eos=eos_token_id is not None,
            temperature=float(temperature),
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p))
        toks = np.asarray(toks)
        if eos_token_id is not None:
            toks = _trim_after_eos(toks, int(eos_token_id))
        return np.concatenate(
            [np.asarray(ids), toks.astype(np.asarray(ids).dtype)], axis=1)

    def _generate_per_token(self, ids, max_new_tokens, eos_token_id,
                            do_sample, temperature, top_k, top_p, seed):
        """Per-token host loop (the pre-fused path): one device dispatch
        per token plus a host sync each step. Kept only as the
        ``decode_fallback`` debugging escape hatch and as the parity
        reference the fused path is tested against."""
        import jax.random as jrandom

        B, S = ids.shape
        kc, vc = self._empty_cache(B)
        logits, kc, vc = self._prefill(self.params, ids, kc, vc)
        key = jrandom.key(seed)
        out = [ids]
        pos = S
        done = np.zeros((B,), bool)
        for i in range(max_new_tokens):
            if do_sample:
                key, sub = jrandom.split(key)
                nxt = np.asarray(_sample_logits(logits, sub, temperature,
                                                top_k, top_p))
            else:
                nxt = np.asarray(jnp.argmax(logits, axis=-1))
            nxt = nxt.astype(np.asarray(ids).dtype)
            if eos_token_id is not None:
                # rows already finished stay pinned to eos (per-row
                # stopping; the reference pads post-eos positions likewise)
                nxt = np.where(done, eos_token_id, nxt)
                done |= nxt == eos_token_id
            out.append(jnp.asarray(nxt[:, None]))
            if (eos_token_id is not None and bool(done.all())) \
                    or i == max_new_tokens - 1:
                break  # no wasted forward for tokens nobody consumes
            # pos as a device scalar: a Python int would bake into the trace
            # and recompile every step
            logits, kc, vc = self._step(self.params, jnp.asarray(nxt[:, None]),
                                        kc, vc, jnp.asarray(pos, jnp.int32))
            pos += 1
        return np.asarray(jnp.concatenate(out, axis=1))


import functools


def decode_fallback_active() -> bool:
    """True when the per-token debugging path is requested, via the
    ``decode_fallback`` flag or the ``PADDLE_TPU_DECODE_FALLBACK`` env."""
    import os

    from paddle_tpu.flags import flags
    if flags.decode_fallback:
        return True
    return os.environ.get("PADDLE_TPU_DECODE_FALLBACK", "").strip().lower() \
        in ("1", "true", "yes", "on")


def _trim_after_eos(toks: np.ndarray, eos_token_id: int) -> np.ndarray:
    """Drop columns past the point where every row has emitted eos — the
    fused path pins finished rows to eos on device, so trimming here
    reproduces the per-token loop's early-stop output length exactly."""
    hit = toks == eos_token_id
    n = toks.shape[1]
    first = np.where(hit.any(axis=1), hit.argmax(axis=1), n - 1)
    return toks[:, :int(first.max()) + 1]


def _sample_from(logits, key, temperature: float = 1.0,
                 top_k=None, top_p=None):
    """Temperature / top-k / top-p filtered categorical sample.
    (B, V) -> (B,). Pure trace-level function: runs inside the fused
    decode scan body and under the jitted `_sample_logits` wrapper."""
    lg = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None:
        kth = jnp.sort(lg, axis=-1)[:, -int(top_k)][:, None]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    if top_p is not None:
        sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest logit still inside the nucleus
        keep_n = jnp.sum(cum - probs < top_p, axis=-1)  # (B,)
        cutoff = jnp.take_along_axis(
            sorted_lg, jnp.maximum(keep_n - 1, 0)[:, None], axis=-1)
        lg = jnp.where(lg < cutoff, -jnp.inf, lg)
    return jax.random.categorical(key, lg, axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("temperature", "top_k", "top_p"))
def _sample_logits(logits, key, temperature: float = 1.0,
                   top_k=None, top_p=None):
    """Jitted `_sample_from` (the per-token host loops' sampling op)."""
    return _sample_from(logits, key, temperature, top_k, top_p)
