"""AOT predictor bundles — serving with zero model Python.

Round-4 answer to VERDICT item 3. Reference capability:
paddle/fluid/inference/api/analysis_predictor.h +
paddle_analysis_config.h — a configurable predictor loaded from an
exported artifact: named inputs/outputs, device/dtype config, MULTIPLE
entry functions (prefill + decode), shape buckets.

TPU-native design: each entry point is a ``jax.export`` StableHLO module
with the parameters BAKED IN as constants (the serving process never
imports model code or loads a separate weights file — one artifact, no
pickle, no Python execution on load). Static shapes are the deployment
contract; a bundle carries one compiled entry per declared shape bucket,
exactly like TensorRT optimization profiles.

Bundle layout (a directory):
    bundle.json                      # metadata: kind, io names, buckets,
                                     #   cache shapes/dtype, dtypes
    predict_<bucket>.aot             # plain forward entries
    prefill_b{B}_s{S}.aot            # LM prefill entries
    decode_b{B}_n{N}.aot             # LM greedy scan-decode entries

``AotPredictor`` loads a bundle and serves `run` / `generate` from the
deserialized executables only.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["export_predict_bundle", "export_decoder_bundle", "AotPredictor"]

_META = "bundle.json"


def _save_exp(fn, args, path, donate_argnums=(), meta=None):
    """Export one entry module (crash-safe write) and return its sha256
    for the bundle manifest. ``meta`` embeds an entry self-description
    in the .aot file itself (``aot.read_meta``) so a stray entry stays
    identifiable away from bundle.json."""
    from paddle_tpu.inference.aot import save_compiled
    return save_compiled(fn, args, path, donate_argnums=donate_argnums,
                         meta=meta)


def _load_exp(path, expected_sha256=None):
    from paddle_tpu.inference.aot import load_compiled
    return load_compiled(path, expected_sha256=expected_sha256)


def _write_meta(out_dir: str, meta: dict) -> None:
    """bundle.json write: temp + atomic rename, so a killed exporter
    leaves either the previous metadata or the new one — never a torn
    JSON that would poison every later load."""
    from paddle_tpu.runtime.resilience import atomic_write_bytes
    atomic_write_bytes(os.path.join(out_dir, _META),
                       json.dumps(meta, indent=2).encode())


def export_predict_bundle(layer, example_inputs: Sequence[np.ndarray],
                          out_dir: str,
                          input_names: Optional[List[str]] = None,
                          output_names: Optional[List[str]] = None,
                          extra_batch_sizes: Sequence[int] = ()) -> None:
    """Export a plain forward model as an AOT bundle.

    ``example_inputs`` fixes the primary shape bucket; each entry of
    ``extra_batch_sizes`` adds another bucket with the leading dim
    replaced. Parameters are baked into the modules at export time (the
    exporting process runs the model Python once per bucket; the serving
    process runs none)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.tensor import Tensor

    if hasattr(layer, "eval"):
        layer.eval()

    def fwd(*arrs):
        from paddle_tpu.autograd import tape
        with tape.no_grad():
            out = layer(*[Tensor(a) for a in arrs])
        outs = out if isinstance(out, (list, tuple)) else [out]
        return tuple(o._value if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in outs)

    os.makedirs(out_dir, exist_ok=True)
    examples = [jnp.asarray(a) for a in example_inputs]
    buckets = []
    manifest = {}
    shapes_list = [tuple(tuple(a.shape) for a in examples)]
    for b in extra_batch_sizes:
        shapes_list.append(tuple((int(b),) + tuple(a.shape[1:])
                                 for a in examples))
    for shapes in shapes_list:
        args = [jnp.zeros(s, a.dtype) for s, a in zip(shapes, examples)]
        tag = "predict_" + "_".join(
            "x".join(map(str, s)) for s in shapes)
        manifest[tag + ".aot"] = _save_exp(
            fwd, args, os.path.join(out_dir, tag + ".aot"))
        buckets.append({"file": tag + ".aot",
                        "shapes": [list(s) for s in shapes],
                        "dtypes": [str(a.dtype) for a in examples]})
    outs0 = jax.eval_shape(fwd, *examples)
    n_out = len(outs0)
    meta = {
        "kind": "predict",
        "inputs": input_names or [f"x{i}" for i in range(len(examples))],
        "outputs": output_names or [f"out_{i}" for i in range(n_out)],
        "buckets": buckets,
        "manifest": manifest,
    }
    # Identify which outputs are batch-major BY CONSTRUCTION (abstract
    # re-trace at a different batch: an output is batch-major iff its
    # leading dim tracks the input batch), so the padded-bucket run()
    # path never trims a non-batch output whose leading dim happens to
    # equal the padded batch (ADVICE r5).
    try:
        B0 = examples[0].shape[0]
        alt = B0 + 1
        outs1 = jax.eval_shape(fwd, *[
            jax.ShapeDtypeStruct((alt,) + tuple(a.shape[1:]), a.dtype)
            for a in examples])
        meta["output_batch_major"] = [
            bool(len(s0.shape) and len(s1.shape)
                 and s0.shape[0] == B0 and s1.shape[0] == alt)
            for s0, s1 in zip(outs0, outs1)]
    except Exception:
        # batch-polymorphic retrace unsupported (e.g. batch-baked model):
        # leave batch axes unknown -> run() serves exact shapes only
        pass
    _write_meta(out_dir, meta)


def export_decoder_bundle(decoder, out_dir: str,
                          prompt_lens: Sequence[int],
                          decode_steps: Sequence[int],
                          batch_sizes: Sequence[int] = (1,),
                          do_sample: bool = False,
                          temperature: float = 1.0,
                          top_k: Optional[int] = None,
                          top_p: Optional[float] = None,
                          draft_model=None,
                          num_speculative_tokens: Optional[int] = None,
                          plain_fallback: bool = True,
                          chunk_sizes: Sequence[int] = ()) -> None:
    """Export a ``LlamaDecoder`` as prefill + fused scan-decode AOT
    entries (the compiled-decode serving artifact the reference ships via
    its generation ops + AnalysisPredictor). One prefill module per
    (B, S) bucket, one decode module per (B, N) bucket; KV-cache buffers
    are donated so serving decodes in place.

    Decode entries run the SAME one-dispatch fused loop the in-process
    decoder uses: the eos id, the jax.random key AND the temperature are
    runtime inputs (one entry serves any eos — pass eos=-1 for "none" —
    any seed and any temperature); ``do_sample``/``top_k``/``top_p``
    change program structure, are baked at export and recorded in the
    bundle metadata (``decode_mode``; the export-time ``temperature``
    is recorded as ``default_temperature`` for callers that don't pass
    one).

    With ``draft_model`` (a LlamaForCausalLM or ``'skip:N'``; see
    ``LlamaDecoder.generate``) the decode entries are SPECULATIVE: the
    bundle additionally carries ``draft_prefill_b{B}_s{S}.aot`` entries
    and draft cache metadata, each decode entry takes both cache pairs
    and returns (tokens, rounds, accepted), and ``decode_mode``
    records the speculation statics. For speculative buckets ``N`` is
    the OUTPUT BUFFER size (serves max_new_tokens <= N); plain buckets
    keep the scan-steps meaning (serves max_new_tokens <= N + 1).

    ``plain_fallback`` (default on, speculative bundles only) also
    exports a plain fused decode entry per bucket — the serve-side
    degradation ladder's lower rung: when the speculative entry keeps
    failing dispatch at serve time, AotPredictor steps down to the plain
    entry automatically (bit-exact for greedy bundles) instead of
    failing the request.

    ``chunk_sizes`` additionally exports the CONTINUOUS-BATCHING serving
    entries (``decode_mode.chunked``): per batch bucket, one
    ``decode_chunk_b{B}_t{T}.aot`` running T steps of the re-enterable
    fused loop — the chunk size is a compile-time static; the whole loop
    carry (next-token logits, both cache buffers, per-row positions /
    RNG keys / done mask / eos ids / temperatures) is runtime inputs and
    outputs — plus one batch-1 ``admit_prefill_s{S}.aot`` per prompt
    bucket (right-padded prompt + runtime true length, returning the
    true last position's logits) for slot admission. A chunk size of 1
    is always included as the serve-side degradation rung. Serve with
    ``paddle_tpu.serving.ServingEngine(AotPredictor(dir), ...)`` — the
    same scheduler as in-process serving, zero model Python."""
    import jax
    import jax.numpy as jnp

    os.makedirs(out_dir, exist_ok=True)
    cfg = decoder.cfg
    p = decoder.params
    # a mesh-built decoder exports PARTITIONED entries: the example args
    # below are committed to their carry placements so jax.export bakes
    # the GSPMD program (sharded weight constants included), and the
    # topology + partition rules are recorded in decode_mode.mesh — the
    # load side refuses a different mesh instead of crashing mid-serve
    srd = getattr(decoder, "sharding", None)
    hm = getattr(decoder, "_head_major", False)

    def sput(x, field=None):
        if srd is None:
            return x
        if field is None:
            return srd.put(x, ())           # replicated on the mesh
        return srd.put_state_field(field, x, hm)

    eng, K = None, None
    if draft_model is not None:
        if srd is not None:
            from paddle_tpu.inference.sharding import SpeculativeMeshError
            raise SpeculativeMeshError(
                "speculative bundles cannot be exported from a mesh-built "
                "decoder (speculative decode is refused on a mesh)")
        from paddle_tpu.flags import flags
        eng = decoder._spec_engine(draft_model)
        K = int(num_speculative_tokens if num_speculative_tokens is not None
                else flags.decode_speculative_tokens)
        if K < 1:
            raise ValueError(f"num_speculative_tokens must be >= 1, got {K}")
        worst = max(prompt_lens) + max(decode_steps) + K
        if worst > decoder.max_len:
            raise ValueError(
                f"speculative buckets can overshoot the cache by up to "
                f"K={K} slots: prompt {max(prompt_lens)} + buffer "
                f"{max(decode_steps)} + {K} exceeds max_len "
                f"{decoder.max_len}")
    elif num_speculative_tokens is not None:
        raise ValueError("num_speculative_tokens requires a draft_model")
    prefills, dprefills, decodes = [], [], []
    chunks, admits = [], []
    csizes = sorted({int(t) for t in chunk_sizes} | {1}) if chunk_sizes \
        else []
    caches, dcaches = {}, {}
    manifest = {}

    def _cache_meta(kc):
        from paddle_tpu.quantization.kv_cache import is_quantized_kv
        bufs = kc if isinstance(kc, tuple) else (kc,)
        meta = {"n_buffers": len(bufs),
                "layout": "stacked" if len(bufs) == 1 else "per_layer"}
        if is_quantized_kv(bufs[0]):
            # int8 KV carry (the int8wk recipe): the serving process
            # rebuilds {"q": int8, "s": f32 scale} buffers from this
            meta.update(
                shape=list(bufs[0]["q"].shape),
                dtype=str(bufs[0]["q"].dtype),
                quant={"kv": str(bufs[0]["q"].dtype),
                       "scale_shape": list(bufs[0]["s"].shape),
                       "scale_dtype": str(bufs[0]["s"].dtype)})
        else:
            meta.update(shape=list(bufs[0].shape),
                        dtype=str(bufs[0].dtype))
        return meta

    for B in batch_sizes:
        kc, vc = decoder._empty_cache(int(B))
        caches[str(int(B))] = _cache_meta(kc)
        if eng is not None:
            dkc, dvc = decoder._empty_cache(int(B), eng["cfg"])
            dcaches[str(int(B))] = _cache_meta(dkc)
        for S in prompt_lens:
            ids = sput(jnp.zeros((int(B), int(S)), jnp.int32))

            def prefill(ids, kc, vc):
                return decoder._prefill(p, ids, kc, vc)

            tag = f"prefill_b{B}_s{S}"
            manifest[tag + ".aot"] = _save_exp(
                prefill, (ids, kc, vc),
                os.path.join(out_dir, tag + ".aot"),
                donate_argnums=(1, 2))
            prefills.append({"file": tag + ".aot", "batch": int(B),
                             "seq": int(S)})
            if eng is not None:
                def dprefill(ids, dkc, dvc):
                    return eng["prefill"](eng["params"], ids, dkc, dvc)

                dtag = f"draft_prefill_b{B}_s{S}"
                manifest[dtag + ".aot"] = _save_exp(
                    dprefill, (ids, dkc, dvc),
                    os.path.join(out_dir, dtag + ".aot"),
                    donate_argnums=(1, 2))
                dprefills.append({"file": dtag + ".aot", "batch": int(B),
                                  "seq": int(S)})
        logits_sds = jax.eval_shape(
            lambda ids, kc, vc: decoder._prefill(p, ids, kc, vc),
            jnp.zeros((int(B), int(prompt_lens[0])), jnp.int32), kc, vc)[0]
        for N in decode_steps:
            logits0 = sput(jnp.zeros(logits_sds.shape, logits_sds.dtype),
                           "logits")
            pos0 = sput(jnp.asarray(0, jnp.int32))
            key0 = sput(jax.random.PRNGKey(0))
            done0 = sput(jnp.zeros((int(B),), jnp.bool_), "done")
            eos0 = sput(jnp.asarray(-1, jnp.int32))
            temp0 = sput(jnp.asarray(float(temperature), jnp.float32))
            tag = f"decode_b{B}_n{N}"
            if eng is None:
                def decode(logits, kc, vc, pos, key, done, eos, temp,
                           N=int(N)):
                    return decoder._fused_decode(
                        p, logits, kc, vc, pos, key, done, eos, temp,
                        steps=N, do_sample=bool(do_sample), use_eos=True,
                        top_k=None if top_k is None else int(top_k),
                        top_p=None if top_p is None else float(top_p))

                manifest[tag + ".aot"] = _save_exp(
                    decode,
                    (logits0, kc, vc, pos0, key0, done0, eos0, temp0),
                    os.path.join(out_dir, tag + ".aot"),
                    donate_argnums=(1, 2))
                decodes.append({"file": tag + ".aot", "batch": int(B),
                                "steps": int(N)})
            else:
                def decode(logits, kc, vc, dkc, dvc, pos, key, done, eos,
                           temp, N=int(N)):
                    return eng["decode"](
                        p, eng["params"], logits, kc, vc, dkc, dvc, pos,
                        key, done, eos, temp, max_new=N, K=K,
                        do_sample=bool(do_sample), use_eos=True,
                        top_k=None if top_k is None else int(top_k),
                        top_p=None if top_p is None else float(top_p))

                manifest[tag + ".aot"] = _save_exp(
                    decode,
                    (logits0, kc, vc, dkc, dvc, pos0, key0, done0,
                     eos0, temp0),
                    os.path.join(out_dir, tag + ".aot"),
                    donate_argnums=(1, 2, 3, 4))
                decodes.append({"file": tag + ".aot", "batch": int(B),
                                "steps": int(N), "speculative": True})
                if plain_fallback and N >= 1:
                    # the ladder's lower rung: a plain fused entry with
                    # the SAME serve capacity (N tokens) as the
                    # speculative buffer above it
                    def pdecode(logits, kc, vc, pos, key, done, eos,
                                temp, N=int(N)):
                        return decoder._fused_decode(
                            p, logits, kc, vc, pos, key, done, eos, temp,
                            steps=N - 1, do_sample=bool(do_sample),
                            use_eos=True,
                            top_k=None if top_k is None else int(top_k),
                            top_p=None if top_p is None else float(top_p))

                    ptag = f"decode_plain_b{B}_n{N}"
                    manifest[ptag + ".aot"] = _save_exp(
                        pdecode,
                        (logits0, kc, vc, pos0, key0, done0, eos0, temp0),
                        os.path.join(out_dir, ptag + ".aot"),
                        donate_argnums=(1, 2))
                    decodes.append({"file": ptag + ".aot",
                                    "batch": int(B), "steps": int(N) - 1})
        for T in csizes:
            # continuous-batching chunk entry: T loop steps per dispatch,
            # whole carry in/out (ServingEngine re-enters it between
            # admissions); T=1 doubles as the per-token degradation rung
            def cdecode(logits, kc, vc, pos, keys, done, eos, temp,
                        T=int(T)):
                return decoder._chunk_decode(
                    p, logits, kc, vc, pos, keys, done, eos, temp, None,
                    steps=T, do_sample=bool(do_sample),
                    top_k=None if top_k is None else int(top_k),
                    top_p=None if top_p is None else float(top_p))

            logits0 = sput(jnp.zeros(logits_sds.shape, logits_sds.dtype),
                           "logits")
            ctag = f"decode_chunk_b{B}_t{T}"
            manifest[ctag + ".aot"] = _save_exp(
                cdecode,
                (logits0, kc, vc,
                 sput(jnp.zeros((int(B),), jnp.int32), "pos"),
                 sput(jnp.zeros((int(B), 2), jnp.uint32), "keys"),
                 sput(jnp.zeros((int(B),), jnp.bool_), "done"),
                 sput(jnp.full((int(B),), -1, jnp.int32), "eos"),
                 sput(jnp.ones((int(B),), jnp.float32), "temp")),
                os.path.join(out_dir, ctag + ".aot"),
                donate_argnums=(1, 2),
                # the entry self-describes its statics: this chunk
                # program has NO ring-admission prologue and NO
                # speculative verify loop — what the serving engine's
                # typed demotions point at
                meta={"entry": "decode_chunk", "batch": int(B),
                      "chunk": int(T), "admit_ring": False,
                      "spec_chunk": False})
            chunks.append({"file": ctag + ".aot", "batch": int(B),
                           "chunk": int(T)})
    if csizes:
        # batch-1 admission prefills: right-padded prompt bucket + the
        # runtime true length; the returned row state is what the engine
        # scatters into a freed slot of the batch carry
        kc1, vc1 = decoder._empty_cache(1)
        caches["1"] = _cache_meta(kc1)
        for S in prompt_lens:
            # true_len/pos0 are PER-ROW (1,) runtime inputs: pos0 > 0 is
            # the prefix-cache suffix prefill (the caches arrive
            # preloaded with the cached prefix's KV rows [0, pos0)) — the
            # SAME bucketed entry serves cold and cached-suffix admission
            def aprefill(ids, kc, vc, true_len, pos0):
                return decoder._admit_prefill(p, ids, kc, vc, true_len,
                                              pos0)

            atag = f"admit_prefill_s{S}"
            manifest[atag + ".aot"] = _save_exp(
                aprefill,
                (sput(jnp.zeros((1, int(S)), jnp.int32)), kc1, vc1,
                 sput(jnp.ones((1,), jnp.int32)),
                 sput(jnp.zeros((1,), jnp.int32))),
                os.path.join(out_dir, atag + ".aot"),
                meta={"entry": "admit_prefill", "batch": 1,
                      "seq": int(S), "admit_pos0": True})
            admits.append({"file": atag + ".aot", "batch": 1,
                           "seq": int(S)})
    # the fused-decode serving contract: key/done/eos/temperature are
    # runtime inputs; do_sample/top_k/top_p (and the speculation statics)
    # were baked at export
    mode = {"do_sample": bool(do_sample),
            "temperature": "runtime",
            "default_temperature": float(temperature),
            "top_k": None if top_k is None else int(top_k),
            "top_p": None if top_p is None else float(top_p),
            # the dtype recipe baked into every entry (weights are
            # StableHLO constants; the KV carry dtype is structural):
            # load-side serving cross-checks an explicit quant ask
            # against this and refuses mismatches typed
            "quant": {
                "recipe": getattr(decoder, "quant", None) or "none",
                "weights": ("int8" if getattr(decoder, "weight_dtype",
                                              None) == "int8"
                            else str(jnp.dtype(cfg.dtype))),
                "kv_cache": ("int8" if getattr(decoder, "quant_kv", False)
                             else str(jnp.dtype(cfg.dtype))),
            }}
    if eng is not None:
        mode["speculative"] = {
            "num_speculative_tokens": K,
            "draft": (draft_model if isinstance(draft_model, str)
                      else "model"),
            "draft_layers": eng["cfg"].num_hidden_layers,
        }
    if csizes:
        # continuous-batching contract: chunk size is a static (one
        # entry per size); the loop carry — logits, caches, per-row
        # pos/keys/done/eos/temperature — is runtime inputs AND outputs
        mode["chunked"] = {"chunk_sizes": csizes,
                           "state_inputs": ["logits", "kc", "vc", "pos",
                                            "keys", "done", "eos",
                                            "temp"],
                           # admit entries take per-row (1,) true_len +
                           # pos0 — the prefix-cache suffix-prefill
                           # contract; absent on pre-prefix bundles,
                           # whose partial hits the engine demotes to
                           # misses
                           "admit_pos0": True,
                           # bundle entries carry neither the device
                           # admission-ring prologue nor a speculative
                           # chunk program: ServingEngine demotes bundle
                           # serving to host-scatter admission, and
                           # refuses draft_model= over a bundle typed
                           # (pointing at these statics) instead of
                           # crashing on a missing entry mid-serve
                           "admit_ring": False,
                           "spec_chunk": False}
    if srd is not None:
        # the mesh contract: entries are partitioned programs for THIS
        # topology (jax.export refuses other device counts outright);
        # AotPredictor/_BundleBackend refuse a different mesh typed, at
        # load, and rebuild the carry placements from these rules
        mode["mesh"] = srd.describe()
    meta = {
        "kind": "llama_decoder",
        "inputs": ["input_ids"],
        "outputs": ["tokens"],
        # int8 weight-only decoders export with the quantized params baked
        # into the modules (the PTQ -> serving chain, VERDICT r5 item 6)
        "weight_dtype": decoder.weight_dtype or "none",
        "max_len": decoder.max_len,
        "vocab_size": cfg.vocab_size,
        "logits_dtype": str(logits_sds.dtype),
        "caches": caches,
        "prefill_buckets": prefills,
        "decode_buckets": decodes,
        "decode_mode": mode,
        # per-file sha256 of the intended bytes (computed BEFORE the
        # write hit disk): AotPredictor verifies each entry at load and
        # refuses corrupt modules with a typed CorruptBundleError
        "manifest": manifest,
    }
    if eng is not None:
        meta["draft_caches"] = dcaches
        meta["draft_prefill_buckets"] = dprefills
    if csizes:
        meta["chunk_buckets"] = chunks
        meta["admit_prefill_buckets"] = admits
    _write_meta(out_dir, meta)


class AotPredictor:
    """Serve an AOT bundle: no model Python, no re-tracing, no pickle.

    ``run`` serves plain-forward bundles by named inputs/outputs;
    ``generate`` serves llama_decoder bundles (prefill at the (B, S)
    bucket, greedy decode at the smallest (B, N>=max_new_tokens) bucket,
    trimmed to the requested length).

    Ergonomics (round-5 VERDICT item 8, AnalysisConfig capability):
    - a smaller batch than any exported bucket pads up to the NEAREST
      bucket and trims the outputs (TensorRT-profile style), instead of
      exact-shape-or-error;
    - ``warmup=True`` executes every entry once with zeros at load time,
      so the first request pays no deserialization/transfer latency;
    - ``cast_inputs=True`` coerces feeds to the bucket dtype;
    - ``memory_report()`` sizes the artifact and the serving buffers."""

    def __init__(self, bundle_dir: str, device: Optional[str] = None,
                 warmup: bool = False, cast_inputs: bool = True,
                 allow_bucket_padding: bool = True):
        """``allow_bucket_padding``: serve smaller batches by zero-padding
        to the nearest bucket. CAVEAT: only sound when the model treats
        batch rows independently (the overwhelmingly common case); a graph
        with cross-batch-coupled outputs (e.g. a batch-mean output) would
        silently fold the pad rows in — disable padding for such models
        (Config.set_bucket_padding(False)) to get the strict
        exact-shape-or-error behavior back."""
        with open(os.path.join(bundle_dir, _META)) as f:
            self.meta = json.load(f)
        self._dir = bundle_dir
        self._entries: Dict[str, object] = {}
        self.device = device
        self.cast_inputs = cast_inputs
        self.allow_bucket_padding = allow_bucket_padding
        # mesh-exported bundles: rebuild the recorded sharding (raises a
        # typed MeshMismatchError when this process cannot host the
        # topology — "refuse at load", never a mid-serve device crash);
        # serving state and fed arrays are then committed to the mesh
        self._sharding = None
        mesh_rec = (self.meta.get("decode_mode") or {}).get("mesh")
        if mesh_rec is not None:
            from paddle_tpu.inference.sharding import DecodeSharding
            self._sharding = DecodeSharding.from_describe(mesh_rec)
        self.padded_calls = 0      # observability: nearest-bucket serves
        self.last_spec_stats = None  # speculative bundles: last generate's
        #                              round/acceptance totals
        self.last_resilience = None  # retry/degradation record of the
        #                              last generate (also on the result)
        self._events = []
        if warmup:
            self.warmup()

    # -- common ------------------------------------------------------------
    @property
    def quant_recipe(self) -> Optional[str]:
        """The dtype recipe this bundle was exported with (``None`` =
        unquantized, else 'int8w'/'int8wk'). Read from
        ``decode_mode.quant``; legacy bundles fall back to the
        ``weight_dtype`` metadata (int8 weights = 'int8w')."""
        mode = self.meta.get("decode_mode") or {}
        q = mode.get("quant")
        if q is not None:
            r = q.get("recipe")
            return None if r in (None, "none") else r
        return ("int8w" if self.meta.get("weight_dtype") == "int8"
                else None)

    def get_input_names(self) -> List[str]:
        return list(self.meta["inputs"])

    def get_output_names(self) -> List[str]:
        return list(self.meta["outputs"])

    def _entry(self, fname):
        fn = self._entries.get(fname)
        if fn is None:
            # verify-on-load: bundles carrying a manifest get each entry's
            # on-disk bytes checked against the export-time sha256 — a
            # bit-flipped weight constant raises CorruptBundleError here
            # instead of silently serving wrong numerics. Pre-manifest
            # bundles load unchecked (legacy contract).
            expected = (self.meta.get("manifest") or {}).get(fname)
            fn = _load_exp(os.path.join(self._dir, fname),
                           expected_sha256=expected)
            self._entries[fname] = fn
        return fn

    def _run_entry(self, fname, site, *args):
        """Execute one exported module under the resilience contract:
        the fault-injection hook fires first, then transient backend
        errors retry with backoff; retry events accumulate on the
        in-flight generate/run record.

        With obs enabled (paddle_tpu/obs) each executed entry records a
        dispatch span named after its fault site (the entry file in the
        attrs) and bumps ``dispatches.<site>`` — timing only: a
        jax.export-deserialized module exposes no cost_analysis hooks,
        so bundle spans carry no FLOPs record (the in-process decoder's
        spans do)."""
        import paddle_tpu.obs as obs
        from paddle_tpu.runtime.resilience import (fault_injector,
                                                   resilient_call)

        def attempt():
            fault_injector.on_call(site)
            if not obs.enabled():
                return self._entry(fname)(*args)
            with obs.span(site, kind="dispatch", entry=fname):
                out = self._entry(fname)(*args)
            obs.metrics.counter(
                "dispatches." + site,
                "bundle entries executed at this site").inc()
            return out

        return resilient_call(attempt, site=site,
                              on_event=self._events.append)

    # -- config/ops surface ------------------------------------------------
    def warmup(self) -> None:
        """Execute every exported entry once with zeros: pays module
        deserialization + first-dispatch cost at LOAD time instead of on
        the first real request (AnalysisConfig warmup analog)."""
        import jax.numpy as jnp
        if self.meta["kind"] == "predict":
            for b in self.meta["buckets"]:
                args = [jnp.zeros(tuple(s), jnp.dtype(d))
                        for s, d in zip(b["shapes"], b["dtypes"])]
                self._entry(b["file"])(*args)
            return
        # EVERY decode bucket warms once (each is its own module); the
        # prefill feeding it re-runs per decode bucket because its cache
        # outputs are donated into the decode call. Prefill buckets with
        # no same-batch decode still warm on their own.
        decode_by_batch: Dict[int, list] = {}
        for dc in self.meta["decode_buckets"]:
            decode_by_batch.setdefault(dc["batch"], []).append(dc)
        for pf in self.meta["prefill_buckets"]:
            B = pf["batch"]
            decs = decode_by_batch.get(B, [None]) \
                if pf is self._first_prefill(B) else [None]
            for dc in decs:
                ids = jnp.zeros((B, pf["seq"]), jnp.int32)
                kc, vc = self._make_cache(B)
                logits, kc, vc = self._entry(pf["file"])(ids, kc, vc)
                if dc is None:
                    continue
                draft_caches = None
                if dc.get("speculative"):
                    dpf = next(b for b in self.meta["draft_prefill_buckets"]
                               if b["batch"] == B and b["seq"] == pf["seq"])
                    dkc, dvc = self._make_cache(B, "draft_caches")
                    _, dkc, dvc = self._entry(dpf["file"])(ids, dkc, dvc)
                    draft_caches = (dkc, dvc)
                self._entry(dc["file"])(*self._decode_args(
                    logits, kc, vc, pf["seq"], B, None, 0,
                    draft_caches=draft_caches))

    def _first_prefill(self, B: int):
        return next((b for b in self.meta["prefill_buckets"]
                     if b["batch"] == B), None)

    def memory_report(self) -> Dict[str, object]:
        """Artifact + serving-buffer sizes: per-entry bytes on disk (the
        baked-weight modules ARE the deployment payload) and the KV-cache
        bytes a generate() call allocates per batch bucket."""
        entries = {}
        total = 0
        for f in os.listdir(self._dir):
            if f.endswith(".aot"):
                sz = os.path.getsize(os.path.join(self._dir, f))
                entries[f] = sz
                total += sz
        report = {"entries_bytes": entries, "artifact_bytes": total}
        if self.meta["kind"] == "llama_decoder":
            caches = {}
            for b, cm in self.meta["caches"].items():
                per = int(np.prod(cm["shape"])) * cm["n_buffers"] \
                    * np.dtype(cm["dtype"]).itemsize
                q = cm.get("quant")
                if q is not None:        # + the int8 carry's f32 scales
                    per += int(np.prod(q["scale_shape"])) \
                        * cm["n_buffers"] \
                        * np.dtype(q["scale_dtype"]).itemsize
                caches[b] = 2 * per                      # K and V
            report["kv_cache_bytes_per_batch"] = caches
        return report

    def _cast(self, arr, dtype):
        a = np.asarray(arr)
        if self.cast_inputs and str(a.dtype) != dtype:
            a = a.astype(np.dtype(dtype))
        return a

    # -- plain forward -----------------------------------------------------
    def run(self, feeds: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        if self.meta["kind"] != "predict":
            raise ValueError(f"bundle kind {self.meta['kind']!r} has no "
                             "plain-forward entry; use generate()")
        names = self.meta["inputs"]
        args = [np.asarray(feeds[n]) for n in names]
        shapes = tuple(tuple(a.shape) for a in args)
        self._events = []
        for b in self.meta["buckets"]:
            if tuple(tuple(s) for s in b["shapes"]) == shapes:
                args = [self._cast(a, d) for a, d in zip(args, b["dtypes"])]
                outs = self._run_entry(b["file"], "bundle.predict", *args)
                outs = outs if isinstance(outs, (list, tuple)) else [outs]
                return {n: np.asarray(o)
                        for n, o in zip(self.meta["outputs"], outs)}
        # nearest-bucket batch padding: every input must share ONE leading
        # batch dim; same trailing dims as the bucket; smallest bucket
        # batch that fits; outputs trimmed back to the fed batch
        B = shapes[0][0] if shapes and shapes[0] else None
        same_batch = (self.allow_bucket_padding and B is not None
                      and all(s and s[0] == B for s in shapes))
        cands = []
        for b in self.meta["buckets"]:
            bs = [tuple(s) for s in b["shapes"]]
            if (same_batch
                    and all(len(s) == len(g) and s[1:] == g[1:]
                            for s, g in zip(bs, shapes))
                    and all(s[0] == bs[0][0] for s in bs)
                    and bs[0][0] > B):
                cands.append((bs[0][0], b))
        if cands:
            nb, b = min(cands, key=lambda t: t[0])
            self.padded_calls += 1
            padded = []
            for a, d in zip(args, b["dtypes"]):
                a = self._cast(a, d)
                pad = np.zeros((nb - a.shape[0],) + a.shape[1:], a.dtype)
                padded.append(np.concatenate([a, pad], axis=0))
            outs = self._run_entry(b["file"], "bundle.predict", *padded)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            # trim ONLY the outputs the exporter identified as batch-major
            # (abstract re-trace at a second batch size); a non-batch
            # output whose leading dim coincidentally equals the padded
            # batch must pass through untouched (ADVICE r5)
            bm = self.meta.get("output_batch_major")
            if bm is None:
                # legacy bundle without batch-axis metadata: padding could
                # silently truncate a non-batch output — refuse, per the
                # strict exact-shape contract
                raise ValueError(
                    f"no exact shape bucket for inputs {shapes} and this "
                    "bundle predates output batch-axis metadata; re-export "
                    "it to enable padded serving (exported buckets: "
                    f"{[b['shapes'] for b in self.meta['buckets']]})")
            return {n: (np.asarray(o)[:B] if is_bm else np.asarray(o))
                    for n, o, is_bm in zip(self.meta["outputs"], outs, bm)}
        raise ValueError(
            f"no shape bucket for inputs {shapes}; exported buckets: "
            f"{[b['shapes'] for b in self.meta['buckets']]}")

    # -- LM decode ---------------------------------------------------------
    def _head_major(self) -> bool:
        """Cache row layout from the recorded shapes: head-major rows are
        ``(B, KV, max_len, D)`` (max_len second-to-last), token-major
        ``(B, max_len, KV, D)``."""
        caches = self.meta.get("caches") or {}
        for cm in caches.values():
            shape = cm["shape"]
            return len(shape) >= 2 and shape[-2] == self.meta["max_len"]
        return False

    def _make_cache(self, B: int, which: str = "caches"):
        import jax.numpy as jnp
        cm = self.meta[which][str(B)]
        dt = jnp.dtype(cm["dtype"])
        shape = tuple(cm["shape"])
        quant = cm.get("quant")

        def z():
            if quant is not None:
                # int8wk carry: int8 rows + their scale buffer (never
                # mesh-exported — int8wk is refused on a mesh at build)
                return {"q": jnp.zeros(shape, dt),
                        "s": jnp.zeros(tuple(quant["scale_shape"]),
                                       jnp.dtype(quant["scale_dtype"]))}
            buf = jnp.zeros(shape, dt)
            if self._sharding is None:
                return buf
            return self._sharding.put_state_field("kc", buf,
                                                  self._head_major())

        if cm["n_buffers"] == 1:
            return z(), z()
        kc = tuple(z() for _ in range(cm["n_buffers"]))
        vc = tuple(z() for _ in range(cm["n_buffers"]))
        return kc, vc

    def _decode_temp(self, temperature):
        """Resolve the decode temperature against the bundle contract:
        runtime-temperature bundles serve any value (export-time value as
        the default); legacy static bundles reject a mismatching ask."""
        mode = self.meta.get("decode_mode") or {}
        if mode.get("temperature") == "runtime":
            if temperature is None:
                return float(mode.get("default_temperature", 1.0))
            return float(temperature)
        if temperature is not None and mode and \
                float(temperature) != float(mode.get("temperature", 1.0)):
            raise ValueError(
                f"this bundle predates runtime-temperature decode entries "
                f"(baked temperature={mode.get('temperature')}); re-export "
                f"it to serve temperature={temperature}")
        return None        # static bundles take no temperature input

    def _decode_args(self, logits, kc, vc, pos, nb, eos_token_id, seed,
                     temperature=None, draft_caches=None):
        """Positional inputs for a decode entry. Fused-decode bundles
        (``decode_mode`` in the metadata) take (logits, caches, pos, key,
        done, eos[, temperature]) — eos=-1 means "no eos"; speculative
        bundles insert the draft cache pair after the target's; legacy
        greedy bundles take the original 4 inputs."""
        import jax.numpy as jnp

        pos = jnp.asarray(pos, jnp.int32)
        if self.meta.get("decode_mode") is None:
            return (logits, kc, vc, pos)
        import jax
        key = jax.random.PRNGKey(seed)
        done = jnp.zeros((nb,), jnp.bool_)
        eos = jnp.asarray(-1 if eos_token_id is None else int(eos_token_id),
                          jnp.int32)
        if self._sharding is not None:
            # partitioned entries call with committed mesh arrays only
            pos = self._sharding.put(pos, ())
            key = self._sharding.put(key, ())
            eos = self._sharding.put(eos, ())
            done = self._sharding.put_state_field("done", done,
                                                  self._head_major())
        args = (logits, kc, vc)
        if draft_caches is not None:
            args = args + tuple(draft_caches)
        args = args + (pos, key, done, eos)
        t = self._decode_temp(temperature)
        if t is not None:
            t = jnp.asarray(t, jnp.float32)
            if self._sharding is not None:
                t = self._sharding.put(t, ())
            args = args + (t,)
        return args

    def generate(self, input_ids, max_new_tokens: int,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False,
                 temperature: Optional[float] = None,
                 seed: int = 0, quant: Optional[str] = None) -> np.ndarray:
        """Serve a decode: the whole token loop is ONE exported fused
        module execution. Eos id (``None`` or negative = no eos), seed
        and — on current bundles — temperature are runtime inputs;
        ``do_sample``/``top_k``/``top_p`` were fixed at export and a
        mismatching request is a contract violation. ``quant`` is a
        cross-check against the recipe baked into the bundle
        (``decode_mode.quant``): an unquantized bundle refuses a
        quantized ask typed (``QuantMismatchError``) and vice versa —
        ``None`` serves whatever was exported. Speculative bundles
        (``decode_mode.speculative``) additionally run the exported
        draft prefill and record the round/acceptance totals in
        ``last_spec_stats``."""
        if self.meta["kind"] != "llama_decoder":
            raise ValueError(f"bundle kind {self.meta['kind']!r} cannot "
                             "generate; use run()")
        if quant is not None:
            from paddle_tpu.quantization.kv_cache import (
                QuantMismatchError, canonical_quant)
            want, have = canonical_quant(quant), self.quant_recipe
            if want != have:
                raise QuantMismatchError(
                    f"this bundle was exported with quant recipe "
                    f"{have or 'none'!r} (weights are baked StableHLO "
                    f"constants); the ask for {want or 'none'!r} cannot "
                    f"be served — re-export the decoder with the "
                    f"matching quant=")
        import jax.numpy as jnp

        from paddle_tpu.inference.generate import _normalize_eos
        eos_token_id = _normalize_eos(eos_token_id)

        mode = self.meta.get("decode_mode")
        if mode is None:
            if do_sample or eos_token_id is not None:
                raise ValueError(
                    "this bundle predates fused-decode entries and serves "
                    "greedy-without-eos only; re-export it for "
                    "sampling/eos support")
        elif bool(do_sample) != bool(mode["do_sample"]):
            raise ValueError(
                f"bundle decode entries were exported with do_sample="
                f"{mode['do_sample']} (temperature={mode['temperature']}, "
                f"top_k={mode['top_k']}, top_p={mode['top_p']}); "
                f"requested do_sample={do_sample}")
        spec = (mode or {}).get("speculative")

        ids = np.asarray(input_ids)
        B, S = ids.shape
        # admission hook for batch-conditional faults (OOM above batch B)
        from paddle_tpu.runtime.resilience import fault_injector
        fault_injector.on_call("bundle.generate", batch=B)
        if S + max_new_tokens > self.meta["max_len"]:
            raise ValueError(
                f"prompt {S} + {max_new_tokens} new tokens exceeds the "
                f"bundle's max_len {self.meta['max_len']}")
        # exact batch bucket, else the smallest exported batch that fits
        # (prompt rows padded with zeros, outputs trimmed back; decode
        # rows are independent, so padding is always sound here)
        min_b = B if self.allow_bucket_padding else None
        batches = sorted({b["batch"] for b in self.meta["prefill_buckets"]
                          if b["seq"] == S
                          and (b["batch"] == B
                               or (min_b is not None
                                   and b["batch"] >= min_b))})
        if not batches:
            have = [(b["batch"], b["seq"])
                    for b in self.meta["prefill_buckets"]]
            raise ValueError(
                f"no prefill bucket for (B={B}, S={S}); exported: {have}")
        nb = batches[0]
        pf = next(b for b in self.meta["prefill_buckets"]
                  if b["batch"] == nb and b["seq"] == S)

        # bucket capacity: plain entries decode steps+1 tokens (scan steps
        # + the last pick); speculative entries' ``steps`` IS the output
        # buffer size
        def cap(b):
            return b["steps"] + (0 if b.get("speculative") else 1)

        want_spec = spec is not None
        cands = [b for b in self.meta["decode_buckets"]
                 if b["batch"] == nb and cap(b) >= max_new_tokens
                 and bool(b.get("speculative")) == want_spec]
        if not cands:
            have = [(b["batch"], cap(b))
                    for b in self.meta["decode_buckets"]]
            raise ValueError(
                f"no decode bucket with B={nb}, "
                f"capacity>={max_new_tokens}; exported (batch, capacity): "
                f"{have}")
        dc = min(cands, key=cap)

        fed = ids
        if nb != B:
            self.padded_calls += 1
            fed = np.concatenate(
                [ids, np.zeros((nb - B, S), ids.dtype)], axis=0)
        fed_d = jnp.asarray(fed, jnp.int32)
        if self._sharding is not None:
            fed_d = self._sharding.put(fed_d, ())

        def run_level(dcb):
            """One serve attempt at one decode bucket, from fresh caches
            (a failed higher rung may have consumed its donated
            buffers)."""
            use_spec = bool(dcb.get("speculative"))
            kc, vc = self._make_cache(nb)
            logits, kc, vc = self._run_entry(pf["file"], "bundle.prefill",
                                             fed_d, kc, vc)
            draft_caches = None
            if use_spec:
                dpf = next(b for b in self.meta["draft_prefill_buckets"]
                           if b["batch"] == nb and b["seq"] == S)
                dkc, dvc = self._make_cache(nb, "draft_caches")
                _, dkc, dvc = self._run_entry(
                    dpf["file"], "bundle.draft_prefill", fed_d, dkc, dvc)
                draft_caches = (dkc, dvc)
            site = "bundle.spec_decode" if use_spec else "bundle.decode"
            out = self._run_entry(dcb["file"], site,
                                  *self._decode_args(
                                      logits, kc, vc, S, nb, eos_token_id,
                                      seed, temperature=temperature,
                                      draft_caches=draft_caches))
            return out, use_spec

        # serve-side degradation ladder: the speculative bucket steps
        # down to a plain fused bucket of the same batch/capacity when
        # the bundle exported one (export_decoder_bundle plain_fallback)
        ladder = [("speculative" if want_spec else "fused", dc)]
        if want_spec:
            plain = [b for b in self.meta["decode_buckets"]
                     if b["batch"] == nb and not b.get("speculative")
                     and cap(b) >= max_new_tokens]
            if plain:
                ladder.append(("fused", min(plain, key=cap)))

        from paddle_tpu.flags import flags as _flags
        from paddle_tpu.runtime.resilience import (
            DecodeFailedError, DegradationEvent, GenerateResult,
            classify_error, record_event)
        self._events = []
        self.last_resilience = None
        degradations = []
        out, use_spec, level = None, False, None
        for li, (name, dcb) in enumerate(ladder):
            try:
                out, use_spec = run_level(dcb)
                level = name
                break
            except Exception as e:
                if classify_error(e) != "transient":
                    raise
                if (li == len(ladder) - 1
                        or not _flags.resilience_auto_degrade):
                    import paddle_tpu.obs as obs
                    obs.record_crash(
                        "bundle.ladder_exhausted", error=e,
                        extra={"site": "bundle.generate",
                               "failed_level": name,
                               "bundle_dir": self._dir})
                    raise DecodeFailedError(
                        f"bundle decode failed at ladder level {name!r} "
                        f"with no further fallback: {str(e)[:300]}",
                        events=list(self._events), last_error=e) from e
                ev = DegradationEvent(
                    site="bundle.generate", from_level=name,
                    to_level=ladder[li + 1][0],
                    error_class=type(e).__name__, error=str(e)[:300])
                record_event(ev)
                self._events.append(ev)
                degradations.append(ev)
        if use_spec:
            toks, sr, sa = out
            r, a = int(sr), int(sa)
            self.last_spec_stats = {
                "rounds": r, "accepted_drafts": a,
                "acceptance_len_mean": (a / r) if r else float(
                    spec["num_speculative_tokens"]),
                "num_speculative_tokens": spec["num_speculative_tokens"],
            }
        else:
            toks = out
            self.last_spec_stats = None
        toks = np.asarray(toks)[:B, :max_new_tokens]
        if eos_token_id is not None:
            from paddle_tpu.inference.generate import _trim_after_eos
            toks = _trim_after_eos(toks, int(eos_token_id))
        self.last_resilience = {
            "level": level,
            "requested_level": ladder[0][0],
            "retries": sum(1 for e in self._events
                           if getattr(e, "kind", "") == "retry"),
            "degradations": [e.as_dict() for e in degradations],
            "events": [e.as_dict() for e in self._events],
        }
        return GenerateResult.wrap(
            np.concatenate([ids, toks.astype(ids.dtype)], axis=1),
            self.last_resilience)
