"""paddle_tpu.inference — deployment predictor.

Analog of the reference's AnalysisPredictor stack (paddle/fluid/inference/
api/analysis_predictor.h + paddle_infer python API): load an exported
model, "IR optimization" = XLA compilation with static shapes + buffer
donation, cloned-scope concurrency = one compiled executable shared by
threads (jax executables are thread-safe).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """paddle_infer.Config analog (api/paddle_analysis_config.h)."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = "tpu"
        self._memory_optim = True
        self._layer = None
        self._aot_dir = None
        self._warmup = False
        self._cast_inputs = True
        self._bucket_padding = True

    def enable_warmup(self, flag: bool = True):
        """Execute every AOT entry once at load (first request pays no
        deserialization/compile-transfer latency)."""
        self._warmup = flag

    def set_cast_inputs(self, flag: bool):
        """Coerce feeds to each bucket's exported dtype (default on)."""
        self._cast_inputs = flag

    def set_bucket_padding(self, flag: bool):
        """Serve smaller batches by padding to the nearest bucket (default
        on; disable for models with cross-batch-coupled outputs)."""
        self._bucket_padding = flag

    def set_aot_bundle(self, bundle_dir: str):
        """Serve from an AOT bundle (inference/bundle.py): StableHLO
        entries with baked-in weights — the serving process imports no
        model Python (AnalysisPredictor-from-artifact analog)."""
        self._aot_dir = bundle_dir

    def set_model(self, model_path: str, params_path: Optional[str] = None):
        self.model_path = model_path
        self.params_path = params_path

    def set_layer(self, layer):
        """TPU-native path: predict directly from an nn.Layer or a
        jit.load'd TranslatedLayer."""
        self._layer = layer

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def disable_gpu(self):
        self._device = "cpu"

    def enable_use_gpu(self, *a, **k):
        self._device = "tpu"


class _IOHandle:
    def __init__(self, predictor, name):
        self._p = predictor
        self.name = name

    def copy_from_cpu(self, arr: np.ndarray):
        self._p._feeds[self.name] = np.asarray(arr)

    def reshape(self, shape):
        pass

    def copy_to_cpu(self) -> np.ndarray:
        return self._p._results[self.name]


class Predictor:
    def __init__(self, config: Config):
        self.config = config
        if getattr(config, "_aot_dir", None) is not None:
            from paddle_tpu.inference.bundle import AotPredictor
            aot = AotPredictor(config._aot_dir, device=config._device,
                               warmup=getattr(config, "_warmup", False),
                               cast_inputs=getattr(config, "_cast_inputs",
                                                   True),
                               allow_bucket_padding=getattr(
                                   config, "_bucket_padding", True))
            self._aot = aot
            self._layer = None
            self._input_names = aot.get_input_names()
            self._output_names = aot.get_output_names()
            self._feeds, self._results = {}, {}
            return
        self._aot = None
        if config._layer is not None:
            self._layer = config._layer
        elif config.model_path is not None:
            self._layer = paddle.jit.load(config.model_path)
        else:
            raise ValueError("Config needs set_model(path) or set_layer(layer)")
        if hasattr(self._layer, "eval"):
            self._layer.eval()
        self._static = paddle.jit.to_static(self._layer)
        self._feeds: Dict[str, np.ndarray] = {}
        self._results: Dict[str, np.ndarray] = {}
        self._input_names: List[str] = ["x"]
        self._output_names: List[str] = ["out"]

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        if name not in self._input_names:
            self._input_names.append(name)
        return _IOHandle(self, name)

    def get_output_handle(self, name: str) -> _IOHandle:
        return _IOHandle(self, name)

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        if self._aot is not None:
            feeds = dict(self._feeds)
            if inputs is not None:
                feeds = {n: np.asarray(a)
                         for n, a in zip(self._input_names, inputs)}
            self._results = self._aot.run(feeds)
            self._output_names = list(self._aot.get_output_names())
            if inputs is not None:
                return [self._results[n] for n in self._output_names]
            return True
        if inputs is not None:
            args = [Tensor(np.asarray(a)) for a in inputs]
        else:
            args = [Tensor(self._feeds[n]) for n in self._input_names
                    if n in self._feeds]
        with paddle.no_grad():
            out = self._static(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out_{i}" for i in range(len(outs))] \
            if len(outs) > 1 else ["out"]
        self._results = {n: o.numpy() for n, o in zip(self._output_names, outs)}
        if inputs is not None:
            return [self._results[n] for n in self._output_names]
        return True


    def memory_report(self):
        """AOT bundles: artifact + serving-buffer sizes (see
        AotPredictor.memory_report)."""
        if self._aot is not None:
            return self._aot.memory_report()
        raise ValueError("memory_report requires an AOT bundle predictor")

    def generate(self, input_ids, max_new_tokens: int = 32,
                 max_len: int = 512, eos_token_id=None,
                 do_sample: bool = False, temperature=None,
                 top_k=None, top_p=None, seed: int = 0,
                 draft_model=None, num_speculative_tokens=None
                 ) -> np.ndarray:
        """Autoregressive decode with a compile-once KV cache
        (block_multi_head_attention capability analog; see
        inference/generate.py). Every mode — greedy/sampled, with or
        without eos — runs the token loop as ONE fused device dispatch;
        with ``draft_model`` it runs speculatively (draft proposes
        ``num_speculative_tokens`` per target verify) still as one decode
        dispatch after the prefills. AOT bundles take eos id, seed and
        temperature as runtime inputs; ``do_sample``/``top_k``/``top_p``
        — and any draft model — were fixed at export (``bundle.json``'s
        ``decode_mode``), so pass ``draft_model`` to
        ``export_decoder_bundle`` rather than here when serving AOT."""
        if self._aot is not None:
            if draft_model is not None or num_speculative_tokens is not None:
                raise ValueError(
                    "AOT bundles bake the draft model at export time; "
                    "pass draft_model to export_decoder_bundle, not to "
                    "generate()")
            return self._aot.generate(input_ids,
                                      max_new_tokens=max_new_tokens,
                                      eos_token_id=eos_token_id,
                                      do_sample=do_sample,
                                      temperature=temperature, seed=seed)
        from paddle_tpu.inference.generate import LlamaDecoder
        dec = getattr(self, "_decoder", None)
        if dec is None or dec.max_len < max_len:
            dec = LlamaDecoder(self._layer, max_len=max_len)
            self._decoder = dec
        return dec.generate(input_ids, max_new_tokens=max_new_tokens,
                            eos_token_id=eos_token_id, do_sample=do_sample,
                            temperature=(1.0 if temperature is None
                                         else temperature),
                            top_k=top_k, top_p=top_p, seed=seed,
                            draft_model=draft_model,
                            num_speculative_tokens=num_speculative_tokens)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


from paddle_tpu.inference.aot import (  # noqa: E402,F401
    load_compiled, read_meta, save_compiled,
)
from paddle_tpu.inference.bundle import (  # noqa: E402,F401
    AotPredictor, export_decoder_bundle, export_predict_bundle,
)
from paddle_tpu.inference.sharding import (  # noqa: E402,F401
    DecodeSharding, MeshMismatchError, SpeculativeMeshError,
)

__all__ += ["save_compiled", "load_compiled", "read_meta",
            "AotPredictor",
            "export_predict_bundle", "export_decoder_bundle",
            "DecodeSharding", "MeshMismatchError", "SpeculativeMeshError"]
