"""Mesh sharding for the decode/serving stack (GSPMD tensor parallelism).

Pope et al. (2211.05102, PAPERS.md): small-batch decode is
weight-bandwidth-bound per chip, so splitting attention heads and the
MLP hidden dim over a ``tp`` mesh axis is the direct tokens/s-per-replica
lever, and the batch (= the serving engine's slot table) rides a ``dp``
axis for data-parallel replicas. GSPMD (Xu et al.) is the mechanism: we
annotate placements, XLA inserts the collectives.

``DecodeSharding`` is the one object the whole stack shares:

- regex partition rules (``DEFAULT_DECODE_RULES``, the SNIPPETS.md
  ``match_partition_rules`` idiom) shard the decoder's fused param dict
  — qkv/gate_up column-parallel, o_proj/down_proj row-parallel,
  vocab-parallel embedding and lm head;
- the ``DecodeState`` carry lives sharded ON DEVICE across chunks: KV
  caches on ``(dp, tp-on-heads)``, per-row positions/keys/done/eos/temp
  on ``dp`` — re-entry and engine admission never gather to host;
- every placement passes the divisibility guard
  (``parallel.placements.guarded_spec``): an axis that cannot split a
  dim evenly replicates that dim instead. Replication is always
  numerically correct under GSPMD, so any model/mesh combination runs —
  the guard only costs efficiency, never parity.

Parity contract (enforced by tests on the 8-virtual-device CPU harness):
sharded decode emits bit-identical TOKENS to the single-device path for
greedy and per-row-keyed sampling. Logits may differ in float ulps
(sharded matmuls reassociate reductions); argmax/categorical picks are
insensitive to that except on exact ties, which measure-zero never hits.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DecodeSharding", "DEFAULT_DECODE_RULES", "MeshMismatchError",
           "SpeculativeMeshError", "QuantizedKVMeshError"]


class MeshMismatchError(ValueError):
    """A mesh/sharding contract violation: a bundle exported for one mesh
    loaded under another, an engine mesh that contradicts its backend's,
    or too few devices for a recorded topology."""


class QuantizedKVMeshError(NotImplementedError):
    """The ``int8wk`` recipe (int8 KV cache + per-row scales) is not
    supported on a mesh yet: the quantized carry's scale buffers have no
    partition rules and the hand-written kernels gate off under GSPMD
    anyway, so the bandwidth win would not materialize. ``int8w``
    (weight-only) DOES serve on a mesh — the dequant matmul falls back
    to the XLA form, which shards like any dot. Typed so decoder
    construction refuses up front, never a mid-dispatch failure."""


class SpeculativeMeshError(NotImplementedError):
    """Historically: speculative decoding refused on a mesh. The live
    decode path now RUNS speculation under dp/tp meshes — the per-row
    uneven cache advance lowers through ``shard_map`` (dp splits the
    batch, tp splits heads; the per-row dynamic-update-slice needs no
    collectives, so the local-shard body is the single-device body) and
    is parity-tested bit-exact on the virtual CPU mesh. The type remains
    for the one surface that still refuses: exporting a SPECULATIVE AOT
    bundle from a mesh-built decoder (``export_decoder_bundle``), where
    the serialized entries would bake the mesh topology into the draft
    programs. Typed so the refusal stays up-front and the resilience
    classifier treats it as fatal, never a retry/degrade candidate."""


# Megatron-parity rules over the DECODE param dict (_build_params names:
# fused qkv / gate_up, optional :int8/:scale splits, precomputed rope).
# Column-parallel weights shard dim 1, row-parallel dim 0; the int8
# per-output-channel scale follows its matrix's output dim. Vocab axes
# (embedding rows, head columns) shard on tp — logits come out
# vocab-sharded and argmax/sampling reduce across the axis in-program
# (XLA inserts the gather; "sharded sampling" rather than a host trip).
DEFAULT_DECODE_RULES: Tuple[Tuple[str, tuple], ...] = (
    # stacked LoRA delta pairs (serving/lora): FIRST — their names embed
    # the host matrix names, and first-match would otherwise hand a 3-D
    # stack a 2-D host rule. Replicated: rank-r stacks are tiny next to
    # their host matrices and replication keeps the per-row gather
    # collective-free on any mesh (sharding B's d_out on tp like the
    # host column-parallel matrices is a valid refinement — measure
    # before switching).
    (r"^lora\.", ()),
    (r"self_attn\.qkv\.weight:scale", ("tp",)),
    (r"mlp\.gate_up\.weight:scale", ("tp",)),
    (r"(o_proj|down_proj)\.weight:scale", ()),
    (r"^head:scale", ("tp",)),
    (r"self_attn\.qkv\.weight", (None, "tp")),
    (r"self_attn\.o_proj\.weight", ("tp", None)),
    (r"mlp\.gate_up\.weight", (None, "tp")),
    (r"mlp\.down_proj\.weight", ("tp", None)),
    (r"embed_tokens\.weight", ("tp", None)),
    (r"lm_head\.weight", (None, "tp")),
    (r"^head", (None, "tp")),
    (r"rope\.(cos|sin)", ()),
    (r".*", ()),                      # norms and anything else: replicate
)


class DecodeSharding:
    """The decode stack's mesh + partition plan.

    ``mesh``: a ``ProcessMesh`` / ``jax.sharding.Mesh`` / ``"dp:2,tp:4"``
    spec (``parallel.mesh.decode_mesh`` accepts all three). ``dp`` and
    ``tp`` are conventional axis names — axes the rules don't mention
    replicate, so e.g. a pure-``tp`` mesh serves a single replica.
    """

    def __init__(self, mesh, rules: Optional[Sequence] = None,
                 dp_axis: str = "dp", tp_axis: str = "tp"):
        from paddle_tpu.parallel.mesh import decode_mesh
        self.mesh = decode_mesh(mesh)
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.rules = tuple((str(r), tuple(e)) for r, e in
                           (rules if rules is not None
                            else DEFAULT_DECODE_RULES))

    # -- mesh surface -------------------------------------------------------
    @property
    def jax_mesh(self):
        return self.mesh.jax_mesh

    @property
    def size(self) -> int:
        return self.mesh.size

    @property
    def axes(self) -> Dict[str, int]:
        return {n: self.mesh.dim_size(n) for n in self.mesh.dim_names}

    def dp_size(self) -> int:
        return (self.mesh.dim_size(self.dp_axis)
                if self.dp_axis in self.mesh.dim_names else 1)

    def dp_shards(self, batch: int) -> int:
        """How many ways the guard actually splits a ``batch``-row carry
        on dp (1 when the batch doesn't divide — the slot table then maps
        onto a single replica)."""
        d = self.dp_size()
        return d if d > 1 and batch % d == 0 else 1

    def same_topology(self, other: "DecodeSharding") -> bool:
        return self.axes == other.axes

    # -- spec construction --------------------------------------------------
    def named(self, shape, entries):
        """Guarded ``NamedSharding`` for one array shape."""
        from jax.sharding import NamedSharding

        from paddle_tpu.parallel.placements import guarded_spec
        return NamedSharding(self.jax_mesh,
                             guarded_spec(shape, entries, self.mesh))

    def guarded(self, shape, entries):
        """Guarded raw ``PartitionSpec`` for one array shape — what
        ``shard_map`` in/out_specs take (``named`` wraps the same spec in
        a NamedSharding for device_put/constraint use)."""
        from paddle_tpu.parallel.placements import guarded_spec
        return guarded_spec(shape, entries, self.mesh)

    def state_entries(self, field: str, ndim: int,
                      head_major: Optional[bool] = None) -> tuple:
        """Spec entries for one ``DecodeState`` field."""
        dp, tp = self.dp_axis, self.tp_axis
        if field == "logits":              # (B, V): vocab-sharded logits
            return (dp, tp)
        if field in ("pos", "done", "eos", "temp", "tok", "spec_rounds",
                     "spec_accepted", "nv", "adapter_idx", "spec_on"):
            return (dp,)
        if field == "keys":                # (B, 2) raw uint32 keys
            return (dp, None)
        if field in ("kc", "vc", "dkc", "dvc"):
            off = ndim - 4
            e = [None] * ndim
            e[off] = dp
            if head_major is not None:
                e[off + (1 if head_major else 2)] = tp
            return tuple(e)
        raise ValueError(f"unknown DecodeState field {field!r}")

    # -- params -------------------------------------------------------------
    def param_specs(self, params: Dict[str, object]) -> Dict[str, tuple]:
        from paddle_tpu.parallel.placements import match_partition_rules
        return match_partition_rules(self.rules, params)

    def shard_params(self, params: Dict[str, object]) -> Dict[str, object]:
        from paddle_tpu.parallel.placements import shard_by_rules
        return shard_by_rules(params, self.mesh, self.rules)

    # -- arrays / carries ---------------------------------------------------
    def put(self, x, entries):
        """Commit one array to its guarded sharding (host -> mesh)."""
        import jax
        return jax.device_put(x, self.named(np.shape(x), entries))

    def put_state_field(self, field: str, x, head_major: bool):
        import jax
        if x is None:
            return None
        if isinstance(x, tuple):          # per-layer cache buffers
            return tuple(self.put_state_field(field, b, head_major)
                         for b in x)
        ns = self.named(np.shape(x),
                        self.state_entries(field, np.ndim(x), head_major))
        return jax.device_put(x, ns)

    def put_state(self, state, head_major: bool):
        """Commit a whole ``DecodeState`` to its on-mesh placements."""
        import dataclasses
        kw = {}
        for f in ("logits", "kc", "vc", "pos", "keys", "done", "eos",
                  "temp", "dkc", "dvc", "tok", "spec_rounds",
                  "spec_accepted", "nv", "adapter_idx", "spec_on"):
            v = getattr(state, f, None)
            if v is None:
                continue                  # plain carries skip spec fields
            kw[f] = self.put_state_field(f, v, head_major)
        return dataclasses.replace(state, **kw)

    def constrain(self, x, field: str, head_major: bool):
        """``with_sharding_constraint`` inside a traced function — the
        sharding-preserving-jit half of the contract: carry OUTPUTS are
        pinned to the same placements the inputs arrived with, so chunk
        re-entry is a fixed-signature cache hit and the carry can never
        silently decay to replicated/host between dispatches."""
        import jax
        if x is None:
            return None
        if isinstance(x, tuple):
            return tuple(self.constrain(b, field, head_major) for b in x)
        ns = self.named(tuple(x.shape),
                        self.state_entries(field, x.ndim, head_major))
        return jax.lax.with_sharding_constraint(x, ns)

    def constrain_carry(self, logits, kc, vc, pos, keys, done,
                        head_major: bool):
        return (self.constrain(logits, "logits", head_major),
                self.constrain(kc, "kc", head_major),
                self.constrain(vc, "vc", head_major),
                self.constrain(pos, "pos", head_major),
                self.constrain(keys, "keys", head_major),
                self.constrain(done, "done", head_major))

    # -- metadata (bundle.json / statusz / bench records) -------------------
    def describe(self) -> Dict[str, object]:
        """The recordable topology: ordered axes, device kind, the rule
        list — what ``export_decoder_bundle`` writes into
        ``decode_mode.mesh`` and ``ServingEngine.status()`` reports."""
        import jax
        try:
            kind = str(self.jax_mesh.devices.reshape(-1)[0].device_kind)
        except Exception:
            kind = str(jax.devices()[0].device_kind)
        return {
            "axes": dict(self.axes),
            "size": self.size,
            "dp_axis": self.dp_axis,
            "tp_axis": self.tp_axis,
            "device_kind": kind,
            "partition_rules": [[r, list(e)] for r, e in self.rules],
        }

    @classmethod
    def from_describe(cls, meta: Dict[str, object]) -> "DecodeSharding":
        """Rebuild the sharding from a recorded description (bundle
        load). Raises :class:`MeshMismatchError` when this process does
        not have enough devices for the recorded topology."""
        import jax
        axes = dict(meta["axes"])
        size = int(np.prod([int(v) for v in axes.values()]))
        if jax.device_count() < size:
            raise MeshMismatchError(
                f"recorded mesh {axes} needs {size} devices; this "
                f"process has {jax.device_count()}")
        rules = [(r, tuple(e)) for r, e in meta.get("partition_rules",
                                                    DEFAULT_DECODE_RULES)]
        return cls(axes, rules=rules,
                   dp_axis=meta.get("dp_axis", "dp"),
                   tp_axis=meta.get("tp_axis", "tp"))

    @staticmethod
    def spec_str(x) -> str:
        """Human/JSON form of a live array's sharding spec (statusz)."""
        try:
            return str(getattr(x.sharding, "spec", x.sharding))
        except Exception:
            return "unknown"

    def __repr__(self):
        return (f"DecodeSharding(axes={self.axes}, "
                f"devices={self.size})")
