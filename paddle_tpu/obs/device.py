"""Device-time span attribution via merged ``jax.profiler`` traces.

The obs spine measures HOST intervals around device dispatches
(``trace.py``) and MODELED cost (``cost.py`` — analytical FLOPs from
``cost_analysis``). Both are proxies: over a tunneled TPU runtime the
host interval includes RTT, and the cost model says what the program
*should* cost, not what the device *spent*. This module closes the gap
with measured device time, the number Pope et al.'s efficient-scaling
analysis actually needs per dispatch:

- a :class:`DeviceTraceSession` wraps an obs evidence window in
  ``jax.profiler.start_trace``/``stop_trace`` and, for its duration,
  plugs a span hook into the tracer so every active obs span also opens
  a ``jax.profiler.TraceAnnotation("obs#<span_id>")`` — the profiler
  timeline then carries one host region per obs span;
- on ``stop()`` the exported profiler trace (the ``*.trace.json.gz``
  chrome-format file the profiler writes next to its xplane protobuf)
  is parsed, device-op events (``hlo_op`` args, or any event on a
  ``/device:*`` process) are attributed to the ``obs#`` region they
  overlap most, and the summed durations are merged back onto the
  owning spans as ``device_ms`` / ``device_occupancy`` attrs;
- the session reports **attribution coverage** — attributed device time
  over total captured device time — so a merge that lost ops (spans
  evicted from the ring, work outside any span) is visible instead of
  silently undercounting.

Everything here degrades to "no device attribution" on failure —
profiler unavailable, trace unparseable, zero captured ops — and never
breaks the measured window. Strictly an evidence mode
(``FLAGS_obs_device_trace`` / ``PADDLE_TPU_OBS_DEVICE=1``): a profiler
session is far too heavy for the default serving hot path.
"""

from __future__ import annotations

import bisect
import glob
import gzip
import json
import os
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

from paddle_tpu.obs import trace as _trace

__all__ = ["DeviceTraceSession", "device_trace_enabled",
           "merge_device_events"]

_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional["DeviceTraceSession"] = None


def device_trace_enabled() -> bool:
    """``FLAGS_obs_device_trace`` or ``PADDLE_TPU_OBS_DEVICE=1`` — the
    evidence-mode switch the benches consult (always AND-ed with the obs
    master switch; without spans there is nothing to merge onto)."""
    try:
        from paddle_tpu.flags import flags
        if flags.obs_device_trace:
            return True
    except Exception:
        pass
    return os.environ.get("PADDLE_TPU_OBS_DEVICE", "").strip().lower() \
        in ("1", "true", "yes", "on")


def _load_profile_trace(log_dir: str) -> Optional[dict]:
    """Newest chrome-format trace the profiler wrote under ``log_dir``
    (``plugins/profile/<run>/*.trace.json.gz``), parsed, or None."""
    paths = sorted(glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        return None
    try:
        with gzip.open(paths[-1], "rt") as f:
            return json.load(f)
    except Exception:
        return None


def _split_events(data: dict) -> Tuple[List[dict], List[dict]]:
    """Partition a profiler chrome trace into (obs annotation regions,
    device-op events). Device ops are events carrying an ``hlo_op`` arg
    (how XLA labels executed thunks/ops on every backend) or any
    complete event on a process the profiler named ``/device:*`` (the
    TPU device timeline)."""
    device_pids = set()
    for e in data.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = (e.get("args") or {}).get("name", "")
            if str(name).startswith("/device:"):
                device_pids.add(e.get("pid"))
    annotations, device_events = [], []
    for e in data.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        if name.startswith("obs#"):
            annotations.append(e)
        elif ("hlo_op" in (e.get("args") or {})
                or e.get("pid") in device_pids):
            device_events.append(e)
    return annotations, device_events


def merge_device_events(annotations: List[dict],
                        device_events: List[dict]) -> dict:
    """Attribute each device-op event to the ``obs#<span_id>`` region it
    overlaps most (innermost wins on ties — nested spans shadow their
    parents, matching the tracer's parent/child semantics). All times
    are profiler-timeline microseconds, so no cross-clock alignment is
    needed. Returns::

        {"attributed_us": {span_id: us}, "device_total_us": float,
         "attributed_total_us": float, "coverage": float,
         "device_ops": int}
    """
    windows = []                       # (start, end, dur, span_id)
    for a in annotations:
        try:
            sid = int(str(a["name"]).split("#", 1)[1])
        except (ValueError, KeyError, IndexError):
            continue
        s = float(a.get("ts", 0.0))
        d = float(a.get("dur", 0.0))
        windows.append((s, s + d, d, sid))
    windows.sort()
    starts = [w[0] for w in windows]
    max_dur = max((w[2] for w in windows), default=0.0)
    attributed: Dict[int, float] = {}
    total = attributed_total = 0.0
    n_ops = 0
    for e in device_events:
        s = float(e.get("ts", 0.0))
        d = float(e.get("dur", 0.0))
        if d <= 0:
            continue
        n_ops += 1
        total += d
        best_sid, best_ov, best_len = None, 0.0, 0.0
        # only windows starting before this op ends can overlap it, and
        # none starting more than max_dur before it begins still can
        hi = bisect.bisect_right(starts, s + d)
        for i in range(hi - 1, -1, -1):
            ws, we, wd, sid = windows[i]
            if ws < s - max_dur:
                break
            ov = min(we, s + d) - max(ws, s)
            if ov > best_ov or (ov == best_ov and ov > 0
                                and wd < best_len):
                best_sid, best_ov, best_len = sid, ov, wd
        if best_sid is not None and best_ov > 0:
            attributed[best_sid] = attributed.get(best_sid, 0.0) + d
            attributed_total += d
    return {"attributed_us": attributed, "device_total_us": total,
            "attributed_total_us": attributed_total,
            "coverage": (attributed_total / total) if total else 0.0,
            "device_ops": n_ops}


class DeviceTraceSession:
    """One profiler capture merged back onto the obs spans it covers.

    Usage (what the benches do around their timed windows)::

        sess = DeviceTraceSession().start()
        ... obs-instrumented work ...
        summary = sess.stop()

    After ``stop()``, every obs span recorded during the session whose
    annotation captured device ops carries ``attrs["device_ms"]`` (sum
    of its device-op durations) and ``attrs["device_occupancy"]``
    (device_ms over the span's host interval — >1.0 is legal when ops
    run on several device threads/cores in parallel). ``summary`` (also
    ``self.summary``) reports per-site totals and the coverage check::

        {"active": True, "merged_spans": n, "coverage": 0.97,
         "device_total_ms": ..., "attributed_ms": ...,
         "by_site": {"decode.chunk": {"device_ms": ..., "spans": n,
                                      "device_ms_mean": ...}, ...}}

    Sessions don't nest (the profiler is process-global): starting while
    another session is active yields an inactive session. Obs disabled
    likewise yields an inactive session — there are no spans to merge.
    """

    def __init__(self, log_dir: Optional[str] = None):
        self._log_dir = log_dir
        self._own_dir = log_dir is None
        self._mark: Optional[int] = None
        self.active = False
        self.summary: dict = {"active": False}

    def __enter__(self) -> "DeviceTraceSession":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def start(self) -> "DeviceTraceSession":
        global _ACTIVE
        if not _trace.obs_enabled():
            return self
        with _ACTIVE_LOCK:
            if _ACTIVE is not None:
                return self
            _ACTIVE = self
        try:
            import jax.profiler
            if self._own_dir:
                self._log_dir = tempfile.mkdtemp(prefix="obs_devtrace_")
            self._mark = _trace.tracer.mark()
            jax.profiler.start_trace(self._log_dir)
        except Exception:
            with _ACTIVE_LOCK:
                _ACTIVE = None
            return self
        self.active = True

        def _annotate(name, span_id):
            return jax.profiler.TraceAnnotation(f"obs#{span_id}")

        _trace.set_span_hook(_annotate)
        return self

    def stop(self) -> dict:
        global _ACTIVE
        if not self.active:
            return self.summary
        self.active = False
        _trace.set_span_hook(None)
        try:
            import jax.profiler
            jax.profiler.stop_trace()
        except Exception:
            with _ACTIVE_LOCK:
                _ACTIVE = None
            return self.summary
        with _ACTIVE_LOCK:
            _ACTIVE = None
        data = _load_profile_trace(self._log_dir)
        if data is not None:
            self.summary = self._merge(data)
        if self._own_dir:
            import shutil
            shutil.rmtree(self._log_dir, ignore_errors=True)
        return self.summary

    def _merge(self, data: dict) -> dict:
        annotations, device_events = _split_events(data)
        merged = merge_device_events(annotations, device_events)
        spans = {s.span_id: s
                 for s in _trace.tracer.spans_since(self._mark or 0)}
        by_site: Dict[str, dict] = {}
        merged_spans = 0
        for sid, us in merged["attributed_us"].items():
            sp = spans.get(sid)
            if sp is None:           # evicted from the ring before merge
                continue
            ms = us / 1e3
            sp.attrs["device_ms"] = round(ms, 6)
            if sp.dur_ms > 0:
                sp.attrs["device_occupancy"] = round(ms / sp.dur_ms, 4)
            agg = by_site.setdefault(sp.name,
                                     {"device_ms": 0.0, "spans": 0})
            agg["device_ms"] += ms
            agg["spans"] += 1
            merged_spans += 1
        for agg in by_site.values():
            agg["device_ms"] = round(agg["device_ms"], 6)
            agg["device_ms_mean"] = round(
                agg["device_ms"] / agg["spans"], 6)
        return {"active": True, "merged_spans": merged_spans,
                "coverage": round(merged["coverage"], 4),
                "device_total_ms": round(
                    merged["device_total_us"] / 1e3, 6),
                "attributed_ms": round(
                    merged["attributed_total_us"] / 1e3, 6),
                "device_ops": merged["device_ops"],
                "by_site": dict(sorted(by_site.items()))}
