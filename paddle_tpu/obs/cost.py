"""Compiled-program cost telemetry.

The accounting discipline of Pope et al. (2022, "Efficiently Scaling
Transformer Inference"): a serving number without its FLOPs/bytes
denominator is not evidence. XLA already knows both for every compiled
program — ``compiled.cost_analysis()`` (model FLOPs, bytes accessed)
and ``compiled.memory_analysis()`` (argument/output/temp bytes) — so
the dispatch wrappers attach them to the owning span and every bench
record can report tokens/s AND model-FLOPs-utilisation per dispatch.

The analysis is derived ONCE per (site, input-signature) via
``jitted.lower(...).compile()`` and cached here: the AOT lowering path
may recompile the program (it does not always share the jit dispatch
cache), so this is strictly obs-gated, amortized to one extra compile
per site, and any failure degrades to "no cost attached" — telemetry
never breaks the dispatch it measures. jax.export-deserialized bundle
entries expose no analysis hooks; bundle dispatch spans carry timing
only (documented in README).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

__all__ = ["dispatch_cost", "site_costs", "clear_cost_cache",
           "device_peak_flops", "mfu"]

_CACHE: Dict[Tuple, Optional[dict]] = {}
_BY_SITE: Dict[str, dict] = {}      # latest successful analysis per site
_LOCK = threading.Lock()


def _sig(args, kwargs) -> Tuple:
    """Hashable shape/dtype signature of a dispatch's inputs — static
    kwargs (ints/strs/bools/None) hash as themselves."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return (tuple(x.shape), str(x.dtype))
        return x
    flat, _ = jax.tree_util.tree_flatten((args, kwargs))
    return tuple(leaf(x) for x in flat)


def dispatch_cost(site: str, jitted, args=(), kwargs=None,
                  num_devices: int = 1) -> Optional[dict]:
    """FLOPs/bytes/peak-bytes record for the program ``jitted`` compiles
    at these arguments, or ``None`` when the backend can't say. Cached
    per (site, signature); safe to call per dispatch once obs is on.

    ``num_devices``: mesh size at a SHARDED dispatch site (GSPMD). XLA's
    ``cost_analysis()`` on a partitioned module reports PER-PARTITION
    numbers (verified on this jax: a tp=4 matmul reports global/4 plus
    the collective), so the recorded ``flops`` are already per-device —
    the honest MFU numerator against the per-device peak. The record
    carries ``num_devices`` and the derived ``flops_global`` so nothing
    has to guess which scope a number is in; callers must NOT divide
    again (that would double-count the partitioning)."""
    kwargs = kwargs or {}
    try:
        key = (site, _sig(args, kwargs))
    except Exception:
        return None
    with _LOCK:
        if key in _CACHE:
            return _CACHE[key]
    out: Optional[dict] = None
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        cost = cost or {}
        out = {}
        if cost.get("flops", -1) and float(cost.get("flops", -1)) > 0:
            out["flops"] = float(cost["flops"])
        ba = cost.get("bytes accessed", cost.get("bytes_accessed"))
        if ba is not None and float(ba) > 0:
            out["bytes_accessed"] = float(ba)
        try:
            mem = compiled.memory_analysis()
            for field, k in (("temp_size_in_bytes", "temp_bytes"),
                             ("argument_size_in_bytes", "argument_bytes"),
                             ("output_size_in_bytes", "output_bytes")):
                v = getattr(mem, field, None)
                if v is not None:
                    out[k] = int(v)
            if "temp_bytes" in out:
                out["peak_bytes"] = (out["temp_bytes"]
                                     + out.get("output_bytes", 0))
        except Exception:
            pass
        # the bytes-moved-per-dispatch record (the weight-bandwidth
        # evidence quantized decode is judged by): XLA's "bytes
        # accessed" when the backend reports it, else the
        # argument+output buffer sizes from memory_analysis — both read
        # the program's ACTUAL operand dtypes, so an int8-weight or
        # int8-KV dispatch reports its shrunken byte stream, not a
        # notional fp32 one
        if "bytes_accessed" in out:
            out["bytes_per_dispatch"] = out["bytes_accessed"]
        elif "argument_bytes" in out or "output_bytes" in out:
            out["bytes_per_dispatch"] = (out.get("argument_bytes", 0)
                                         + out.get("output_bytes", 0))
        if out and int(num_devices) > 1:
            out["num_devices"] = int(num_devices)
            if "flops" in out:
                out["flops_global"] = out["flops"] * int(num_devices)
        if not out:
            out = None
    except Exception:
        out = None
    with _LOCK:
        _CACHE[key] = out
        if out is not None:
            _BY_SITE[site] = dict(out)
    return out


def site_costs() -> Dict[str, dict]:
    """Latest successful cost record per dispatch site — the bench
    ``obs`` block's per-dispatch FLOPs source."""
    with _LOCK:
        return {k: dict(v) for k, v in _BY_SITE.items()}


def clear_cost_cache() -> None:
    with _LOCK:
        _CACHE.clear()
        _BY_SITE.clear()


def device_peak_flops() -> float:
    """bf16 peak FLOP/s of device 0 (the BASELINE.md MFU denominators;
    CPU gets a nominal 1 TF so MFU stays a defined, comparable ratio on
    the harness)."""
    import jax
    try:
        kind = str(jax.devices()[0].device_kind).lower()
        platform = jax.devices()[0].platform
    except Exception:
        return 1e12
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if platform == "tpu":
        return 197e12
    return 1e12


def mfu(flops: float, seconds: float,
        peak: Optional[float] = None) -> float:
    """Model-FLOPs-utilisation fraction for ``flops`` of work done in
    ``seconds`` of wall time."""
    if seconds <= 0 or flops <= 0:
        return 0.0
    return flops / seconds / (peak if peak is not None
                              else device_peak_flops())
