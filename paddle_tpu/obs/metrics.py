"""Typed metrics registry: counters, gauges, histograms.

The numeric half of the obs spine. Every ad-hoc accounting dict that
grew across rounds (``ServingEngine.metrics()`` lists, decode dispatch
counters, resilience retry tallies, bench last-line records) rebases
onto these three instrument types, so the same numbers export as a
structured snapshot (dict) and as Prometheus text exposition — the
serving metrics discipline of Orca-style engines (Yu et al., OSDI'22:
iteration-level queue delay / occupancy / latency percentiles).

Instruments are get-or-create by name (``registry.counter("x")`` twice
is the same object; a name can never silently change type) and
thread-safe. Histograms keep explicit cumulative buckets (Prometheus
semantics) PLUS a bounded reservoir of raw samples for the p50/p99
queries serving latency reporting needs — bucket-interpolated quantiles
would be too coarse for the millisecond-scale chunk latencies the
CPU-harness tests assert on.

Two kinds of registry exist on purpose:

- the process-global :data:`metrics` — the obs-gated registry the
  dispatch wrappers and resilience events write into only when
  ``FLAGS_obs_enabled`` / ``PADDLE_TPU_OBS=1`` (near-zero overhead off);
- per-engine private registries (``ServingEngine``) — always on, they
  REPLACE host bookkeeping the engine did anyway, and feed its
  ``metrics()`` compatibility surface.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "metrics",
           "DEFAULT_BUCKETS"]

# latency-shaped default buckets (seconds): spans ~100µs host scatters to
# multi-second drain waits
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_SAMPLE_CAP = 4096   # per-histogram raw-sample reservoir (newest wins)


class Counter:
    """Monotonic counter (``inc`` only)."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self._value}


class Gauge:
    """Point-in-time value (``set``/``inc``/``dec``); tracks its max."""

    __slots__ = ("name", "help", "_value", "_max", "_lock")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self._max = max(self._max, self._value)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            self._max = max(self._max, self._value)

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    @property
    def max(self) -> float:
        return self._max

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value, "max": self._max}


class Histogram:
    """Explicit-bucket histogram + bounded raw-sample reservoir.

    Buckets are upper bounds (Prometheus ``le`` semantics, cumulative at
    export); ``percentile(q)`` answers from the newest ``_SAMPLE_CAP``
    raw observations — exact for the test/bench scales that assert on
    it, honest-best-effort beyond (``samples_dropped`` says when)."""

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_samples", "samples_dropped", "_lock")

    def __init__(self, name: str, help_: str = "",
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help_
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)   # +inf tail
        self._sum = 0.0
        self._count = 0
        self._samples: collections.deque = collections.deque(
            maxlen=_SAMPLE_CAP)
        self.samples_dropped = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if len(self._samples) == _SAMPLE_CAP:
                self.samples_dropped += 1
            self._samples.append(v)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def percentile(self, q: float) -> float:
        """q in [0, 100] over the raw-sample reservoir. An EMPTY
        reservoir answers NaN, never 0.0 — a dashboard must be able to
        tell "no data" from "genuinely 0 ms" (the silent-zero p99 was a
        real misread class)."""
        with self._lock:
            if not self._samples:
                return float("nan")
            s = sorted(self._samples)
        k = (len(s) - 1) * (q / 100.0)
        lo, hi = int(k), min(int(k) + 1, len(s) - 1)
        return s[lo] + (s[hi] - s[lo]) * (k - lo)

    def snapshot(self) -> dict:
        with self._lock:
            cum, acc = [], 0
            for c in self._counts:
                acc += c
                cum.append(acc)
        empty = self._count == 0
        return {"type": "histogram", "count": self._count,
                "sum": self._sum, "mean": None if empty else self.mean,
                "p50": None if empty else self.percentile(50),
                "p99": None if empty else self.percentile(99),
                "buckets": {("+Inf" if i == len(self.buckets)
                             else repr(self.buckets[i])): cum[i]
                            for i in range(len(cum))},
                "samples_dropped": self.samples_dropped}


def _prom_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


class MetricsRegistry:
    """Get-or-create instrument registry with snapshot + Prometheus
    text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_name: Dict[str, Any] = {}

    def _get(self, name: str, cls, *args, **kwargs):
        with self._lock:
            m = self._by_name.get(name)
            if m is None:
                m = self._by_name[name] = cls(name, *args, **kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is a {type(m).__name__}, asked for "
                    f"{cls.__name__}")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, Counter, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, Gauge, help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(name, Histogram, help_, buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._by_name)

    def get(self, name: str):
        with self._lock:
            return self._by_name.get(name)

    def snapshot(self) -> Dict[str, dict]:
        """``{name: instrument.snapshot()}`` — the bench ``obs`` block /
        JSON artifact form."""
        with self._lock:
            items = list(self._by_name.items())
        return {name: m.snapshot() for name, m in sorted(items)}

    def to_prometheus(self, labels: Optional[Dict[str, str]] = None
                      ) -> str:
        """Prometheus text exposition format 0.0.4 (the scrape surface a
        real deployment would mount behind ``/metrics``). ``labels``
        attach to every sample line (e.g. ``{"replica": "replica0"}``)
        — how N same-shaped replica registries share one scrape without
        colliding metric names."""
        lab = ""
        if labels:
            lab = ",".join(f'{_prom_name(k)}="{v}"'
                           for k, v in sorted(labels.items()))
        with self._lock:
            items = sorted(self._by_name.items())
        lines: List[str] = []

        def sample(pn: str, value, extra: str = "") -> str:
            parts = ",".join(p for p in (extra, lab) if p)
            return f"{pn}{{{parts}}} {value}" if parts \
                else f"{pn} {value}"

        for name, m in items:
            pn = _prom_name(name)
            if m.help:
                lines.append(f"# HELP {pn} {m.help}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pn} counter")
                lines.append(sample(pn, f"{m.value:g}"))
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pn} gauge")
                lines.append(sample(pn, f"{m.value:g}"))
            else:
                lines.append(f"# TYPE {pn} histogram")
                snap = m.snapshot()
                for le, c in snap["buckets"].items():
                    lines.append(sample(f"{pn}_bucket", c,
                                        extra=f'le="{le}"'))
                lines.append(sample(f"{pn}_sum", f"{snap['sum']:g}"))
                lines.append(sample(f"{pn}_count", snap["count"]))
                # reservoir quantiles ride as plain gauges — and are
                # OMITTED for an empty histogram, so a scrape can never
                # read "no data yet" as "0 ms p99"
                if snap["count"]:
                    lines.append(sample(f"{pn}_p50", f"{snap['p50']:g}"))
                    lines.append(sample(f"{pn}_p99", f"{snap['p99']:g}"))
                # telemetry saturation is itself telemetry: a clipped
                # reservoir means the quantiles above are best-effort
                lines.append(sample(f"{pn}_samples_dropped",
                                    snap["samples_dropped"]))
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._by_name.clear()


metrics = MetricsRegistry()
