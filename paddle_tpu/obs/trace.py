"""Structured span tracer — the timing spine of the obs subsystem.

One thread-safe tracer serves every layer (decode dispatch wrappers,
serving engine request timelines, bundle entries, the legacy profiler
facade): ``with span("decode.chunk", batch=8):`` records a nested,
monotonic-clock span into a bounded ring buffer. Nothing here touches
jax — spans measure HOST intervals around device dispatches (the number
that matters over a tunneled TPU runtime, where per-dispatch RTT is the
decode tax the fused programs exist to amortize); the device-side FLOPs
and bytes of the dispatched program ride in as span attributes from
``obs.cost`` (compiled-program cost telemetry).

Clock discipline: all timestamps are ``time.monotonic_ns()`` — the same
clock family the serving engine and ``distributed/elastic.py`` use for
latency math, so a span's interval can never jump on an NTP step and
serving timeline spans (built from the engine's monotonic stamps) land
on the SAME axis as dispatch spans in one exported trace.

Disabled (the default — ``FLAGS_obs_enabled`` / ``PADDLE_TPU_OBS=1``),
``span()`` returns a shared no-op context manager: the per-call cost is
one enabled check, guarded by an overhead test in tests/test_obs.py.

Exporters: ``export_chrome_trace`` (chrome://tracing / Perfetto
loadable) and ``export_jsonl`` (one span dict per line — the
``tools/trace_report.py`` input; chrome JSON is accepted there too).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Span", "Tracer", "tracer", "span", "obs_enabled",
           "set_span_hook"]

# Optional per-span hook: a callable ``(name, span_id) -> context
# manager or None`` entered for the lifetime of every ACTIVE span.
# obs/device.py plugs a jax.profiler.TraceAnnotation factory in here for
# the duration of a device-trace capture, so the profiler timeline
# carries one ``obs#<span_id>`` region per obs span and device-op
# durations can be merged back onto the owning span. None (the default)
# costs one global read per enabled span; the disabled span path never
# consults it.
_SPAN_HOOK: Optional[Callable[[str, int], Any]] = None


def set_span_hook(hook: Optional[Callable[[str, int], Any]]) -> None:
    global _SPAN_HOOK
    _SPAN_HOOK = hook


def obs_enabled() -> bool:
    """The obs master switch: ``FLAGS_obs_enabled`` (settable at runtime
    via ``set_flags``/``FLAGS_obs_enabled=1``) or the ``PADDLE_TPU_OBS``
    environment variable. Read live — tests and benches toggle it around
    measurement windows."""
    try:
        from paddle_tpu.flags import flags
        if flags.obs_enabled:
            return True
    except Exception:
        pass
    return os.environ.get("PADDLE_TPU_OBS", "").strip().lower() in (
        "1", "true", "yes", "on")


class Span:
    """One recorded interval. ``parent_id`` encodes nesting (same-thread
    enclosing span); ``seq`` is the tracer-wide admission order (marks /
    windowed counting); ``attrs`` carries site metadata and the attached
    compiled-program cost record."""

    __slots__ = ("name", "span_id", "parent_id", "start_ns", "end_ns",
                 "tid", "attrs", "seq", "kind")

    def __init__(self, name, span_id, parent_id, start_ns, end_ns, tid,
                 attrs, seq, kind="span"):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tid = tid
        self.attrs = attrs
        self.seq = seq
        self.kind = kind              # "span" | "event" (instant)

    @property
    def dur_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def ok(self) -> bool:
        """True unless the spanned body raised (error spans are excluded
        from dispatch-count accounting — a failed dispatch never ran)."""
        return "error" not in self.attrs

    def as_dict(self) -> dict:
        return {"name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id, "start_ns": self.start_ns,
                "end_ns": self.end_ns, "dur_ms": self.dur_ms,
                "tid": self.tid, "kind": self.kind, "attrs": self.attrs}

    def as_chrome(self) -> dict:
        ev = {"name": self.name, "pid": os.getpid(), "tid": self.tid,
              "ts": self.start_ns / 1e3, "cat": self.kind,
              "args": dict(self.attrs)}
        if self.kind == "event":
            ev.update(ph="i", s="t")
        else:
            ev.update(ph="X", dur=(self.end_ns - self.start_ns) / 1e3)
        return ev


class _ActiveSpan:
    """The context manager handed out by ``Tracer.span`` when enabled.
    Records on exit; ``annotate()`` attaches attrs mid-flight (the cost
    telemetry hook)."""

    __slots__ = ("_tracer", "name", "attrs", "_start", "_parent",
                 "span_id", "_hook_cm")

    def __init__(self, tracer_, name, attrs):
        self._tracer = tracer_
        self.name = name
        self.attrs = attrs

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self):
        t = self._tracer
        self.span_id = t._next_id()
        stack = t._stack()
        self._parent = stack[-1] if stack else None
        stack.append(self.span_id)
        self._hook_cm = None
        hook = _SPAN_HOOK
        if hook is not None:
            # telemetry must never break the spanned body
            try:
                cm = hook(self.name, self.span_id)
                if cm is not None:
                    cm.__enter__()
                    self._hook_cm = cm
            except Exception:
                self._hook_cm = None
        self._start = time.monotonic_ns()
        return self

    def __exit__(self, etype, exc, tb):
        if self._hook_cm is not None:
            try:
                self._hook_cm.__exit__(None, None, None)
            except Exception:
                pass
            self._hook_cm = None
        end = time.monotonic_ns()
        stack = self._tracer._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        if etype is not None:
            self.attrs["error"] = f"{etype.__name__}: {str(exc)[:200]}"
        self._tracer._record(Span(
            self.name, self.span_id, self._parent, self._start, end,
            threading.get_ident() & 0xFFFF, self.attrs,
            self._tracer._next_seq()))
        return False


class _NullSpan:
    """Shared no-op for the disabled path — zero allocation per call."""

    __slots__ = ()

    def annotate(self, **attrs) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class Tracer:
    """Thread-safe bounded span recorder.

    ``enabled``: ``None`` follows the global obs switch
    (:func:`obs_enabled`); a callable is consulted per call (the legacy
    profiler facade plugs its own recording state in here). The buffer
    is a ring: the newest ``capacity`` spans win, and ``dropped`` counts
    what the ring evicted so reports never silently claim completeness.
    ``mark()``/``spans_since(mark)`` give windowed views keyed by a
    monotonic admission counter — how the benches count dispatch spans
    for exactly the timed window."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[Callable[[], bool]] = None):
        self._cap = capacity
        self._enabled = enabled
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._ids = 0
        self._seq = 0
        self.dropped = 0
        self._local = threading.local()

    # -- internals ----------------------------------------------------------
    def _capacity(self) -> int:
        if self._cap is not None:
            return self._cap
        try:
            from paddle_tpu.flags import flags
            return int(flags.obs_buffer_size)
        except Exception:
            return 8192

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _next_id(self) -> int:
        with self._lock:
            self._ids += 1
            return self._ids

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _record(self, sp: Span) -> None:
        with self._lock:
            self._spans.append(sp)
            cap = self._capacity()
            if len(self._spans) > cap:
                drop = len(self._spans) - cap
                del self._spans[:drop]
                self.dropped += drop

    def enabled(self) -> bool:
        return self._enabled() if self._enabled is not None \
            else obs_enabled()

    # -- recording API ------------------------------------------------------
    def span(self, name: str, **attrs):
        """Context manager timing a nested interval. No-op (shared
        singleton, no allocation) when disabled."""
        if not self.enabled():
            return _NULL
        return _ActiveSpan(self, name, attrs)

    def event(self, name: str, **attrs) -> None:
        """Instant event (Chrome 'i' phase) — serving request phase
        markers (queued/admitted/finished) and resilience events."""
        if not self.enabled():
            return
        now = time.monotonic_ns()
        self._record(Span(name, self._next_id(), None, now, now,
                          threading.get_ident() & 0xFFFF, attrs,
                          self._next_seq(), kind="event"))

    def add_span(self, name: str, start_ns: int, end_ns: int,
                 **attrs) -> None:
        """Retroactive span from caller-supplied ``time.monotonic_ns``
        stamps — the serving engine builds each request's lifetime span
        (submit -> finish) this way at finish time."""
        if not self.enabled():
            return
        self._record(Span(name, self._next_id(), None, int(start_ns),
                          int(end_ns), threading.get_ident() & 0xFFFF,
                          attrs, self._next_seq()))

    # -- views --------------------------------------------------------------
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def mark(self) -> int:
        """Current admission counter; pair with :meth:`spans_since`."""
        with self._lock:
            return self._seq

    def spans_since(self, mark: int) -> List[Span]:
        with self._lock:
            return [s for s in self._spans if s.seq > mark]

    def counts(self, since: int = 0, ok_only: bool = True
               ) -> Dict[str, int]:
        """Span count per name admitted after ``since`` (a ``mark()``
        value). ``ok_only`` drops error spans — the dispatch-accounting
        comparison counts only dispatches that ran."""
        out: Dict[str, int] = {}
        for s in self.spans_since(since):
            if s.kind != "span" or (ok_only and not s.ok()):
                continue
            out[s.name] = out.get(s.name, 0) + 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def drain(self) -> List[Span]:
        with self._lock:
            out, self._spans = self._spans, []
            return out

    # -- exporters ----------------------------------------------------------
    def chrome_events(self, since: int = 0) -> List[dict]:
        return [s.as_chrome() for s in self.spans_since(since)]

    def export_chrome_trace(self, path: str, since: int = 0,
                            extra_events: Optional[List[dict]] = None
                            ) -> str:
        """Write a chrome://tracing-loadable JSON trace; returns the
        path. Crash-safe write (atomic rename) — a trace artifact is
        evidence, and half a JSON is none."""
        from paddle_tpu.runtime.resilience import atomic_write_bytes
        events = self.chrome_events(since) + list(extra_events or [])
        atomic_write_bytes(path, json.dumps(
            {"traceEvents": events, "displayTimeUnit": "ms"}).encode())
        return path

    def export_jsonl(self, path: str, since: int = 0) -> str:
        from paddle_tpu.runtime.resilience import atomic_write_bytes
        lines = "".join(json.dumps(s.as_dict()) + "\n"
                        for s in self.spans_since(since))
        atomic_write_bytes(path, lines.encode())
        return path


tracer = Tracer()


def span(name: str, **attrs):
    """``with obs.span("decode.chunk", batch=8):`` on the global tracer."""
    return tracer.span(name, **attrs)
