"""Crash flight recorder: the last N spans + resilience timeline +
metrics, dumped atomically when decode dies.

A failed run's most valuable telemetry is the part that never got
exported: the trace ring and metrics registries live in the process
that just raised. The flight recorder turns an exhausted degradation
ladder / ``DecodeFailedError`` into a bounded postmortem JSON on disk —
written BEFORE the exception propagates, so the evidence survives the
process — with:

- the newest ``FLAGS_obs_flight_spans`` spans from the tracer ring
  (plus the ring's drop count — saturation is part of the record),
- the typed resilience event timeline (retries, degradations, injected
  faults — ``runtime/resilience.recent_events``),
- the process-global metrics snapshot and every attached registry
  (ServingEngines attach theirs, by weakref),
- the crash reason, error class/message and site.

Dumps are atomic (private tmp+rename — deliberately NOT
``atomic_write_bytes``: the fault injector hooks that path, and a
torn-write fault plan must never be able to tear the postmortem that
documents it). Active only while obs is enabled and
``FLAGS_obs_flight_recorder`` is on; every failure inside the recorder
is swallowed — a crash dump must never mask the crash.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from paddle_tpu.obs.metrics import metrics as _global_metrics
from paddle_tpu.obs.trace import obs_enabled as _obs_enabled
from paddle_tpu.obs.trace import tracer as _tracer

__all__ = ["FlightRecorder", "flight_recorder", "record_crash"]


def _flag(name: str, default):
    try:
        from paddle_tpu.flags import flags
        return flags.get(name)
    except Exception:
        return default


class FlightRecorder:
    """Bounded postmortem dumper. One process-global instance
    (:data:`flight_recorder`) serves every crash site; engines attach
    their private registries via :meth:`add_registry` (weakref — the
    recorder never extends an engine's lifetime)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registries: List[tuple] = []     # (name, weakref)
        self._states: List[tuple] = []         # (name, weakref) — any
        #                                        object with .snapshot()
        self._seq = 0
        self.last_path: Optional[str] = None

    def add_registry(self, name: str, registry) -> None:
        """Re-attaching a name REPLACES the old entry (a rebuilt engine
        or unfenced replica must not leave a stale twin in the dump)."""
        with self._lock:
            self._registries = [
                (n, r) for n, r in self._registries
                if r() is not None and n != name]
            self._registries.append((name, weakref.ref(registry)))

    def add_state(self, name: str, provider) -> None:
        """Attach any stateful component exposing ``snapshot()`` (e.g. a
        serving prefix cache, a replica router's health table) so its
        live state lands in the postmortem — weakref, like registries,
        so the recorder never extends a component's lifetime; same
        name-replacement rule as :meth:`add_registry`."""
        with self._lock:
            self._states = [
                (n, r) for n, r in self._states
                if r() is not None and n != name]
            self._states.append((name, weakref.ref(provider)))

    def enabled(self) -> bool:
        return _obs_enabled() and bool(
            _flag("obs_flight_recorder", True))

    def dump(self, reason: str, error: Optional[BaseException] = None,
             extra: Optional[dict] = None,
             path: Optional[str] = None) -> Optional[str]:
        """Write the postmortem; returns its path, or None when the
        recorder is disabled. Never raises."""
        try:
            if path is None and not self.enabled():
                return None
            n_spans = max(1, int(_flag("obs_flight_spans", 256)))
            spans = _tracer.spans()
            from paddle_tpu.runtime.resilience import recent_events
            record: Dict[str, Any] = {
                "kind": "paddle_tpu.postmortem",
                "reason": reason,
                "error": None if error is None else {
                    "class": type(error).__name__,
                    "message": str(error)[:2000],
                },
                "pid": os.getpid(),
                "time_unix": time.time(),
                "monotonic_ns": time.monotonic_ns(),
                "spans": [s.as_dict() for s in spans[-n_spans:]],
                "spans_in_ring": len(spans),
                "spans_dropped": _tracer.dropped,
                "resilience_events": [
                    e.as_dict() if hasattr(e, "as_dict") else str(e)
                    for e in recent_events()],
                "metrics": _global_metrics.snapshot(),
            }
            with self._lock:
                regs = list(self._registries)
                states = list(self._states)
            registries = {}
            for name, ref in regs:
                reg = ref()
                if reg is not None:
                    try:
                        registries[name] = reg.snapshot()
                    except Exception:
                        pass
            record["registries"] = registries
            state = {}
            for name, ref in states:
                prov = ref()
                if prov is not None:
                    try:
                        state[name] = prov.snapshot()
                    except Exception:
                        pass
            if state:
                record["state"] = state
            if extra:
                record["extra"] = extra
            if path is None:
                with self._lock:
                    self._seq += 1
                    seq = self._seq
                d = str(_flag("obs_flight_dir", "")) or "."
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"postmortem_{os.getpid()}_{seq}.json")
            # NaN-safe strict JSON (histogram quantiles may be None
            # already; allow_nan=False catches anything else)
            from paddle_tpu.obs.exporter import json_safe
            data = json.dumps(json_safe(record), indent=1,
                              default=str, allow_nan=False).encode()
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self.last_path = path
            return path
        except Exception:
            return None


flight_recorder = FlightRecorder()


def record_crash(reason: str, error: Optional[BaseException] = None,
                 extra: Optional[dict] = None) -> Optional[str]:
    """The one-line hook the decode ladder / serving chunk path calls
    right before raising ``DecodeFailedError``. Never raises; returns
    the postmortem path (None when disabled) and stderr-notes it so an
    operator tailing a dead run sees where the evidence went."""
    path = flight_recorder.dump(reason, error=error, extra=extra)
    if path is not None:
        import sys
        print(f"flight recorder: postmortem -> {path} ({reason})",
              file=sys.stderr)
    return path
