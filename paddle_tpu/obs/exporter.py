"""Live telemetry plane: /metrics, /statusz, /tracez over stdlib HTTP.

The obs spine's pull surface — what turns the tracer ring and metrics
registries from post-hoc trace files into something a running serving
process exposes, the deferred ROADMAP rung ("the /metrics endpoint over
MetricsRegistry.to_prometheus()"):

- ``/metrics`` — Prometheus text exposition 0.0.4: the process-global
  obs registry plus every attached registry (a ServingEngine's private
  registry attaches under its name). Telemetry saturation is exported
  first-class: the tracer's ring-buffer drop count syncs into the
  ``obs.tracer.dropped_spans`` gauge on every scrape, and histograms
  carry ``_samples_dropped`` lines — silent span/sample loss is a
  metric, not a mystery.
- ``/statusz`` — one JSON document: process/build info, backend, obs
  switches, and every attached status provider (the engine contributes
  its slot table, occupancy, queue depth, in-flight requests and
  resilience-ladder rung). NaN/Inf are sanitized to null — strict JSON
  for dashboards.
- ``/tracez`` — the newest completed spans from the tracer ring as JSON
  (``?limit=N``, default 256, plus the drop count), the "what just
  happened" debugging view.
- ``/healthz`` — the fleet prober's liveness/readiness verdict: 200
  with the attached health provider's JSON when it answers ``ok``,
  503 when it answers not-ok or raises (a broken health check IS the
  unhealthy signal).

One daemon ``ThreadingHTTPServer`` thread; ``start()`` binds (port 0 =
ephemeral, the test mode) and returns the actual port, ``stop()`` shuts
the server down and releases it. Wired from ``ServingEngine.start_exporter``
and ``bench.py --serve`` via ``FLAGS_obs_export_port`` /
``PADDLE_TPU_OBS_PORT``.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from paddle_tpu.obs.metrics import metrics as _global_metrics
from paddle_tpu.obs.trace import obs_enabled as _obs_enabled
from paddle_tpu.obs.trace import tracer as _tracer

__all__ = ["ObsExporter", "resolve_export_port", "json_safe"]

_START_MONOTONIC = time.monotonic()


def resolve_export_port() -> int:
    """The configured exporter port: ``FLAGS_obs_export_port``, else the
    ``PADDLE_TPU_OBS_PORT`` environment variable, else 0 (= no
    exporter)."""
    try:
        from paddle_tpu.flags import flags
        p = int(flags.obs_export_port)
        if p:
            return p
    except Exception:
        pass
    try:
        return int(os.environ.get("PADDLE_TPU_OBS_PORT", "0") or 0)
    except ValueError:
        return 0


def json_safe(obj: Any) -> Any:
    """Recursively replace NaN/Inf floats with None: /statusz and
    /tracez promise STRICT JSON (Python's json.dumps would happily emit
    the non-standard ``NaN`` literal and break consumers)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


def _backend_info() -> dict:
    try:
        import jax
        devs = jax.devices()
        return {"platform": devs[0].platform if devs else None,
                "device_kind": str(devs[0].device_kind) if devs else None,
                "device_count": len(devs)}
    except Exception as e:
        return {"platform": None, "error": str(e)[:200]}


class ObsExporter:
    """The start/stoppable telemetry endpoint bundle."""

    def __init__(self, port: Optional[int] = None,
                 host: str = "127.0.0.1"):
        self._port = resolve_export_port() if port is None else int(port)
        self._host = host
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._registries: Dict[str, Any] = {}
        self._status: Dict[str, Callable[[], dict]] = {}
        self._text: Dict[str, Callable[[], str]] = {}
        self._health: Optional[Callable[[], dict]] = None

    # -- composition --------------------------------------------------------
    def add_registry(self, name: str, registry,
                     labels: Optional[Dict[str, str]] = None
                     ) -> "ObsExporter":
        """Attach a MetricsRegistry whose instruments join the /metrics
        scrape (after the process-global registry). ``labels`` attach to
        every sample line — how N replica registries with identical
        metric names share one exposition (``{replica="replica0"}``)."""
        with self._lock:
            self._registries[name] = (registry, dict(labels or {}))
        return self

    def add_status_provider(self, name: str,
                            fn: Callable[[], dict]) -> "ObsExporter":
        """Attach a callable whose dict lands under ``name`` in
        /statusz. Provider errors are reported in-band, never a 500."""
        with self._lock:
            self._status[name] = fn
        return self

    def add_text_provider(self, name: str,
                          fn: Callable[[], str]) -> "ObsExporter":
        """Attach a callable returning raw Prometheus exposition text,
        appended verbatim to every /metrics scrape — how a cluster
        frontend folds its workers' live (already per-worker-labelled)
        /metrics into ONE fleet exposition. A provider that raises
        contributes a comment line, never a failed scrape."""
        with self._lock:
            self._text[name] = fn
        return self

    def set_health_provider(self, fn: Callable[[], dict]
                            ) -> "ObsExporter":
        """Attach the /healthz verdict callable: its dict must carry a
        truthy ``"ok"`` for a 200; a falsy ``"ok"`` — or the provider
        raising — answers 503 (an unreachable or broken health check IS
        the unhealthy signal a fleet prober wants). Without a provider
        /healthz answers ``{"ok": true}`` while the server runs."""
        with self._lock:
            self._health = fn
        return self

    def add_engine(self, engine, name: str = "serving",
                   labels: Optional[Dict[str, str]] = None
                   ) -> "ObsExporter":
        """Attach a ServingEngine: its private registry joins /metrics
        (optionally labelled — a replicated router attaches each replica
        with ``labels={"replica": name}``) and its live status (slot
        table, queue, occupancy, ladder rung) joins /statusz. Held by
        weakref — an exporter never keeps a dead engine (and its device
        carry) alive."""
        ref = weakref.ref(engine)
        self.add_registry(name, engine.registry, labels=labels)

        def status():
            eng = ref()
            if eng is None:
                return {"gone": True}
            return eng.status()
        return self.add_status_provider(name, status)

    # -- lifecycle ----------------------------------------------------------
    @property
    def port(self) -> int:
        return self._port

    def running(self) -> bool:
        return self._server is not None

    def start(self) -> int:
        """Bind and serve on a daemon thread; returns the actual port
        (meaningful with port 0). Idempotent while running."""
        if self._server is not None:
            return self._port
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet: telemetry, not access logs
                pass

            def do_GET(self):
                try:
                    exporter._handle(self)
                except BrokenPipeError:
                    pass
                except Exception as e:
                    try:
                        self.send_error(500, str(e)[:200])
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((self._host, self._port),
                                           Handler)
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="obs-exporter",
            daemon=True)
        self._thread.start()
        return self._port

    def stop(self) -> None:
        """Shut down and release the port (join bounded — stop() must
        never hang a drain path)."""
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    # -- request handling ---------------------------------------------------
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        url = urlparse(req.path)
        if url.path == "/metrics":
            body = self.metrics_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif url.path == "/statusz":
            body = json.dumps(json_safe(self.statusz()), indent=1,
                              default=str).encode()
            ctype = "application/json"
        elif url.path == "/healthz":
            ok, payload = self.healthz()
            body = json.dumps(json_safe(payload), default=str).encode()
            req.send_response(200 if ok else 503)
            req.send_header("Content-Type", "application/json")
            req.send_header("Content-Length", str(len(body)))
            req.end_headers()
            req.wfile.write(body)
            return
        elif url.path == "/tracez":
            q = parse_qs(url.query)
            try:
                limit = int(q.get("limit", ["256"])[0])
            except ValueError:
                limit = 256
            body = json.dumps(json_safe(self.tracez(limit)),
                              default=str).encode()
            ctype = "application/json"
        else:
            req.send_error(
                404, "unknown path (serving /metrics /statusz /tracez "
                     "/healthz)")
            return
        req.send_response(200)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    # -- payload builders (public: tests and bench reuse them) --------------
    def metrics_text(self) -> str:
        # saturation sync: the ring's drop counter becomes a scrapeable
        # gauge the moment anyone looks
        _global_metrics.gauge(
            "obs.tracer.dropped_spans",
            "spans evicted from the tracer ring buffer (telemetry "
            "saturation — raise FLAGS_obs_buffer_size if nonzero)"
        ).set(_tracer.dropped)
        parts = [_global_metrics.to_prometheus()]
        with self._lock:
            regs = list(self._registries.items())
        for _, (reg, labels) in regs:
            try:
                parts.append(reg.to_prometheus(labels=labels or None))
            except Exception:
                pass
        with self._lock:
            texts = list(self._text.items())
        for name, fn in texts:
            try:
                parts.append(fn())
            except Exception as e:
                parts.append(f"# text provider {name} unavailable: "
                             f"{type(e).__name__}\n")
        return "".join(p for p in parts if p)

    def statusz(self) -> dict:
        out = {
            "pid": os.getpid(),
            "time_unix": time.time(),
            "uptime_s": round(time.monotonic() - _START_MONOTONIC, 3),
            "backend": _backend_info(),
            "obs": {
                "enabled": _obs_enabled(),
                "tracer_spans": len(_tracer.spans()),
                "tracer_dropped_spans": _tracer.dropped,
            },
            "flags": self._flag_block(),
        }
        with self._lock:
            providers = list(self._status.items())
        for name, fn in providers:
            try:
                out[name] = fn()
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: "
                                      f"{str(e)[:200]}"}
        return out

    def healthz(self):
        """The /healthz verdict as ``(ok, payload)`` — public so tests
        and the cluster frontend can probe without HTTP."""
        with self._lock:
            fn = self._health
        if fn is None:
            return True, {"ok": True}
        try:
            payload = dict(fn())
        except Exception as e:
            return False, {"ok": False,
                           "error": f"{type(e).__name__}: "
                                    f"{str(e)[:200]}"}
        return bool(payload.get("ok")), payload

    def tracez(self, limit: int = 256) -> dict:
        spans = _tracer.spans()
        limit = max(1, min(int(limit), 4096))
        return {"count": len(spans),
                "dropped": _tracer.dropped,
                "spans": [s.as_dict() for s in spans[-limit:]]}

    @staticmethod
    def _flag_block() -> dict:
        try:
            from paddle_tpu.flags import flags
            return {n: flags.get(n) for n in flags.names()
                    if n.startswith(("obs_", "resilience_", "decode_"))}
        except Exception:
            return {}
