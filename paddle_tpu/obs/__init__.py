"""paddle_tpu.obs — unified observability spine.

One telemetry surface shared by decode, serving, resilience, checkpoint
IO and bench:

- :mod:`~paddle_tpu.obs.trace` — thread-safe structured span tracer
  (nested spans, monotonic clocks, bounded ring buffer) with Chrome
  trace and JSONL exporters;
- :mod:`~paddle_tpu.obs.metrics` — typed metrics registry (counters /
  gauges / explicit-bucket histograms) with snapshot + Prometheus text
  export;
- :mod:`~paddle_tpu.obs.cost` — compiled-program cost telemetry:
  ``cost_analysis()`` FLOPs/bytes and ``memory_analysis()`` peak bytes
  attached to the owning dispatch span, so every bench can report
  tokens/s AND MFU per dispatch (Pope et al., 2211.05102 discipline);
- :mod:`~paddle_tpu.obs.device` — device-time attribution: a
  ``jax.profiler`` capture merged back onto the owning spans
  (``device_ms`` / ``device_occupancy`` attrs, measured MFU, an
  attribution-coverage check);
- :mod:`~paddle_tpu.obs.exporter` — the live telemetry plane:
  ``/metrics`` (Prometheus), ``/statusz`` (JSON status), ``/tracez``
  (recent spans) on a stdlib HTTP thread
  (``FLAGS_obs_export_port`` / ``PADDLE_TPU_OBS_PORT``);
- :mod:`~paddle_tpu.obs.flight` — the crash flight recorder: last-N
  spans + resilience timeline + metrics snapshot dumped to a
  postmortem JSON when the decode ladder exhausts.

Disabled by default: enable with ``FLAGS_obs_enabled=1`` /
``set_flags({"obs_enabled": True})`` / ``PADDLE_TPU_OBS=1``. The
disabled path is a single enabled check per instrumented call (guarded
by an overhead test). ``tools/trace_report.py`` renders an exported
trace into per-phase / per-request summary tables.
"""

from paddle_tpu.obs.trace import (  # noqa: F401
    Span, Tracer, obs_enabled, set_span_hook, span, tracer,
)
from paddle_tpu.obs.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, metrics,
)
from paddle_tpu.obs.cost import (  # noqa: F401
    clear_cost_cache, device_peak_flops, dispatch_cost, mfu, site_costs,
)
from paddle_tpu.obs.device import (  # noqa: F401
    DeviceTraceSession, device_trace_enabled,
)
from paddle_tpu.obs.exporter import (  # noqa: F401
    ObsExporter, resolve_export_port,
)
from paddle_tpu.obs.flight import (  # noqa: F401
    FlightRecorder, flight_recorder, record_crash,
)

__all__ = [
    "Span", "Tracer", "tracer", "span", "obs_enabled", "set_span_hook",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "metrics",
    "dispatch_cost", "site_costs", "clear_cost_cache",
    "device_peak_flops", "mfu",
    "DeviceTraceSession", "device_trace_enabled",
    "ObsExporter", "resolve_export_port",
    "FlightRecorder", "flight_recorder", "record_crash",
    "enabled",
]

# the short form call sites use: ``if obs.enabled():``
enabled = obs_enabled
