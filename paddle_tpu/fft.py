"""paddle_tpu.fft — spectral ops (python/paddle/fft.py analog).

The reference routes to phi fft kernels backed by pocketfft/cuFFT; on TPU
XLA's FFT HLO does the work, so these are thin taped wrappers.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _mk(name, fn, n_arg="n"):
    @register_op(f"fft_{name}", ref="python/paddle/fft.py (capability analog)")
    def op(x, n=None, axis=-1, norm="backward"):
        return fn(x, n, axis, norm)
    op.__name__ = name
    return op


fft = _mk("fft", lambda x, n, a, norm: jnp.fft.fft(x, n, a, norm))
ifft = _mk("ifft", lambda x, n, a, norm: jnp.fft.ifft(x, n, a, norm))
rfft = _mk("rfft", lambda x, n, a, norm: jnp.fft.rfft(x, n, a, norm))
irfft = _mk("irfft", lambda x, n, a, norm: jnp.fft.irfft(x, n, a, norm))
hfft = _mk("hfft", lambda x, n, a, norm: jnp.fft.hfft(x, n, a, norm))
ihfft = _mk("ihfft", lambda x, n, a, norm: jnp.fft.ihfft(x, n, a, norm))


@register_op("fft_fft2")
def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s, axes, norm)


@register_op("fft_ifft2")
def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s, axes, norm)


@register_op("fft_rfft2")
def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s, axes, norm)


@register_op("fft_irfft2")
def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s, axes, norm)


@register_op("fft_fftn")
def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s, axes, norm)


@register_op("fft_ifftn")
def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s, axes, norm)


@register_op("fft_rfftn")
def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s, axes, norm)


@register_op("fft_irfftn")
def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s, axes, norm)


def fftfreq(n, d=1.0, dtype=None):
    from paddle_tpu.framework.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None):
    from paddle_tpu.framework.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


@register_op("fft_fftshift")
def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes)


@register_op("fft_ifftshift")
def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes)
