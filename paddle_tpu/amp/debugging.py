"""AMP debugging tools (python/paddle/amp/debugging.py analog):
check_numerics + tensor stat collection."""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from paddle_tpu.flags import flags, set_flags
from paddle_tpu.framework.tensor import Tensor

__all__ = ["enable_operator_stats_collection", "check_numerics", "TensorCheckerConfig",
           "enable_tensor_checker", "disable_tensor_checker", "collect_operator_stats"]


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode=None):
    v = tensor.value if isinstance(tensor, Tensor) else tensor
    n_nan = int(jnp.sum(jnp.isnan(v)))
    n_inf = int(jnp.sum(jnp.isinf(v)))
    if n_nan or n_inf:
        raise FloatingPointError(
            f"check_numerics: op={op_type} var={var_name}: {n_nan} NaN, {n_inf} Inf")
    return n_nan, n_inf


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=None, output_dir=None,
                 checked_op_list=None, skipped_op_list=None):
        self.enable = enable


def enable_tensor_checker(config: TensorCheckerConfig) -> None:
    set_flags({"check_nan_inf": bool(config.enable)})


def disable_tensor_checker() -> None:
    set_flags({"check_nan_inf": False})


@contextlib.contextmanager
def collect_operator_stats():
    from paddle_tpu.ops import registry
    stats = {}
    orig = registry.apply_op

    def wrapper(opdef, args, kwargs):
        stats[opdef.name] = stats.get(opdef.name, 0) + 1
        return orig(opdef, args, kwargs)

    registry.apply_op = wrapper
    try:
        yield stats
    finally:
        registry.apply_op = orig


enable_operator_stats_collection = collect_operator_stats
