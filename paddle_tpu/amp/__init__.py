"""AMP — mixed precision (python/paddle/amp analog).

TPU redesign (SURVEY §7.1): bf16 is the native training dtype; ``auto_cast``
inserts casts at op dispatch using white/black lists exactly like the
reference's eager AMP state (python/paddle/amp/auto_cast.py:860,
paddle/fluid/eager/amp_auto_cast.h), and ``GradScaler`` exists for fp16
parity (no-op for bf16 — no loss scaling needed).
"""

from paddle_tpu.amp.auto_cast import (  # noqa: F401
    auto_cast, amp_guard, is_auto_cast_enabled, amp_state,
    white_list, black_list, decorate,
)
from paddle_tpu.amp.grad_scaler import GradScaler, AmpScaler  # noqa: F401
from paddle_tpu.amp import debugging  # noqa: F401
