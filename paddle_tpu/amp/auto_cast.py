"""auto_cast context: per-op cast insertion at dispatch time."""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Set

import jax.numpy as jnp

from paddle_tpu.framework.dtype import convert_dtype

# ops that benefit from low precision (MXU ops) — reference white list analog
WHITE_LIST: Set[str] = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "sdpa_ref", "flash_attention",
}
# numerically sensitive ops kept in f32
BLACK_LIST: Set[str] = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax", "log_softmax", "cross_entropy", "layer_norm", "rms_norm",
    "batch_norm_train", "batch_norm_infer", "mean", "sum", "logsumexp",
    "cosine_similarity", "norm",
}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white: Set[str] = set()
        self.custom_black: Set[str] = set()


_state = _AmpState()


def amp_state() -> _AmpState:
    return _state


def is_auto_cast_enabled() -> bool:
    return _state.enabled


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None, custom_black_list=None,
              level: str = "O1", dtype: str = "bfloat16"):
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = enable
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def amp_dtype_for_op(op_name: str):
    """Called by ops.registry.apply_op: returns target dtype or None."""
    if not _state.enabled:
        return None
    if op_name in _state.custom_black or (op_name in BLACK_LIST and op_name not in _state.custom_white):
        return jnp.float32
    if op_name in WHITE_LIST or op_name in _state.custom_white:
        return _state.dtype
    if _state.level == "O2":
        return _state.dtype
    return None


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the amp dtype (amp.decorate analog).
    Optimizer master weights are automatic (f32 moments/master in Adam)."""
    d = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=d)
    if optimizers is None:
        return models
    return models, optimizers
