"""GradScaler — dynamic loss scaling for fp16 (python/paddle/amp/grad_scaler.py
analog: AmpScaler:41, GradScaler:619). With bf16 (TPU default) scaling is
unnecessary and `enable=False` makes every method a passthrough."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.framework.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2, use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self) -> bool:
        return self._enable

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer) -> None:
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._params():
            if p.grad is None:
                continue
            g = p.grad.value * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found = True
            p.grad = Tensor(g)
        self._found_inf = found

    def step(self, optimizer) -> None:
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss) -> None:
        self.step(optimizer)

    def update(self) -> None:
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._unscaled = False

    def get_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, v: float) -> None:
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state["good_steps"]
        self._bad_steps = state["bad_steps"]


AmpScaler = GradScaler
