"""Define-by-run autograd tape.

TPU-native redesign of the reference's eager autograd engine
(paddle/fluid/eager/: ``AutogradMeta`` autograd_meta.h:61, ``GradNodeBase``
grad_node_info.h:197, ``egr::Backward`` backward.cc:439, topological queue
``RunBackward`` backward.cc:105, ``GradTensorHolder`` accumulation).

Instead of per-op hand-written C++ grad nodes, each recorded op captures a
``jax.vjp`` of its (pure, jax-traceable) forward. Backward is a host-side
topological walk over these nodes; every vjp call is itself an XLA-dispatched
computation, so gradients run on TPU like any forward op. Saved residuals live
inside the vjp closure (TensorWrapper analog, tensor_wrapper.h:39).
"""

from __future__ import annotations

import contextlib
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "is_grad_enabled", "no_grad", "enable_grad", "set_grad_enabled",
    "backward", "grad",
]


class _GradMode(threading.local):
    def __init__(self):
        self.enabled = True


_mode = _GradMode()


def is_grad_enabled() -> bool:
    return _mode.enabled


@contextlib.contextmanager
def set_grad_enabled(enabled: bool):
    prev = _mode.enabled
    _mode.enabled = bool(enabled)
    try:
        yield
    finally:
        _mode.enabled = prev


class no_grad(contextlib.ContextDecorator):
    """``paddle.no_grad`` analog — context manager *and* decorator."""

    def __enter__(self):
        self._prev = _mode.enabled
        _mode.enabled = False
        return self

    def __exit__(self, *exc):
        _mode.enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _mode.enabled
        _mode.enabled = True
        return self

    def __exit__(self, *exc):
        _mode.enabled = self._prev
        return False


class GradNode:
    """One recorded op on the tape.

    ``vjp_fn(cotangents_for_outputs) -> cotangents_for_inputs`` where inputs
    are the flat list of differentiable input tensors recorded in ``inputs``.

    ``closure`` (optional) is the pure forward fn of the primal values; when
    present, ``create_graph=True`` re-linearizes through it so second-order
    gradients see the full dependence of the vjp on BOTH primals and
    cotangents (GeneralGrad analog, paddle/fluid/eager/general_grad.h).

    ``hooks`` maps output slot -> list of gradient hooks, run on that slot's
    fully-accumulated cotangent before it enters the vjp
    (GradNodeBase::RegisterGradientHook analog, grad_node_info.h:197).
    """

    __slots__ = ("name", "vjp_fn", "inputs", "n_outputs", "out_avals",
                 "closure", "hooks", "tuple_out", "__weakref__")

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence[Any], n_outputs: int,
                 out_avals: Sequence[Tuple[tuple, Any]], closure: Optional[Callable] = None,
                 tuple_out: Optional[bool] = None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # list[Tensor]
        self.n_outputs = n_outputs
        self.out_avals = list(out_avals)  # [(shape, dtype)] per output
        self.closure = closure
        self.hooks: Optional[Dict[int, List[Callable]]] = None
        # whether the recorded forward closure returned a tuple/list: the
        # cotangent passed to vjp_fn must mirror that pytree even when there
        # is a single output (e.g. to_static impls return 1-tuples)
        self.tuple_out = n_outputs > 1 if tuple_out is None else tuple_out

    def add_hook(self, out_index: int, fn: Callable):
        if self.hooks is None:
            self.hooks = {}
        self.hooks.setdefault(out_index, []).append(fn)

    def __repr__(self):
        return f"GradNode<{self.name}, n_in={len(self.inputs)}, n_out={self.n_outputs}>"


def _accumulate(a, b):
    if a is None:
        return b
    return a + b


def _topo_from(roots: Sequence[GradNode]) -> Dict[GradNode, int]:
    """BFS dependency counting (backward.cc:24-65 ``getInDegreeMap`` analog).

    Returns map node -> number of downstream nodes that feed cotangents into it.
    """
    indeg: Dict[GradNode, int] = {}
    seen = set(id(n) for n in roots)
    for n in roots:
        indeg.setdefault(n, 0)
    queue = deque(roots)
    while queue:
        node = queue.popleft()
        for t in node.inputs:
            nxt = t._grad_node
            if nxt is None:
                continue
            indeg[nxt] = indeg.get(nxt, 0) + 1
            if id(nxt) not in seen:
                seen.add(id(nxt))
                queue.append(nxt)
    return indeg


def _taped_vjp(node: GradNode, cotangents: Sequence[Any]) -> List[Any]:
    """Fire `node` as a NEW taped op over (primals, cotangents) so the
    returned input-gradients carry grad nodes of their own (create_graph).

    Re-linearizing through ``node.closure`` (not reusing ``node.vjp_fn``,
    which closes over the primals as constants) is what makes second-order
    terms like d(dy/dx)/dtheta correct — the vjp output depends on both the
    cotangent AND the primal inputs.
    """
    from paddle_tpu.framework.tensor import Tensor

    if node.closure is None:
        raise NotImplementedError(
            f"create_graph=True through {node.name}: this node records no "
            "re-differentiable forward closure (PyLayer backward is opaque "
            "to the tape)")
    n_in = len(node.inputs)
    multi = node.tuple_out

    def vjp_closure(*vals):
        primals, cts = vals[:n_in], vals[n_in:]
        _, fvjp = jax.vjp(node.closure, *primals)
        gs = fvjp(tuple(cts) if multi else cts[0])
        # single-input nodes return a bare array so the walk's
        # n_outputs==1 cotangent convention round-trips through jax.vjp
        return gs[0] if len(gs) == 1 else tuple(gs)

    in_tensors = list(node.inputs) + [
        c if isinstance(c, Tensor) else Tensor(c, stop_gradient=True)
        for c in cotangents]
    values = [t._value for t in in_tensors]
    out_vals, vjp_fn = jax.vjp(vjp_closure, *values)
    out_list = list(out_vals) if isinstance(out_vals, (tuple, list)) else [out_vals]
    avals = [(tuple(v.shape), getattr(v, "dtype", None)) for v in out_list]
    new_node = GradNode(f"grad_{node.name}", vjp_fn, in_tensors,
                        len(out_list), avals, closure=vjp_closure)
    outs: List[Any] = []
    for i, v in enumerate(out_list):
        if getattr(v, "dtype", None) == jax.dtypes.float0:
            outs.append(None)  # non-differentiable input slot
            continue
        t = Tensor(v, stop_gradient=False)
        t._grad_node = new_node
        t._out_index = i
        outs.append(t)
    return outs


def _apply_hooks(hooks: List[Callable], g):
    """Run slot hooks in registration order; each may return a replacement
    gradient (Tensor or array) or None to keep the current one."""
    from paddle_tpu.framework.tensor import Tensor

    is_tensor = isinstance(g, Tensor)
    cur = g if is_tensor else Tensor(g, stop_gradient=True)
    for fn in hooks:
        new = fn(cur)
        if new is not None:
            cur = new if isinstance(new, Tensor) else Tensor(new, stop_gradient=True)
    return cur if is_tensor else cur._value


def _run_backward(
    tensors: Sequence[Any],
    grad_tensors: Optional[Sequence[Any]],
    retain_graph: bool,
    accumulate_into_grad: bool,
    wanted: Optional[Dict[int, Any]] = None,
    create_graph: bool = False,
) -> Dict[int, Any]:
    """Core topological backward walk (RunBackward analog, backward.cc:105).

    Returns {id(tensor): cotangent} for leaves (and for `wanted` tensors).
    With ``create_graph`` the walk operates on Tensors and records every vjp
    as a fresh taped op, so the results are differentiable again.
    """
    from paddle_tpu.framework.tensor import Tensor  # local import, avoids cycle

    roots: List[GradNode] = []
    buffers: Dict[GradNode, List[Any]] = {}  # GradTensorHolder analog
    results: Dict[int, Any] = {}
    leaf_objs: Dict[int, Any] = {}  # id -> leaf Tensor (for deferred hooks)

    def as_grad(g):
        if create_graph:
            return g if isinstance(g, Tensor) else Tensor(g, stop_gradient=True)
        return g.value if isinstance(g, Tensor) else g

    def land_on_leaf(t, g):
        results[id(t)] = _accumulate(results.get(id(t)), as_grad(g))
        leaf_objs.setdefault(id(t), t)

    grad_tensors = grad_tensors or [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t.size != 1:
                raise ValueError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones(t.shape, t.dtype)
        g = as_grad(g)
        node = t._grad_node
        if node is None:
            # root is a leaf tensor
            if not t.stop_gradient:
                land_on_leaf(t, g)
            continue
        if node not in buffers:
            roots.append(node)  # dedupe: two outputs of one op share a node
        buf = buffers.setdefault(node, [None] * node.n_outputs)
        buf[t._out_index] = _accumulate(buf[t._out_index], g)

    indeg = _topo_from(roots)
    ready = deque(n for n in indeg if indeg[n] == 0 and n in buffers)

    def zeros_for(shape, dtype):
        if dtype == jax.dtypes.float0:
            import numpy as _np
            z = _np.zeros(shape, jax.dtypes.float0)
        else:
            z = jnp.zeros(shape, dtype)
        return Tensor(z, stop_gradient=True) if create_graph else z

    while ready:
        node = ready.popleft()
        buf = buffers.pop(node, None)
        if buf is not None:
            # fill missing output cotangents with zeros
            cotangents = [
                zeros_for(shape, dtype) if g is None else g
                for g, (shape, dtype) in zip(buf, node.out_avals)
            ]
            if node.hooks:
                for idx, fns in node.hooks.items():
                    cotangents[idx] = _apply_hooks(fns, cotangents[idx])
            if create_graph:
                in_grads = _taped_vjp(node, cotangents)
            else:
                if node.vjp_fn is None:
                    raise RuntimeError(
                        f"grad node {node.name} was already released; pass "
                        "retain_graph=True to backward() to allow a second backward pass")
                in_grads = node.vjp_fn(tuple(cotangents) if node.tuple_out
                                       else cotangents[0])
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
                if not retain_graph:
                    node.vjp_fn = None  # free residuals eagerly
            for t, g in zip(node.inputs, in_grads):
                if g is None:
                    continue  # non-differentiable (integer/bool) input
                gv = g._value if isinstance(g, Tensor) else g
                if getattr(gv, "dtype", None) == jax.dtypes.float0:
                    continue
                nxt = t._grad_node
                if nxt is None:
                    if not t.stop_gradient:
                        land_on_leaf(t, g)
                    elif wanted is not None and id(t) in wanted:
                        results[id(t)] = _accumulate(results.get(id(t)), as_grad(g))
                else:
                    nbuf = buffers.setdefault(nxt, [None] * nxt.n_outputs)
                    nbuf[t._out_index] = _accumulate(nbuf[t._out_index], as_grad(g))
                    if wanted is not None and id(t) in wanted:
                        results[id(t)] = _accumulate(results.get(id(t)), as_grad(g))
        # always release dependency counts, even when this node received no
        # cotangents (e.g. all contributions were float0) — upstream nodes may
        # still hold real gradients from other paths
        for t in node.inputs:
            nxt = t._grad_node
            if nxt is None:
                continue
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                ready.append(nxt)

    # leaf hooks fire ONCE with the fully-accumulated gradient (the
    # AccumulateGrad ordering: hooks run before .grad accumulation)
    for tid, t in leaf_objs.items():
        g = results[tid]
        if getattr(t, "_hooks", None):
            g = _apply_hooks(list(t._hooks.values()), g)
            results[tid] = g
        if accumulate_into_grad:
            t._accumulate_grad(g._value if isinstance(g, Tensor) else g)
    return results


def backward(tensors, grad_tensors=None, retain_graph: bool = False) -> None:
    """``loss.backward()`` entry (tensor_patch_methods.py:250 analog)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    _run_backward(tensors, grad_tensors, retain_graph, accumulate_into_grad=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph: Optional[bool] = None,
         create_graph: bool = False, allow_unused: bool = False):
    """``paddle.grad`` analog (GeneralGrad, paddle/fluid/eager/general_grad.h).

    Computes gradients of `outputs` w.r.t. `inputs` without touching `.grad`.
    """
    from paddle_tpu.framework.tensor import Tensor

    single = not isinstance(inputs, (list, tuple))
    if single:
        inputs = [inputs]
    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if retain_graph is None:
        retain_graph = create_graph
    wanted = {id(t): t for t in inputs}
    results = _run_backward(outputs, grad_outputs, retain_graph,
                            accumulate_into_grad=False, wanted=wanted,
                            create_graph=create_graph)
    out = []
    for t in inputs:
        g = results.get(id(t))
        if g is None and not allow_unused:
            raise ValueError(
                "one of the inputs receives no gradient; pass allow_unused=True "
                "to return None for it")
        if g is None:
            out.append(None)
        elif create_graph:
            # graph-connected result: differentiating it reaches back into
            # the original primals through the re-recorded vjp ops
            out.append(g if isinstance(g, Tensor) else Tensor(g, stop_gradient=True))
        else:
            out.append(Tensor(g._value if isinstance(g, Tensor) else g,
                              stop_gradient=True))
    return out[0] if single else out
