"""Define-by-run autograd tape.

TPU-native redesign of the reference's eager autograd engine
(paddle/fluid/eager/: ``AutogradMeta`` autograd_meta.h:61, ``GradNodeBase``
grad_node_info.h:197, ``egr::Backward`` backward.cc:439, topological queue
``RunBackward`` backward.cc:105, ``GradTensorHolder`` accumulation).

Instead of per-op hand-written C++ grad nodes, each recorded op captures a
``jax.vjp`` of its (pure, jax-traceable) forward. Backward is a host-side
topological walk over these nodes; every vjp call is itself an XLA-dispatched
computation, so gradients run on TPU like any forward op. Saved residuals live
inside the vjp closure (TensorWrapper analog, tensor_wrapper.h:39).
"""

from __future__ import annotations

import contextlib
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "is_grad_enabled", "no_grad", "enable_grad", "set_grad_enabled",
    "backward", "grad",
]


class _GradMode(threading.local):
    def __init__(self):
        self.enabled = True


_mode = _GradMode()


def is_grad_enabled() -> bool:
    return _mode.enabled


@contextlib.contextmanager
def set_grad_enabled(enabled: bool):
    prev = _mode.enabled
    _mode.enabled = bool(enabled)
    try:
        yield
    finally:
        _mode.enabled = prev


class no_grad(contextlib.ContextDecorator):
    """``paddle.no_grad`` analog — context manager *and* decorator."""

    def __enter__(self):
        self._prev = _mode.enabled
        _mode.enabled = False
        return self

    def __exit__(self, *exc):
        _mode.enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _mode.enabled
        _mode.enabled = True
        return self

    def __exit__(self, *exc):
        _mode.enabled = self._prev
        return False


class GradNode:
    """One recorded op on the tape.

    ``vjp_fn(cotangents_for_outputs) -> cotangents_for_inputs`` where inputs
    are the flat list of differentiable input tensors recorded in ``inputs``.
    """

    __slots__ = ("name", "vjp_fn", "inputs", "n_outputs", "out_avals", "__weakref__")

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence[Any], n_outputs: int,
                 out_avals: Sequence[Tuple[tuple, Any]]):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # list[Tensor]
        self.n_outputs = n_outputs
        self.out_avals = list(out_avals)  # [(shape, dtype)] per output

    def __repr__(self):
        return f"GradNode<{self.name}, n_in={len(self.inputs)}, n_out={self.n_outputs}>"


def _accumulate(a, b):
    if a is None:
        return b
    return a + b


def _topo_from(roots: Sequence[GradNode]) -> Dict[GradNode, int]:
    """BFS dependency counting (backward.cc:24-65 ``getInDegreeMap`` analog).

    Returns map node -> number of downstream nodes that feed cotangents into it.
    """
    indeg: Dict[GradNode, int] = {}
    seen = set(id(n) for n in roots)
    for n in roots:
        indeg.setdefault(n, 0)
    queue = deque(roots)
    while queue:
        node = queue.popleft()
        for t in node.inputs:
            nxt = t._grad_node
            if nxt is None:
                continue
            indeg[nxt] = indeg.get(nxt, 0) + 1
            if id(nxt) not in seen:
                seen.add(id(nxt))
                queue.append(nxt)
    return indeg


def _run_backward(
    tensors: Sequence[Any],
    grad_tensors: Optional[Sequence[Any]],
    retain_graph: bool,
    accumulate_into_grad: bool,
    wanted: Optional[Dict[int, Any]] = None,
) -> Dict[int, Any]:
    """Core topological backward walk (RunBackward analog, backward.cc:105).

    Returns {id(tensor): cotangent} for leaves (and for `wanted` tensors).
    """
    from paddle_tpu.framework.tensor import Tensor  # local import, avoids cycle

    roots: List[GradNode] = []
    buffers: Dict[GradNode, List[Any]] = {}  # GradTensorHolder analog
    results: Dict[int, Any] = {}

    grad_tensors = grad_tensors or [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t.size != 1:
                raise ValueError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones(t.shape, t.dtype)
        elif isinstance(g, Tensor):
            g = g.value
        node = t._grad_node
        if node is None:
            # root is a leaf tensor
            if not t.stop_gradient:
                results[id(t)] = _accumulate(results.get(id(t)), g)
            continue
        if node not in buffers:
            roots.append(node)  # dedupe: two outputs of one op share a node
        buf = buffers.setdefault(node, [None] * node.n_outputs)
        buf[t._out_index] = _accumulate(buf[t._out_index], g)

    indeg = _topo_from(roots)
    ready = deque(n for n in indeg if indeg[n] == 0 and n in buffers)

    while ready:
        node = ready.popleft()
        buf = buffers.pop(node, None)
        if buf is not None:
            # fill missing output cotangents with zeros
            cotangents = tuple(
                jnp.zeros(shape, dtype) if g is None else g
                for g, (shape, dtype) in zip(buf, node.out_avals)
            )
            if node.vjp_fn is None:
                raise RuntimeError(
                    f"grad node {node.name} was already released; pass "
                    "retain_graph=True to backward() to allow a second backward pass")
            in_grads = node.vjp_fn(cotangents if node.n_outputs > 1 else cotangents[0])
            if not isinstance(in_grads, (tuple, list)):
                in_grads = (in_grads,)
            if not retain_graph:
                node.vjp_fn = None  # free residuals eagerly
            for t, g in zip(node.inputs, in_grads):
                if g is None or getattr(g, "dtype", None) == jax.dtypes.float0:
                    continue  # non-differentiable (integer/bool) input
                nxt = t._grad_node
                if nxt is None:
                    if not t.stop_gradient:
                        results[id(t)] = _accumulate(results.get(id(t)), g)
                        if accumulate_into_grad:
                            t._accumulate_grad(g)
                    elif wanted is not None and id(t) in wanted:
                        results[id(t)] = _accumulate(results.get(id(t)), g)
                else:
                    nbuf = buffers.setdefault(nxt, [None] * nxt.n_outputs)
                    nbuf[t._out_index] = _accumulate(nbuf[t._out_index], g)
                    if wanted is not None and id(t) in wanted:
                        results[id(t)] = _accumulate(results.get(id(t)), g)
        # always release dependency counts, even when this node received no
        # cotangents (e.g. all contributions were float0) — upstream nodes may
        # still hold real gradients from other paths
        for t in node.inputs:
            nxt = t._grad_node
            if nxt is None:
                continue
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                ready.append(nxt)
    return results


def backward(tensors, grad_tensors=None, retain_graph: bool = False) -> None:
    """``loss.backward()`` entry (tensor_patch_methods.py:250 analog)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    _run_backward(tensors, grad_tensors, retain_graph, accumulate_into_grad=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph: Optional[bool] = None,
         create_graph: bool = False, allow_unused: bool = False):
    """``paddle.grad`` analog (GeneralGrad, paddle/fluid/eager/general_grad.h).

    Computes gradients of `outputs` w.r.t. `inputs` without touching `.grad`.
    """
    from paddle_tpu.framework.tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True on the eager tape is not supported; use "
            "paddle_tpu.incubate.autograd (jax.grad composition) for higher-order AD")
    single = not isinstance(inputs, (list, tuple))
    if single:
        inputs = [inputs]
    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if retain_graph is None:
        retain_graph = False
    wanted = {id(t): t for t in inputs}
    results = _run_backward(outputs, grad_outputs, retain_graph,
                            accumulate_into_grad=False, wanted=wanted)
    out = []
    for t in inputs:
        g = results.get(id(t))
        if g is None and not allow_unused:
            raise ValueError(
                "one of the inputs receives no gradient; pass allow_unused=True "
                "to return None for it")
        out.append(None if g is None else Tensor(g, stop_gradient=True))
    return out[0] if single else out
