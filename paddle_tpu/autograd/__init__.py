"""User-facing autograd API (python/paddle/autograd analog)."""

from paddle_tpu.autograd.tape import (  # noqa: F401
    backward, grad, no_grad, enable_grad, is_grad_enabled, set_grad_enabled,
    GradNode,
)
from paddle_tpu.autograd.py_layer import PyLayer, PyLayerContext  # noqa: F401
from paddle_tpu.autograd.functional import jacobian, hessian, jvp, vjp  # noqa: F401
