"""PyLayer — user-defined forward/backward pairs on the eager tape.

Analog of the reference's ``paddle.autograd.PyLayer``
(python/paddle/autograd/py_layer.py + C++ side paddle/fluid/eager/pylayer/).
The backward runs arbitrary Python (may itself call ops), so a PyLayer node's
"vjp" is the user function rather than a jax.vjp closure.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from paddle_tpu.autograd import tape
from paddle_tpu.framework.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved: Tuple[Tensor, ...] = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors) -> None:
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx: PyLayerContext, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx: PyLayerContext, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs: List[Tensor] = [a for a in args if isinstance(a, Tensor)]
        needs_grad = tape.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)

        with tape.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (tuple, list))
        outs = (outputs,) if single else tuple(outputs)
        tensor_outs = [o for o in outs if isinstance(o, Tensor)]

        if not needs_grad:
            for o in tensor_outs:
                o.stop_gradient = True
            return outputs

        out_avals = [(o.shape, o.dtype) for o in tensor_outs]

        def vjp_fn(cotangents):
            cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
            ct_tensors = [Tensor(c, stop_gradient=True) for c in cts]
            with tape.no_grad():
                in_grads = cls.backward(ctx, *ct_tensors)
            if not isinstance(in_grads, (tuple, list)):
                in_grads = (in_grads,)
            vals = []
            gi = iter(in_grads)
            for t in tensor_inputs:
                g = next(gi, None)
                if g is None:
                    vals.append(jnp.zeros(t.shape, t.dtype))
                else:
                    vals.append(g._value if isinstance(g, Tensor) else g)
            return tuple(vals)

        node = tape.GradNode(f"PyLayer<{cls.__name__}>", vjp_fn, tensor_inputs,
                             len(tensor_outs), out_avals)
        idx = 0
        for o in outs:
            if isinstance(o, Tensor):
                o._grad_node = node
                o._out_index = idx
                o.stop_gradient = False
                idx += 1
        return outputs
