"""Functional higher-order AD (python/paddle/autograd/autograd.py analog:
jacobian/hessian; incubate jvp). Implemented directly on JAX transforms —
higher-order AD composes for free, unlike the reference's separate "prim"
decomposition machinery (paddle/fluid/prim/)."""

from __future__ import annotations

import jax

from paddle_tpu.framework.tensor import Tensor

__all__ = ["jacobian", "hessian", "jvp", "vjp"]


def _fn_on_values(func):
    def wrapped(*values):
        tensors = [Tensor(v, stop_gradient=False) for v in values]
        out = func(*tensors)
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out
    return wrapped


def _values(xs):
    if isinstance(xs, (tuple, list)):
        return tuple(x._value if isinstance(x, Tensor) else x for x in xs)
    return (xs._value if isinstance(xs, Tensor) else xs,)


def jacobian(func, xs, create_graph: bool = False):
    vals = _values(xs)
    jac = jax.jacrev(_fn_on_values(func), argnums=tuple(range(len(vals))))(*vals)
    if not isinstance(xs, (tuple, list)):
        jac = jac[0] if isinstance(jac, tuple) else jac
        return Tensor(jac)
    return tuple(Tensor(j) for j in jac)


def hessian(func, xs, create_graph: bool = False):
    vals = _values(xs)
    hes = jax.hessian(_fn_on_values(func), argnums=tuple(range(len(vals))))(*vals)
    if not isinstance(xs, (tuple, list)):
        h = hes[0][0] if isinstance(hes, tuple) else hes
        return Tensor(h)
    return tuple(tuple(Tensor(h) for h in row) for row in hes)


def jvp(func, xs, v=None):
    vals = _values(xs)
    tangents = _values(v) if v is not None else tuple(
        jax.numpy.ones_like(x) for x in vals)
    out, tangent_out = jax.jvp(_fn_on_values(func), vals, tangents)
    wrap = lambda o: tuple(Tensor(x) for x in o) if isinstance(o, tuple) else Tensor(o)
    return wrap(out), wrap(tangent_out)


def vjp(func, xs, v=None):
    vals = _values(xs)
    out, vjp_fn = jax.vjp(_fn_on_values(func), *vals)
    if v is None:
        import jax.numpy as jnp
        v_vals = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(
            jnp.ones_like(o) for o in out)
    else:
        v_vals = _values(v)
        if not isinstance(out, tuple):
            v_vals = v_vals[0]
    grads = vjp_fn(v_vals)
    wrap = lambda o: tuple(Tensor(x) for x in o) if isinstance(o, tuple) else Tensor(o)
    return wrap(out), wrap(grads if len(grads) > 1 else grads[0])