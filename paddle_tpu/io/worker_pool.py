"""Multi-process DataLoader workers over the native shm ring.

Analog of _DataLoaderIterMultiProcess (python/paddle/io/dataloader/
dataloader_iter.py): worker subprocesses pull index lists from a task
pipe, build+collate batches, and push serialized numpy payloads through
the shared-memory ring (csrc/shm_queue.cpp) — the bulk tensor bytes never
transit a pickle pipe, mirroring the reference's shared-mem tensor
transport. Batch order is restored on the consumer side via sequence ids.
"""

from __future__ import annotations

import io as _io
import multiprocessing as mp
import os
import pickle
import queue as pyqueue
import struct
import threading
from typing import Optional

import numpy as np

from paddle_tpu.framework.tensor import Tensor

__all__ = ["MultiProcessIter"]


def _serialize_batch(seq: int, batch) -> bytes:
    """[seq u64][npy-count u32][npy blobs...][pickle rest]. Tensors/ndarrays
    go as raw .npy blobs (zero-pickle bulk); structure via a small pickle."""
    arrays = []

    def strip(obj):
        if isinstance(obj, Tensor):
            arrays.append(np.asarray(obj.value))
            return ("__arr__", len(arrays) - 1)
        if isinstance(obj, np.ndarray):
            arrays.append(obj)
            return ("__arr__", len(arrays) - 1)
        if isinstance(obj, (list, tuple)):
            return type(obj)(strip(x) for x in obj)
        if isinstance(obj, dict):
            return {k: strip(v) for k, v in obj.items()}
        return obj

    structure = strip(batch)
    out = bytearray(struct.pack("<QI", seq, len(arrays)))
    for a in arrays:
        buf = _io.BytesIO()
        np.save(buf, a, allow_pickle=False)
        blob = buf.getvalue()
        out += struct.pack("<I", len(blob))
        out += blob
    out += pickle.dumps(structure)
    return bytes(out)


def _deserialize_batch(data: bytes):
    seq, n = struct.unpack_from("<QI", data, 0)
    off = 12
    arrays = []
    for _ in range(n):
        (blen,) = struct.unpack_from("<I", data, off)
        off += 4
        arrays.append(np.load(_io.BytesIO(data[off:off + blen]),
                              allow_pickle=False))
        off += blen
    structure = pickle.loads(data[off:])

    def rebuild(obj):
        if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__arr__":
            return Tensor(arrays[obj[1]])
        if isinstance(obj, (list, tuple)):
            return type(obj)(rebuild(x) for x in obj)
        if isinstance(obj, dict):
            return {k: rebuild(v) for k, v in obj.items()}
        return obj

    return seq, rebuild(structure)


def _worker_main(dataset, collate_fn, qname, task_q, init_fn, wid):
    from paddle_tpu.native import ShmQueue
    if init_fn is not None:
        init_fn(wid)
    shm = ShmQueue(qname, create=False)
    while True:
        task = task_q.get()
        if task is None:
            break
        seq, indices = task
        batch = collate_fn([dataset[i] for i in indices])
        shm.push(_serialize_batch(seq, batch), timeout=300.0)


class MultiProcessIter:
    def __init__(self, loader):
        from paddle_tpu.native import ShmQueue
        self.loader = loader
        self._qname = f"ptdl_{os.getpid()}_{id(self) & 0xFFFF}"
        slot = 1 << 24  # 16MB batches
        self._shm = ShmQueue(self._qname, n_slots=2 * loader.num_workers + 2,
                             slot_size=slot, create=True)
        ctx = mp.get_context("spawn")
        self._task_q = ctx.Queue()
        self._procs = [
            ctx.Process(target=_worker_main,
                        args=(loader.dataset, loader.collate_fn, self._qname,
                              self._task_q, None, w), daemon=True)
            for w in range(loader.num_workers)
        ]
        for p in self._procs:
            p.start()
        self._batches = list(loader.batch_sampler)
        self._n = len(self._batches)
        self._sent = 0
        self._received = 0
        self._reorder = {}
        self._next_seq = 0
        # seed the pipeline: 2 outstanding tasks per worker
        for _ in range(min(self._n, 2 * loader.num_workers)):
            self._send_next()

    def _send_next(self):
        if self._sent < self._n:
            self._task_q.put((self._sent, self._batches[self._sent]))
            self._sent += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._next_seq >= self._n:
            self._shutdown()
            raise StopIteration
        while self._next_seq not in self._reorder:
            data = self._shm.pop(timeout=300.0)
            seq, batch = _deserialize_batch(data)
            self._reorder[seq] = batch
            self._received += 1
            self._send_next()
        batch = self._reorder.pop(self._next_seq)
        self._next_seq += 1
        return batch

    def _shutdown(self):
        for _ in self._procs:
            self._task_q.put(None)
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._shm.close()

    def __len__(self):
        return self._n

    def __del__(self):
        try:
            if any(p.is_alive() for p in self._procs):
                self._shutdown()
        except Exception:
            pass
