"""paddle_tpu.io — datasets + DataLoader (python/paddle/io analog).

DataLoader redesign for TPU: worker threads/processes feed a bounded prefetch
queue, and batches are transferred to device ahead of consumption (the role of
the reference's C++ BufferedReader double-buffering,
paddle/fluid/operators/reader/buffered_reader.cc).
"""

from paddle_tpu.io.dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    Subset, random_split, ConcatDataset,
)
from paddle_tpu.io.sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
    BatchSampler, DistributedBatchSampler, SubsetRandomSampler,
)
from paddle_tpu.io.dataloader import DataLoader, default_collate_fn  # noqa: F401
