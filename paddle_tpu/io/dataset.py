"""Dataset abstractions (python/paddle/io/dataloader/dataset.py analog)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        lens = {len(t) for t in tensors}
        assert len(lens) == 1, "tensors must have equal first dimension"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = datasets

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            sample = ds[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets: List[IterableDataset]):
        self.datasets = datasets

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)
        self.cumulative = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = int(np.searchsorted(self.cumulative, idx, side="right"))
        prev = 0 if ds_idx == 0 else self.cumulative[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    total = sum(lengths)
    assert total == len(dataset), "sum of lengths must equal dataset size"
    perm = np.random.permutation(total)
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out
