"""DataLoader with background prefetch.

Analog of python/paddle/io/reader.py ``DataLoader`` (:216) +
``_DataLoaderIterMultiProcess`` (dataloader/dataloader_iter.py) + the C++
``BufferedReader`` device prefetch (paddle/fluid/operators/reader/
buffered_reader.cc). TPU design: worker threads collate numpy batches into a
bounded queue; the consumer thread converts to device arrays ahead of use
(XLA transfers are async, so enqueueing the device_put is the double-buffer).
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Callable, Optional

import numpy as np

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.io.dataset import Dataset, IterableDataset
from paddle_tpu.io.sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        import jax.numpy as jnp
        return Tensor(jnp.stack([s.value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class _PrefetchIter:
    def __init__(self, loader: "DataLoader"):
        self.loader = loader
        self.queue: "queue.Queue" = queue.Queue(maxsize=loader.prefetch_factor)
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._produce, daemon=True)
        self._worker.start()

    def _produce(self):
        try:
            for batch in self.loader._iter_batches():
                if self._stop.is_set():
                    return
                self.queue.put(batch)
            self.queue.put(_SENTINEL)
        except BaseException as e:  # propagate worker errors to consumer
            self.queue.put(_ExcWrapper(e))

    def __iter__(self):
        return self

    def __next__(self):
        item = self.queue.get()
        if item is _SENTINEL:
            raise StopIteration
        if isinstance(item, _ExcWrapper):
            raise item.exc
        return item

    def __del__(self):
        self._stop.set()


_SENTINEL = object()


class _ExcWrapper:
    def __init__(self, exc):
        self.exc = exc


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler: Optional[BatchSampler] = None,
                 batch_size: int = 1, shuffle: bool = False, drop_last: bool = False,
                 collate_fn: Optional[Callable] = None, num_workers: int = 0,
                 use_buffer_reader: bool = True, prefetch_factor: int = 2,
                 use_shared_memory: bool = True, timeout: int = 0,
                 worker_init_fn=None, persistent_workers: bool = False):
        self.dataset = dataset
        self.num_workers = num_workers
        self.use_buffer_reader = use_buffer_reader
        self.prefetch_factor = max(2, prefetch_factor)
        self.collate_fn = collate_fn or default_collate_fn
        self._is_iterable = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._is_iterable:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def _iter_batches(self):
        if self._is_iterable:
            it = iter(self.dataset)
            if self.batch_size is None:
                for sample in it:
                    yield sample
                return
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield self.collate_fn(batch)

    def __iter__(self):
        if self.num_workers > 0 and not self._is_iterable:
            from paddle_tpu.io.worker_pool import MultiProcessIter
            return MultiProcessIter(self)
        if self.use_buffer_reader:
            return _PrefetchIter(self)
        return self._iter_batches()

    def __len__(self):
        if self._is_iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)
