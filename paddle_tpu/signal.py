"""paddle_tpu.signal — STFT/iSTFT (python/paddle/signal.py analog).

Layout parity with the reference: ``frame(..., axis=-1)`` returns
(..., frame_length, num_frames); ``axis=0`` returns
(num_frames, frame_length, ...). stft returns (..., n_fft//2+1, frames)
for onesided input, matching paddle.signal.stft.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import register_op

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _frames_last(x, frame_length: int, hop_length: int):
    """(..., T) -> (..., num_frames, frame_length)."""
    n = x.shape[-1]
    num = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num)[:, None])
    return x[..., idx]


@register_op("frame", ref="python/paddle/signal.py frame")
def frame(x, frame_length: int, hop_length: int, axis: int = -1):
    if axis in (-1, x.ndim - 1):
        f = _frames_last(x, frame_length, hop_length)
        return jnp.swapaxes(f, -1, -2)     # (..., frame_length, num_frames)
    if axis == 0:
        f = _frames_last(jnp.moveaxis(x, 0, -1), frame_length, hop_length)
        # (..., num, fl) -> (num, fl, ...)
        return jnp.moveaxis(f, (-2, -1), (0, 1))
    raise ValueError("frame: axis must be 0 or -1")


@register_op("overlap_add", ref="python/paddle/signal.py overlap_add")
def overlap_add(x, hop_length: int, axis: int = -1):
    if axis in (-1, x.ndim - 1):
        frames = jnp.swapaxes(x, -1, -2)   # (..., num, fl)
    elif axis == 0:
        frames = jnp.moveaxis(x, (0, 1), (-2, -1))
    else:
        raise ValueError("overlap_add: axis must be 0 or -1")
    *batch, num, flen = frames.shape
    out_len = (num - 1) * hop_length + flen
    out = jnp.zeros((*batch, out_len), frames.dtype)
    for i in range(num):
        out = out.at[..., i * hop_length:i * hop_length + flen].add(
            frames[..., i, :])
    if axis == 0 and x.ndim > 2:
        out = jnp.moveaxis(out, -1, 0)
    return out


def _window_arr(window, win_length):
    if window is None:
        return jnp.ones((win_length,), jnp.float32)
    return window.value if isinstance(window, Tensor) else jnp.asarray(window)


@register_op("stft", ref="python/paddle/signal.py stft")
def _stft_op(x, n_fft, hop_length, win_length, window, center, pad_mode,
             normalized, onesided):
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = _frames_last(x, n_fft, hop_length)      # (..., num, n_fft)
    w = _window_arr(window, win_length)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))
    spec = jnp.fft.rfft(frames * w, axis=-1) if onesided else \
        jnp.fft.fft(frames * w, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    return jnp.swapaxes(spec, -1, -2)  # (..., freq, num_frames)


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    return _stft_op(x, n_fft, hop_length, win_length, window, center,
                    pad_mode, normalized, onesided)


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    spec = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    spec = jnp.swapaxes(spec, -1, -2)      # (..., frames, freq)
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    frames = (jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided
              else jnp.fft.ifft(spec, axis=-1).real)
    w = _window_arr(window, win_length)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))

    def _ola(fr):  # (..., num, fl) -> (..., T)
        *batch, num, flen = fr.shape
        out_len = (num - 1) * hop_length + flen
        out = jnp.zeros((*batch, out_len), fr.dtype)
        for i in range(num):
            out = out.at[..., i * hop_length:i * hop_length + flen].add(
                fr[..., i, :])
        return out

    sig = _ola(frames * w)
    wsq = _ola(jnp.broadcast_to(w * w, frames.shape))
    sig = sig / jnp.maximum(wsq, 1e-10)
    if center:
        sig = sig[..., n_fft // 2:]
        if length is not None:
            sig = sig[..., :length]
        else:
            sig = sig[..., :sig.shape[-1] - n_fft // 2]
    elif length is not None:
        sig = sig[..., :length]
    return Tensor(sig)
