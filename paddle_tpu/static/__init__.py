"""paddle_tpu.static — static-graph compatibility namespace.

Analog of python/paddle/static/ (P10). TPU-native reality: "static mode"
IS tracing + XLA compilation, so Program/Executor here are thin recorders
over the jit machinery — `Program` captures a traced function, `Executor`
compiles and runs it, `save/load_inference_model` round-trips a traced
function + weights (serving export, SURVEY M10).
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "data", "Executor",
           "save_inference_model", "load_inference_model", "gradients",
           "name_scope", "BuildStrategy", "nn"]

from paddle_tpu.static import nn  # noqa: E402,F401 (control flow ops)


class BuildStrategy:
    """Graph-build options (paddle.static.BuildStrategy analog).

    The reference's fuse_* switches turn on PIR fusion passes; here they
    select jaxpr rewrite rules (paddle_tpu/passes) that jit.to_static
    applies to the traced graph before XLA compilation. ``passes`` accepts
    additional user rules (RewriteRule/EqnRule instances)."""

    def __init__(self):
        self.fuse_rms_norm = False
        self.amp_dtype: Optional[str] = None   # e.g. "bfloat16"
        self.decompositions: Optional[dict] = None
        self.passes: list = []

    def build_rules(self) -> list:
        from paddle_tpu import passes as P
        rules: list = list(self.passes)
        if self.fuse_rms_norm:
            rules.append(P.fuse_rms_norm_rule())
        if self.amp_dtype:
            rules.extend(P.amp_cast_rules(self.amp_dtype))
        if self.decompositions is not None:
            rules.extend(P.decomposition_rules(self.decompositions))
        return rules


class InputSpec:
    """paddle.static.InputSpec parity (shape with None dims, dtype, name)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def example(self):
        shape = tuple(1 if s in (None, -1) else s for s in self.shape)
        import jax.numpy as jnp
        return Tensor(jnp.zeros(shape, dtype=self.dtype))

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype!r}, name={self.name!r})"


class Program:
    """Holds a traced callable + its input specs (ProgramDesc stand-in)."""

    def __init__(self):
        self.fn = None
        self.input_specs: List[InputSpec] = []
        self._feed_order: List[str] = []

    def clone(self, for_test: bool = False):
        p = Program()
        p.fn = self.fn
        p.input_specs = list(self.input_specs)
        p._feed_order = list(self._feed_order)
        return p

    def __repr__(self):
        return f"Program(inputs={[s.name for s in self.input_specs]})"


_MAIN = Program()
_STARTUP = Program()


def default_main_program() -> Program:
    return _MAIN


def default_startup_program() -> Program:
    return _STARTUP


class program_guard:
    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        global _MAIN, _STARTUP
        self._prev = (_MAIN, _STARTUP)
        _MAIN = self.main
        if self.startup is not None:
            _STARTUP = self.startup
        return self

    def __exit__(self, *exc):
        global _MAIN, _STARTUP
        _MAIN, _STARTUP = self._prev
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> InputSpec:
    spec = InputSpec(shape, dtype, name)
    _MAIN.input_specs.append(spec)
    _MAIN._feed_order.append(name)
    return spec


class Executor:
    """paddle.static.Executor parity over jit (executor.py:1174 analog)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy: bool = True):
        program = program or _MAIN
        if program.fn is None:
            raise ValueError("Program has no traced function; use "
                             "paddle.jit.to_static or load_inference_model")
        feed = feed or {}
        args = [Tensor(np.asarray(feed[n])) for n in program._feed_order]
        out = program.fn(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        if return_numpy:
            return [o.numpy() if isinstance(o, Tensor) else np.asarray(o)
                    for o in outs]
        return list(outs)


def gradients(targets, inputs, target_gradients=None):
    """static gradients API -> tape grad (base/backward.py append_backward
    capability analog, computed by transform instead of transpiler)."""
    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    loss = ts[0]
    for t in ts[1:]:
        loss = loss + paddle.sum(t)
    return paddle.grad(loss, xs, retain_graph=True, allow_unused=True)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program: Optional[Program] = None, **kwargs) -> None:
    """Export a traced layer/function + weights (static/io.py analog)."""
    program = program or _MAIN
    layer = kwargs.get("layer")
    fn = kwargs.get("fn") or program.fn
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    state = {}
    if layer is not None:
        state = {k: v.numpy() for k, v in layer.state_dict().items()}
        fn = layer
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump({"specs": [(s.shape, s.dtype, s.name)
                               for s in (feed_vars or [])],
                     "has_layer": layer is not None}, f)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump(state, f)
    if fn is not None and layer is None:
        import warnings
        warnings.warn("save_inference_model without layer saves specs+weights "
                      "only; pass layer= for a loadable module")


def load_inference_model(path_prefix: str, executor=None, model_cls=None,
                         **kwargs):
    with open(path_prefix + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    with open(path_prefix + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    if model_cls is not None:
        net = model_cls()
        net.set_state_dict({k: Tensor(v) for k, v in state.items()})
        net.eval()
        prog = Program()
        prog.fn = paddle.jit.to_static(net)
        specs = [InputSpec(s, d, n) for s, d, n in meta["specs"]]
        prog.input_specs = specs
        prog._feed_order = [s.name for s in specs]
        return prog, [s.name for s in specs], []
    return meta, state
