"""paddle_tpu.static.nn — static-graph networking ops (control flow).

Analog of python/paddle/static/nn/control_flow.py. TPU-native design:
the reference builds IR region ops (build_if_op / build_while_op,
paddle/fluid/pir/dialect/operator/ir/control_flow_op.h); here the SAME
user API lowers straight onto XLA's structured control flow —
``lax.cond`` / ``lax.while_loop`` / ``lax.switch`` — when the inputs are
traced, and to plain Python control flow when eager (where predicates
are concrete, so running just the taken branch is both exact and
autograd-friendly; mirrors dygraph-mode behavior of the reference API).
"""

from paddle_tpu.static.nn.control_flow import (  # noqa: F401
    Assert, Print, case, cond, switch_case, while_loop,
)

__all__ = ["cond", "while_loop", "case", "switch_case", "Assert", "Print"]
