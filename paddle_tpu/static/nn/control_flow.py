"""Structured control flow ops (cond / while_loop / case / switch_case).

Reference: python/paddle/static/nn/control_flow.py (user API) over the IR
region ops in paddle/fluid/pir/dialect/operator/ir/control_flow_op.h.
TPU-native lowering:

- traced predicate (inside jit/to_static) -> ``lax.cond`` /
  ``lax.while_loop`` / ``lax.switch``: one compiled XLA program, no
  graph break, no host round-trip;
- concrete predicate (eager) -> ordinary Python control flow running only
  the taken branch on the autograd tape (the reference's dygraph-mode
  semantics: its static control-flow APIs execute ``true_fn()`` directly
  when ``in_dygraph_mode()``).

Contract carried over from XLA's structured ops: under tracing, both/all
branch functions are traced, so they must be pure and return matching
pytrees (same structure, shapes and dtypes); ``while_loop`` bodies must
keep loop-var shapes/dtypes invariant. Reverse-mode autodiff through an
UNBOUNDED traced ``while_loop`` is not defined (XLA limitation); pass
``max_iters`` to lower the loop to a masked ``lax.scan``, which supports
reverse-mode AD — the round-5 analog of the reference's
``while_grad_block`` (python/paddle/autograd/ir_backward.py:783).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.framework.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case", "Assert", "Print"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_tensor(x):
    return isinstance(x, Tensor)


def _unwrap_tree(out):
    return jax.tree_util.tree_map(_unwrap, out, is_leaf=_is_tensor)


def _wrap_tree(out):
    def w(v):
        if isinstance(v, (jax.Array, jax.core.Tracer)):
            return Tensor(v)
        return v
    return jax.tree_util.tree_map(w, out)


def _pred_value(pred):
    """Unwrap a predicate to a scalar jnp bool; report whether it is
    concrete (eager) or traced."""
    pv = _unwrap(pred)
    pv = jnp.asarray(pv)
    if pv.size != 1:
        raise ValueError(
            f"control-flow predicate must be a scalar, got shape {pv.shape}")
    pv = pv.reshape(()).astype(bool)
    traced = isinstance(pv, jax.core.Tracer)
    return pv, traced


def _branch_thunk(fn: Optional[Callable]):
    """A zero-arg branch as lax expects: run the user fn (or nothing),
    hand back a pure pytree of jnp values."""
    def thunk(_):
        out = fn() if fn is not None else None
        return _unwrap_tree(out)
    return thunk


def cond(pred, true_fn: Callable = None, false_fn: Callable = None,
         name: Optional[str] = None, return_names=None):
    """Run ``true_fn()`` if ``pred`` else ``false_fn()``.

    Parity: python/paddle/static/nn/control_flow.py::cond (If op,
    control_flow_op.h). Traced -> ``lax.cond`` (both branches traced,
    matching pytrees required); eager -> only the taken branch runs.
    """
    pv, traced = _pred_value(pred)
    if not traced:
        fn = true_fn if bool(pv) else false_fn
        return fn() if fn is not None else None
    try:
        out = lax.cond(pv, _branch_thunk(true_fn), _branch_thunk(false_fn),
                       None)
    except TypeError as e:
        if isinstance(e, jax.errors.JAXTypeError):
            raise  # tracer/concretization errors keep their identity so
            #        to_static's graph-break fallback can still catch them
        raise TypeError(
            "cond: true_fn and false_fn must return the same pytree "
            f"structure, shapes and dtypes under tracing ({e})") from e
    return _wrap_tree(out)


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: Optional[str] = None,
               max_iters: Optional[int] = None):
    """``while cond(*vars): vars = body(*vars)``; returns the final vars.

    Parity: python/paddle/static/nn/control_flow.py::while_loop (While
    op, with gradients via ir_backward.py while_grad_block). Traced:

    - ``max_iters=None`` -> ``lax.while_loop``: true data-dependent trip
      count, forward-only (XLA's while has no reverse-mode AD);
    - ``max_iters=K`` -> ``lax.scan`` over K steps with an active mask:
      the body runs K times, updates are select-masked once the
      predicate goes false, so the result equals the unbounded loop
      whenever the true trip count is <= K — and reverse-mode AD works
      (this is the round-5 answer to the reference's while_grad_block).
      ``K`` must genuinely bound the trip count: the loop is truncated
      at K regardless of the predicate (the masked tail contributes
      zero gradient either way).

    Eager -> Python while on the tape (gradients always work).
    """
    if not loop_vars:
        raise ValueError("loop_vars cannot be empty")
    p0, traced = _pred_value(cond(*loop_vars))
    if not traced:
        vars_ = tuple(loop_vars)
        pv = p0
        n = 0
        while bool(pv):
            if max_iters is not None and n >= max_iters:
                break  # bound checked BEFORE the body: max_iters=0 runs it
                #        zero times, matching the traced scan path
            out = body(*vars_)
            vars_ = tuple(out) if isinstance(out, (list, tuple)) else (out,)
            if len(vars_) != len(loop_vars):
                raise ValueError(
                    f"body returned {len(vars_)} vars, expected "
                    f"{len(loop_vars)}")
            n += 1
            pv = _pred_value(cond(*vars_))[0]
        return list(vars_)

    init = tuple(jax.tree_util.tree_map(_unwrap, v, is_leaf=_is_tensor)
                 for v in loop_vars)

    def body_fn(carry):
        out = body(*_wrap_tree(list(carry)))
        out = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        return tuple(_unwrap_tree(v) for v in out)

    if max_iters is not None:
        def scan_step(carry, _):
            active, vars_ = carry
            new = body_fn(vars_)
            merged = jax.tree_util.tree_map(
                lambda n_, o: jnp.where(active, n_, o), new, vars_)
            still, _ = _pred_value(cond(*_wrap_tree(list(merged))))
            return (jnp.logical_and(active, still), merged), None

        (_, final), _ = lax.scan(scan_step, (p0, init), None,
                                 length=int(max_iters))
        return [x for x in _wrap_tree(list(final))]

    def cond_fn(carry):
        pv, _ = _pred_value(cond(*_wrap_tree(list(carry))))
        return pv

    final = lax.while_loop(cond_fn, body_fn, init)
    return [x for x in _wrap_tree(list(final))]


def case(pred_fn_pairs, default: Callable = None,
         name: Optional[str] = None):
    """Run the fn of the FIRST true predicate (reference ``case``
    semantics); ``default`` (or the last fn) if none is true.

    Traced -> a fold of ``lax.cond``s (first-match-wins preserved by
    nesting from the back).
    """
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs cannot be empty")
    preds = [p for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    if default is None:
        default = fns[-1]
    traced = any(_pred_value(p)[1] for p in preds)
    if not traced:
        for p, f in zip(preds, fns):
            if bool(_pred_value(p)[0]):
                return f()
        return default()
    out = _branch_thunk(default)(None)
    for p, f in reversed(list(zip(preds, fns))):
        pv, _ = _pred_value(p)
        prev = out
        out = lax.cond(pv, _branch_thunk(f), lambda _, prev=prev: prev, None)
    return _wrap_tree(out)


def switch_case(branch_index, branch_fns, default: Callable = None,
                name: Optional[str] = None):
    """Run ``branch_fns[branch_index]``; ``default`` (or the last fn,
    reference semantics) when the index matches no branch.

    Traced -> ``lax.switch`` over densified branches.
    """
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns)) \
            if branch_fns and callable(branch_fns[0]) \
            else sorted(branch_fns)
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]
    bi, traced = _pred_value(branch_index)
    if not traced:
        k = int(jnp.asarray(_unwrap(branch_index)).reshape(()))
        for key, f in items:
            if key == k:
                return f()
        return default()
    bi = jnp.asarray(_unwrap(branch_index)).reshape(()).astype(jnp.int32)
    pos = jnp.full((), len(keys), jnp.int32)    # default slot
    for i, k in enumerate(keys):
        pos = jnp.where(bi == k, jnp.int32(i), pos)
    out = lax.switch(pos, [_branch_thunk(f) for f in fns]
                     + [_branch_thunk(default)], None)
    return _wrap_tree(out)


def Assert(cond, data=None, summarize: int = 20, name: Optional[str] = None):
    """Assert ``cond`` holds; on failure print up to ``summarize``
    elements of each tensor in ``data``.

    Parity: control_flow.py::Assert (build_assert_op). Eager -> raises
    ValueError immediately. Traced -> ``jax.debug.callback`` raising from
    the host once the value is available (XLA has no abort op; the error
    surfaces at the next host sync, the documented best effort).
    """
    pv, traced = _pred_value(cond)
    datavals = [_unwrap(d) for d in (data or [])]

    def _fail(pred, *vals):
        if not bool(pred):
            shown = "; ".join(
                str(jnp.asarray(v).reshape(-1)[:summarize]) for v in vals)
            raise ValueError(
                f"Assert{'(' + name + ')' if name else ''} failed. {shown}")

    if not traced:
        _fail(pv, *datavals)
        return None
    jax.debug.callback(_fail, pv, *datavals)
    return None


def Print(input, first_n: int = -1, message: Optional[str] = None,
          summarize: int = 20, print_tensor_name: bool = True,
          print_tensor_type: bool = True, print_tensor_shape: bool = True,
          print_tensor_layout: bool = True, print_tensor_lod: bool = True,
          print_phase: str = "both"):
    """Print a tensor's value when it is produced; returns the input
    (identity, so it can be spliced into a graph). Traced ->
    ``jax.debug.print`` (prints from the device stream)."""
    v = _unwrap(input)
    msg = (message + " ") if message else ""
    if isinstance(v, jax.core.Tracer):
        jax.debug.print(msg + "{x}", x=v)
    else:
        print(f"{msg}{jnp.asarray(v)}")
    return input
