"""Guard specialization around non-bool graph breaks (round-5 VERDICT 4).

The reference's SOT (python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py:1594) splits the bytecode at a graph break and executes
compiled subgraphs on both sides. This module gets the same effect the
TPU-native way — whole-program specialization with runtime guards —
without touching bytecode:

- a ``record`` context rides along the eager fallback call (the "probe"):
  every concretization (``Tensor.numpy()`` — the single choke point that
  ``__int__``/``__float__``/``__bool__``-fallback/``item``/``tolist``/
  ``__array__`` all route through) is recorded in call order;
- a ``replay`` context rides a fresh jax trace of the same function: each
  concretization site returns the recorded value as a Python constant (so
  the trace proceeds compiled THROUGH the break) and, when the site's
  tensor is traced, its tracer is appended as an extra program output — a
  runtime GUARD;
- the caller compares guard outputs against the baked values on every
  specialized call: equal -> the compiled result is exact; different ->
  guard miss, re-probe eagerly and (budget permitting) build a new
  specialization keyed by the new values.

Correctness contract: a specialized program is used only when its guards
verify, so results are always exact; the costs of a miss are one wasted
compiled execution plus the eager re-probe. Functions whose concretized
values change every call (e.g. ``float(loss)`` logging) exhaust
``flags.to_static_max_specializations`` and settle on permanent eager —
the round-4 behavior, now the floor instead of the only option.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import numpy as np

__all__ = ["ConcContext", "ConcMismatch", "capture", "resolve_numpy",
           "active"]


class ConcMismatch(Exception):
    """Replay hit a different concretization sequence than the probe."""


class ConcContext:
    __slots__ = ("mode", "values", "cursor", "guards", "guard_idx",
                 "max_elems", "failed", "trace_state")

    def __init__(self, mode: str, values: Optional[List[np.ndarray]] = None,
                 max_elems: int = 64):
        assert mode in ("record", "replay")
        self.mode = mode
        self.values: List[np.ndarray] = list(values) if values else []
        self.cursor = 0
        self.guards: list = []       # tracers (replay) -> guard outputs
        self.guard_idx: List[int] = []  # which recorded site each guard is
        self.max_elems = max_elems
        self.failed: Optional[str] = None
        # replay: the trace this context rides; a concretization hit in a
        # DEEPER trace (lax.cond branch / loop body) cannot become a guard
        # output — its tracer would escape that inner scope
        from paddle_tpu.jit.cond_capture import opaque_trace_state
        self.trace_state = (opaque_trace_state()
                            if mode == "replay" else None)


# per-thread, like the sibling trace-key / grad-mode stacks: another
# thread's Tensor.numpy() (watchdog, DataLoader worker, RPC) must not
# leak into a probe/replay running on this thread
_tls = threading.local()


def _stack() -> List[ConcContext]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def active() -> Optional[ConcContext]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class capture:
    """Context manager activating a :class:`ConcContext`."""

    def __init__(self, ctx: ConcContext):
        self.ctx = ctx

    def __enter__(self):
        _stack().append(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        _stack().pop()
        return False


def resolve_numpy(value):
    """Called from ``Tensor.numpy()``. Returns the ndarray to hand back,
    or ``None`` when no context is active (normal concretization)."""
    ctx = active()
    if ctx is None:
        return None
    if ctx.mode == "record":
        arr = np.asarray(value)
        if arr.size > ctx.max_elems:
            # too big to bake/guard; the probe keeps running correctly,
            # the specialization just won't be built
            ctx.failed = (f"concretized {arr.size}-element array exceeds "
                          f"the guard budget ({ctx.max_elems})")
            return arr
        ctx.values.append(np.array(arr, copy=True))
        return arr
    # replay
    if ctx.cursor >= len(ctx.values):
        raise ConcMismatch(
            "replay hit more concretization sites than the probe recorded")
    baked = ctx.values[ctx.cursor]
    site = ctx.cursor
    ctx.cursor += 1
    if isinstance(value, jax.core.Tracer):
        from paddle_tpu.jit.cond_capture import opaque_trace_state
        if opaque_trace_state() != ctx.trace_state:
            raise ConcMismatch(
                "concretization inside a nested traced region (lax.cond "
                "branch / loop body) cannot be guard-specialized")
        if (tuple(value.shape) != tuple(baked.shape)
                or np.dtype(value.dtype) != baked.dtype):
            raise ConcMismatch(
                f"concretization site {site} changed shape/dtype between "
                f"probe ({baked.shape}/{baked.dtype}) and replay "
                f"({value.shape}/{value.dtype})")
        ctx.guards.append(value)
        ctx.guard_idx.append(site)
        return baked
    return np.asarray(value)
