"""to_static implementation."""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.autograd import tape
from paddle_tpu.framework import random as rnd
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from paddle_tpu.jit.cond_capture import CaptureMismatch, CaptureOverflow
from paddle_tpu.ops.registry import OpDef, apply_op

__all__ = ["to_static", "StaticFunction", "not_to_static"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_tensor_leaf(x):
    return isinstance(x, Tensor)


def _aval_key(vals) -> tuple:
    """Shape/dtype signature of the flat argument list. Trace-time
    metadata (treedef/n_out/buf_names/guard_idx) is stored PER aval key:
    jax.jit keeps one compiled entry per input avals, but the metadata
    cell is a plain dict written only on (re)trace — under alternating
    shapes a cached-shape call would otherwise read the OTHER shape's
    stale guard count and slice its outputs wrong (ADVICE r5, medium)."""
    return tuple((tuple(v.shape), jnp.dtype(v.dtype).name) for v in vals)


class StaticFunction:
    """Callable wrapping a fn/Layer with capture-compile-cache semantics.

    Redesign of dy2static's ``StaticFunction``/``partial_program`` (python/
    paddle/jit/dy2static/program_translator.py): instead of AST transforms +
    a traced ProgramDesc run through the ``run_program`` op, the function is
    jax-traced into one compiled executable. Parameters/buffers are lifted to
    inputs (no weight constants baked in); the executable is recorded as a
    single op on the autograd tape so ``backward()`` differentiates through
    it; buffer mutations (BatchNorm stats) are returned and written back.
    Shape/dtype guards + recompilation come from jax.jit's dispatch cache
    (the SOT guard machinery analog, python/paddle/jit/sot/).
    """

    def __init__(self, function: Callable, input_spec=None, build_strategy=None,
                 full_graph: bool = True, backend=None):
        self._layer: Optional[Layer] = None
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        else:
            self._fn = function
            if hasattr(function, "__self__") and isinstance(function.__self__, Layer):
                self._layer = function.__self__
        self._input_spec = input_spec
        # BuildStrategy fuse/amp/decomposition switches -> jaxpr rewrite
        # rules applied to the traced graph (passes/rewrite.py engine).
        # Resolved lazily at first compile so strategy mutations after
        # decoration still take effect (paddle reads it at build time).
        self._build_strategy = build_strategy
        self._pass_rules: list = []
        self._rules_resolved = False
        try:
            functools.update_wrapper(self, self._fn)
        except Exception:
            pass
        self._cache: Dict[Any, Tuple[OpDef, dict]] = {}
        self._warned_break = False  # one-time graph-break warning
        # cache keys that graph-broke -> specialization state:
        # {"specs": [{"values", "opdef", "cell"}], "permanent": bool}.
        # Round 5: a break no longer means permanent eager — the eager
        # fallback doubles as a probe and later calls run a compiled
        # guard-specialized program (see _call_broken / conc_capture.py)
        self._broken: Dict[Any, dict] = {}

    def _make_body(self, static_kwargs: tuple, training: bool, n_state: int,
                   state_names: Tuple[str, ...], cell: dict):
        layer = self._layer
        fn = self._fn

        def body(flat_args, key):
            state_vals = flat_args[:n_state]
            arg_vals = flat_args[n_state:]
            sub = cell.setdefault(_aval_key(flat_args), {})
            kwargs = dict(static_kwargs)
            rnd.push_trace_key(key)
            try:
                with tape.no_grad():
                    if layer is not None:
                        from paddle_tpu.nn.utils import functional_call
                        state = dict(zip(state_names, state_vals))
                        prev_mode = layer.training
                        (layer.train() if training else layer.eval())
                        try:
                            out, new_buffers = functional_call(
                                layer, state,
                                tuple(Tensor(a) for a in arg_vals), kwargs)
                        finally:
                            (layer.train() if prev_mode else layer.eval())
                    else:
                        out = fn(*[Tensor(a) for a in arg_vals], **kwargs)
                        new_buffers = {}
                    out_vals = jax.tree_util.tree_map(_unwrap, out,
                                                      is_leaf=_is_tensor_leaf)
                    leaves, treedef = jax.tree_util.tree_flatten(out_vals)
                    buf_names = [n for n in state_names if n in new_buffers]
                    if "treedef" in sub and sub["treedef"] != treedef:
                        # branch-capture re-run produced a different output
                        # STRUCTURE (e.g. dict vs tuple) — leaves alone
                        # can't reveal this; bail to the eager fallback
                        raise CaptureMismatch(
                            "data-dependent branches returned different "
                            f"pytree structures: {sub['treedef']} vs "
                            f"{treedef}")
                    sub["treedef"] = treedef
                    sub["n_out"] = len(leaves)
                    sub["buf_names"] = buf_names
                    return tuple(leaves) + tuple(new_buffers[n] for n in buf_names)
            finally:
                rnd.pop_trace_key()

        return body

    def _make_impl(self, static_kwargs: tuple, training: bool, n_state: int,
                   state_names: Tuple[str, ...], cell: dict):
        body = self._make_body(static_kwargs, training, n_state, state_names,
                               cell)

        def impl(*flat_args, key):
            # data-dependent Python bools fork the trace into per-path
            # re-runs combined with lax.cond (jit/cond_capture.py) — the
            # RNG key push/pop lives INSIDE body so every explored path
            # replays an identical random stream
            from paddle_tpu.flags import flags
            from paddle_tpu.jit.cond_capture import explore
            # treedef equality is only meaningful WITHIN one exploration
            # (a shape-specialized retrace may legitimately change the
            # output structure via static Python branching)
            cell.setdefault(_aval_key(flat_args), {}).pop("treedef", None)
            return explore(lambda: body(flat_args, key),
                           max_paths=flags.to_static_max_cond_paths,
                           max_while_iters=flags.to_static_max_while_iters)

        return impl

    def _make_replay_impl(self, static_kwargs: tuple, training: bool,
                          n_state: int, state_names: Tuple[str, ...],
                          cell: dict, baked_values: list):
        """A guard-specialized trace: concretizations replay the probe's
        recorded values as constants; their traced tensors are appended
        as guard outputs (jit/conc_capture.py)."""
        body = self._make_body(static_kwargs, training, n_state, state_names,
                               cell)

        def impl(*flat_args, key):
            from paddle_tpu.jit import conc_capture
            sub = cell.setdefault(_aval_key(flat_args), {})
            sub.pop("treedef", None)
            ctx = conc_capture.ConcContext("replay", values=baked_values)
            with conc_capture.capture(ctx):
                outs = body(flat_args, key)
            sub["guard_idx"] = list(ctx.guard_idx)
            return tuple(outs) + tuple(ctx.guards)

        return impl

    def _resolve_pass_rules(self) -> list:
        # resolved ONCE, at the first compile: every cached specialization of
        # this StaticFunction must share one rule set (mutating the strategy
        # between calls would otherwise fork numerics across cache entries)
        if self._rules_resolved:
            return self._pass_rules
        bs = self._build_strategy
        if bs is not None:
            if hasattr(bs, "build_rules"):
                self._pass_rules = bs.build_rules()
            elif isinstance(bs, (list, tuple)):
                self._pass_rules = list(bs)
            else:
                raise TypeError(
                    "build_strategy must be a static.BuildStrategy or a list "
                    f"of rewrite rules, got {type(bs).__name__}")
        self._rules_resolved = True
        return self._pass_rules

    def __call__(self, *args, **kwargs):
        static_kwargs = tuple(sorted(kwargs.items()))
        training = self._layer.training if self._layer is not None else False

        if self._layer is not None:
            state = dict(self._layer.state_dict())
            for name, b in self._layer.named_buffers():
                state.setdefault(name, b)
            state_names = tuple(state.keys())
            state_tensors = [state[n] for n in state_names]
        else:
            state_names = ()
            state_tensors = []

        cache_key = (static_kwargs, training, state_names)
        state = self._broken.get(cache_key)
        if state is not None:
            # a prior call graph-broke on this specialization: serve it
            # from a guard-specialized compiled program when one matches,
            # else eagerly (probing for a new specialization)
            return self._call_broken(state, cache_key, args, kwargs,
                                     static_kwargs, training, state_names,
                                     state_tensors)
        entry = self._cache.get(cache_key)
        if entry is None:
            cell: dict = {}
            impl = self._make_impl(static_kwargs, training, len(state_tensors),
                                   state_names, cell)
            rules = self._resolve_pass_rules()
            if rules:
                from paddle_tpu.passes.rewrite import rewrite as _rewrite
                impl = _rewrite(impl, rules)
            jitted = jax.jit(impl, static_argnames=())
            opdef = OpDef(f"to_static<{getattr(self._fn, '__name__', 'fn')}>",
                          jitted, n_outputs=-1)
            entry = (opdef, cell)
            self._cache[cache_key] = entry
        opdef, cell = entry

        key = rnd.split_key()
        tensor_args = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
                       for a in args]

        try:
            outs = apply_op(opdef, tuple(state_tensors + tensor_args),
                            {"key": key})
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.TracerArrayConversionError,
                CaptureOverflow, CaptureMismatch):
            # GRAPH BREAK: data-dependent bools are first captured into
            # lax.cond (jit/cond_capture.py, round 4) — this fallback now
            # only fires for int/array concretization, branches whose
            # outputs mismatch across paths, or a blown path budget.
            # Round 5 (SOT parity, jit/sot subgraph execution analog): the
            # eager fallback call doubles as a PROBE that records the
            # concretized values; later calls run a compiled program with
            # those values baked in and runtime guards verifying them
            # (jit/conc_capture.py). STAT counters: to_static_graph_breaks
            # (eager-served calls), to_static_partial_compiled_calls
            # (guard-specialized compiled calls), to_static_guard_misses.
            state = self._broken.setdefault(
                cache_key, {"specs": [], "permanent": False})
            if not self._warned_break:
                self._warned_break = True
                import warnings
                warnings.warn(
                    f"to_static<{getattr(self._fn, '__name__', 'fn')}>: "
                    "data-dependent control flow could not be captured "
                    "into lax.cond (int/array concretization, mismatched "
                    "branch outputs, or path budget exceeded); serving "
                    "these calls EAGERLY while guard-specializing "
                    "(use paddle.where or paddle.static.nn.cond/"
                    "while_loop to stay compiled)",
                    stacklevel=2)
            return self._call_broken(state, cache_key, args, kwargs,
                                     static_kwargs, training, state_names,
                                     state_tensors)
        akey = _aval_key([t._value for t in state_tensors + tensor_args])
        return self._finish_outputs(outs, cell[akey])

    def _finish_outputs(self, outs, sub: dict, n_guards: int = 0):
        """Shared compiled-call epilogue: slice leaves/buffers(/guards),
        write mutated buffers back, unflatten the user pytree. ``sub`` is
        THIS call's per-aval trace metadata (see ``_aval_key``)."""
        if not isinstance(outs, tuple):
            outs = (outs,)
        n_out = sub["n_out"]
        end = len(outs) - n_guards
        buf_outs = outs[n_out:end]
        if self._layer is not None and buf_outs:
            buffers = dict(self._layer.named_buffers())
            for name, v in zip(sub["buf_names"], buf_outs):
                buffers[name]._set_value(v._value)
        return jax.tree_util.tree_unflatten(sub["treedef"],
                                            list(outs[:n_out]))

    def _call_broken(self, state: dict, cache_key, args, kwargs,
                     static_kwargs, training, state_names, state_tensors):
        """Serve a graph-broken specialization: compiled when a
        guard-specialized program's baked concretizations verify at
        runtime, eager (recording a new specialization) otherwise."""
        import numpy as np

        from paddle_tpu.flags import flags
        from paddle_tpu.framework.monitor import stat_add
        from paddle_tpu.jit import conc_capture

        # 1. most-recent specialization first (each trial costs one
        #    execution, so only one is tried per call); a run of
        #    consecutive misses marks the key permanent-eager so a
        #    never-matching function stops paying a wasted compiled run
        if state["specs"] and not state["permanent"]:
            spec = state["specs"][-1]
            key = rnd.split_key()
            tensor_args = [a if isinstance(a, Tensor)
                           else Tensor(jnp.asarray(a)) for a in args]
            try:
                outs = apply_op(spec["opdef"],
                                tuple(state_tensors + tensor_args),
                                {"key": key})
            except (conc_capture.ConcMismatch,
                    jax.errors.TracerBoolConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerIntegerConversionError,
                    jax.errors.TracerArrayConversionError,
                    CaptureOverflow, CaptureMismatch):
                # replay trace failed (non-deterministic concretization
                # sequence, nested break, ...): drop THIS spec and count
                # it toward the guard-miss window — a single shape-driven
                # mismatch must not pin the whole cache key to eager
                # forever (ADVICE r5); the miss-limit/budget paths decide
                # permanence. Anything else (user error, OOM) propagates
                # untouched.
                state["specs"].pop()
                state["misses"] = state.get("misses", 0) + 1
                if state["misses"] >= flags.to_static_guard_miss_limit:
                    state["permanent"] = True
            else:
                if not isinstance(outs, tuple):
                    outs = (outs,)
                akey = _aval_key(
                    [t._value for t in state_tensors + tensor_args])
                sub = spec["cell"][akey]
                n_guards = len(sub["guard_idx"])
                guard_outs = outs[len(outs) - n_guards:] if n_guards else ()
                baked = [spec["values"][i] for i in sub["guard_idx"]]
                if all(np.array_equal(np.asarray(g._value), b)
                       for g, b in zip(guard_outs, baked)):
                    stat_add("to_static_partial_compiled_calls")
                    state["misses"] = 0
                    return self._finish_outputs(outs, sub, n_guards)
                stat_add("to_static_guard_misses")
                state["misses"] = state.get("misses", 0) + 1
                if state["misses"] >= flags.to_static_guard_miss_limit:
                    state["permanent"] = True

        # 2. eager probe: correct results now, a new specialization for
        #    later calls (unless the budget or guard limits say otherwise)
        stat_add("to_static_graph_breaks")
        build = not state["permanent"]
        ctx = conc_capture.ConcContext(
            "record", max_elems=flags.to_static_max_guard_elems)
        if build:
            with conc_capture.capture(ctx):
                out = (self._layer(*args, **kwargs)
                       if self._layer is not None
                       else self._fn(*args, **kwargs))
        else:
            out = (self._layer(*args, **kwargs) if self._layer is not None
                   else self._fn(*args, **kwargs))
            return out
        if ctx.failed or not ctx.values:
            # nothing to specialize on (break came from elsewhere) or a
            # concretization too large to guard: eager is the end state
            state["permanent"] = True
            return out
        # reuse before build: a spec already baked for these exact values
        # just wasn't the most-recent one — move it to MRU instead of
        # compiling a duplicate (and burning the budget)
        for i, spec in enumerate(state["specs"]):
            if (len(spec["values"]) == len(ctx.values)
                    and all(np.array_equal(a, b) for a, b in
                            zip(spec["values"], ctx.values))):
                state["specs"].append(state["specs"].pop(i))
                return out
        if len(state["specs"]) >= flags.to_static_max_specializations:
            return out
        cell2: dict = {}
        impl2 = self._make_replay_impl(static_kwargs, training,
                                       len(state_tensors), state_names,
                                       cell2, list(ctx.values))
        rules = self._resolve_pass_rules()
        if rules:
            from paddle_tpu.passes.rewrite import rewrite as _rewrite
            impl2 = _rewrite(impl2, rules)
        opdef2 = OpDef(
            f"to_static_spec<{getattr(self._fn, '__name__', 'fn')}>",
            jax.jit(impl2), n_outputs=-1)
        state["specs"].append(
            {"values": list(ctx.values), "opdef": opdef2, "cell": cell2})
        return out

    @property
    def code(self) -> str:
        import inspect
        try:
            return inspect.getsource(self._fn)
        except Exception:
            return "<source unavailable>"


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph: bool = True, **kwargs):
    """``paddle.jit.to_static`` analog (decorator or direct call)."""

    def decorate(fn):
        return StaticFunction(fn, input_spec=input_spec,
                              build_strategy=build_strategy,
                              full_graph=full_graph, backend=backend)

    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn
