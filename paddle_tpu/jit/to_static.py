"""to_static implementation."""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.autograd import tape
from paddle_tpu.framework import random as rnd
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from paddle_tpu.jit.cond_capture import CaptureMismatch, CaptureOverflow
from paddle_tpu.ops.registry import OpDef, apply_op

__all__ = ["to_static", "StaticFunction", "not_to_static"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_tensor_leaf(x):
    return isinstance(x, Tensor)


class StaticFunction:
    """Callable wrapping a fn/Layer with capture-compile-cache semantics.

    Redesign of dy2static's ``StaticFunction``/``partial_program`` (python/
    paddle/jit/dy2static/program_translator.py): instead of AST transforms +
    a traced ProgramDesc run through the ``run_program`` op, the function is
    jax-traced into one compiled executable. Parameters/buffers are lifted to
    inputs (no weight constants baked in); the executable is recorded as a
    single op on the autograd tape so ``backward()`` differentiates through
    it; buffer mutations (BatchNorm stats) are returned and written back.
    Shape/dtype guards + recompilation come from jax.jit's dispatch cache
    (the SOT guard machinery analog, python/paddle/jit/sot/).
    """

    def __init__(self, function: Callable, input_spec=None, build_strategy=None,
                 full_graph: bool = True, backend=None):
        self._layer: Optional[Layer] = None
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        else:
            self._fn = function
            if hasattr(function, "__self__") and isinstance(function.__self__, Layer):
                self._layer = function.__self__
        self._input_spec = input_spec
        # BuildStrategy fuse/amp/decomposition switches -> jaxpr rewrite
        # rules applied to the traced graph (passes/rewrite.py engine).
        # Resolved lazily at first compile so strategy mutations after
        # decoration still take effect (paddle reads it at build time).
        self._build_strategy = build_strategy
        self._pass_rules: list = []
        self._rules_resolved = False
        try:
            functools.update_wrapper(self, self._fn)
        except Exception:
            pass
        self._cache: Dict[Any, Tuple[OpDef, dict]] = {}
        self._warned_break = False  # one-time graph-break warning
        self._broken: set = set()   # cache keys that graph-broke: go
        #                             straight to eager, don't re-trace

    def _make_impl(self, static_kwargs: tuple, training: bool, n_state: int,
                   state_names: Tuple[str, ...], cell: dict):
        layer = self._layer
        fn = self._fn

        def body(flat_args, key):
            state_vals = flat_args[:n_state]
            arg_vals = flat_args[n_state:]
            kwargs = dict(static_kwargs)
            rnd.push_trace_key(key)
            try:
                with tape.no_grad():
                    if layer is not None:
                        from paddle_tpu.nn.utils import functional_call
                        state = dict(zip(state_names, state_vals))
                        prev_mode = layer.training
                        (layer.train() if training else layer.eval())
                        try:
                            out, new_buffers = functional_call(
                                layer, state,
                                tuple(Tensor(a) for a in arg_vals), kwargs)
                        finally:
                            (layer.train() if prev_mode else layer.eval())
                    else:
                        out = fn(*[Tensor(a) for a in arg_vals], **kwargs)
                        new_buffers = {}
                    out_vals = jax.tree_util.tree_map(_unwrap, out,
                                                      is_leaf=_is_tensor_leaf)
                    leaves, treedef = jax.tree_util.tree_flatten(out_vals)
                    buf_names = [n for n in state_names if n in new_buffers]
                    if "treedef" in cell and cell["treedef"] != treedef:
                        # branch-capture re-run produced a different output
                        # STRUCTURE (e.g. dict vs tuple) — leaves alone
                        # can't reveal this; bail to the eager fallback
                        raise CaptureMismatch(
                            "data-dependent branches returned different "
                            f"pytree structures: {cell['treedef']} vs "
                            f"{treedef}")
                    cell["treedef"] = treedef
                    cell["n_out"] = len(leaves)
                    cell["buf_names"] = buf_names
                    return tuple(leaves) + tuple(new_buffers[n] for n in buf_names)
            finally:
                rnd.pop_trace_key()

        def impl(*flat_args, key):
            # data-dependent Python bools fork the trace into per-path
            # re-runs combined with lax.cond (jit/cond_capture.py) — the
            # RNG key push/pop lives INSIDE body so every explored path
            # replays an identical random stream
            from paddle_tpu.flags import flags
            from paddle_tpu.jit.cond_capture import explore
            # treedef equality is only meaningful WITHIN one exploration
            # (a shape-specialized retrace may legitimately change the
            # output structure via static Python branching)
            cell.pop("treedef", None)
            return explore(lambda: body(flat_args, key),
                           max_paths=flags.to_static_max_cond_paths,
                           max_while_iters=flags.to_static_max_while_iters)

        return impl

    def _resolve_pass_rules(self) -> list:
        # resolved ONCE, at the first compile: every cached specialization of
        # this StaticFunction must share one rule set (mutating the strategy
        # between calls would otherwise fork numerics across cache entries)
        if self._rules_resolved:
            return self._pass_rules
        bs = self._build_strategy
        if bs is not None:
            if hasattr(bs, "build_rules"):
                self._pass_rules = bs.build_rules()
            elif isinstance(bs, (list, tuple)):
                self._pass_rules = list(bs)
            else:
                raise TypeError(
                    "build_strategy must be a static.BuildStrategy or a list "
                    f"of rewrite rules, got {type(bs).__name__}")
        self._rules_resolved = True
        return self._pass_rules

    def __call__(self, *args, **kwargs):
        static_kwargs = tuple(sorted(kwargs.items()))
        training = self._layer.training if self._layer is not None else False

        if self._layer is not None:
            state = dict(self._layer.state_dict())
            for name, b in self._layer.named_buffers():
                state.setdefault(name, b)
            state_names = tuple(state.keys())
            state_tensors = [state[n] for n in state_names]
        else:
            state_names = ()
            state_tensors = []

        cache_key = (static_kwargs, training, state_names)
        if cache_key in self._broken:
            # a prior call graph-broke on this specialization: skip the
            # (expensive, guaranteed-to-fail) re-trace entirely
            from paddle_tpu.framework.monitor import stat_add
            stat_add("to_static_graph_breaks")
            if self._layer is not None:
                return self._layer(*args, **kwargs)
            return self._fn(*args, **kwargs)
        entry = self._cache.get(cache_key)
        if entry is None:
            cell: dict = {}
            impl = self._make_impl(static_kwargs, training, len(state_tensors),
                                   state_names, cell)
            rules = self._resolve_pass_rules()
            if rules:
                from paddle_tpu.passes.rewrite import rewrite as _rewrite
                impl = _rewrite(impl, rules)
            jitted = jax.jit(impl, static_argnames=())
            opdef = OpDef(f"to_static<{getattr(self._fn, '__name__', 'fn')}>",
                          jitted, n_outputs=-1)
            entry = (opdef, cell)
            self._cache[cache_key] = entry
        opdef, cell = entry

        key = rnd.split_key()
        tensor_args = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
                       for a in args]

        try:
            outs = apply_op(opdef, tuple(state_tensors + tensor_args),
                            {"key": key})
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.TracerArrayConversionError,
                CaptureOverflow, CaptureMismatch):
            # GRAPH BREAK: data-dependent bools are first captured into
            # lax.cond (jit/cond_capture.py, round 4) — this fallback now
            # only fires for int/array concretization, branches whose
            # outputs mismatch across paths, or a blown path budget.
            # The reference's SOT (jit/sot/opcode_translator) splits the
            # bytecode into subgraphs around the break; the contract here
            # is fall-back-to-eager per call (correct results, no
            # compile) with a one-time warning + a STAT counter
            # (to_static_graph_breaks) so the break is observable.
            from paddle_tpu.framework.monitor import stat_add
            stat_add("to_static_graph_breaks")
            self._broken.add(cache_key)
            if not self._warned_break:
                self._warned_break = True
                import warnings
                warnings.warn(
                    f"to_static<{getattr(self._fn, '__name__', 'fn')}>: "
                    "data-dependent control flow could not be captured "
                    "into lax.cond (int/array concretization, mismatched "
                    "branch outputs, or path budget exceeded); falling "
                    "back to EAGER for these calls (use paddle.where or "
                    "paddle.static.nn.cond/while_loop to stay compiled)",
                    stacklevel=2)
            if self._layer is not None:
                return self._layer(*args, **kwargs)
            return self._fn(*args, **kwargs)
        if not isinstance(outs, tuple):
            outs = (outs,)
        n_out = cell["n_out"]
        out_leaves = list(outs[:n_out])
        buf_outs = outs[n_out:]
        if self._layer is not None and buf_outs:
            buffers = dict(self._layer.named_buffers())
            for name, v in zip(cell["buf_names"], buf_outs):
                buffers[name]._set_value(v._value)
        return jax.tree_util.tree_unflatten(cell["treedef"], out_leaves)

    @property
    def code(self) -> str:
        import inspect
        try:
            return inspect.getsource(self._fn)
        except Exception:
            return "<source unavailable>"


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph: bool = True, **kwargs):
    """``paddle.jit.to_static`` analog (decorator or direct call)."""

    def decorate(fn):
        return StaticFunction(fn, input_spec=input_spec,
                              build_strategy=build_strategy,
                              full_graph=full_graph, backend=backend)

    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn):
    fn._not_to_static = True
    return fn
