"""Capture Python ``if tensor:`` branches into ``lax.cond`` under tracing.

Round-4 answer to the reference's first-class IR control flow
(paddle/fluid/pir/dialect/operator/ir/control_flow_op.h) + SOT branch
handling (python/paddle/jit/sot/): when a jit trace hits ``bool()`` on a
traced tensor, instead of graph-breaking to eager, ``to_static`` now
RE-RUNS the function once per outcome of each data-dependent bool — a
decision-tree exploration — and combines the per-path results with
``lax.cond`` on the recorded predicates. The whole function stays one
compiled XLA program with zero graph breaks.

Mechanics. ``Tensor.__bool__`` consults the active :class:`CaptureContext`
when its value is a tracer. If the context has a forced decision for this
bool site, it returns it; otherwise it raises :class:`Fork` carrying the
predicate. :func:`explore` drives the runs depth-first, forcing ``True``
then ``False`` at each newly discovered site, and folds the leaves back
together bottom-up.

Semantics and limits (documented fallback rules — violating any of these
falls back to the round-3 eager graph-break, observable via the
``to_static_graph_breaks`` STAT):

- branch purity: every path is executed during tracing, so branch side
  effects (Python state mutation, appends) happen for ALL paths;
- matching outputs: all paths must produce the same pytree structure,
  shapes and dtypes (:class:`CaptureMismatch` otherwise);
- path budget: at most ``flags.to_static_max_cond_paths`` leaf paths
  (:class:`CaptureOverflow` beyond it) — each data-dependent bool doubles
  the count, so deeply branchy functions belong on
  ``paddle.static.nn.cond`` instead;
- the function must be deterministic across re-runs (same bools hit in
  the same order); the RNG trace key is re-pushed per run so random ops
  replay identically;
- both sides of every branch are computed and the result selected
  (select semantics, like ``paddle.where``) — pick static.nn.cond for
  lazy single-branch execution of expensive branches.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, List

import jax
import jax.numpy as jnp

__all__ = ["explore", "resolve_traced_bool", "CaptureOverflow",
           "CaptureMismatch", "Fork", "opaque_trace_state"]


def opaque_trace_state():
    """jax.core.get_opaque_trace_state grew a required ``convention``
    argument (which it ignores) in newer jax; accept both signatures."""
    try:
        return jax.core.get_opaque_trace_state()
    except TypeError:
        return jax.core.get_opaque_trace_state(convention="flax")


class Fork(Exception):
    """A new data-dependent bool site was hit; carries the predicate and
    the bool site identity (code object + bytecode offset of the caller)
    so :func:`explore` can recognize a ``while tensor:`` spine — the same
    site forking once per iteration."""

    def __init__(self, pred, site=None):
        super().__init__("data-dependent bool (capture fork)")
        self.pred = pred
        self.site = site


class CaptureOverflow(Exception):
    """More leaf paths than the flags.to_static_max_cond_paths budget."""


class CaptureMismatch(Exception):
    """Paths produced different pytree structures/shapes/dtypes."""


class CaptureContext:
    __slots__ = ("decisions", "cursor", "trace_state")

    def __init__(self, decisions: List[bool]):
        self.decisions = decisions
        self.cursor = 0
        # identity of the trace explore() runs under: bool sites hit in a
        # DEEPER trace (a lax.cond branch / loop body) cannot be captured
        # here — their predicate tracer would be dead at our combine level
        self.trace_state = opaque_trace_state()


_stack: List[CaptureContext] = []


def resolve_traced_bool(value) -> bool:
    """Called by ``Tensor.__bool__`` on a traced value. Returns the forced
    decision for this site, raises :class:`Fork` at a new site, or returns
    ``None`` when no capture is active / the value is not a scalar (the
    caller then falls through to the plain concretization error)."""
    if not _stack:
        return None
    aval = getattr(value, "aval", None)
    if aval is None or getattr(aval, "size", None) != 1:
        return None
    ctx = _stack[-1]
    if opaque_trace_state() != ctx.trace_state:
        # nested traced region: fall through to the ordinary
        # concretization error -> to_static graph-breaks cleanly
        return None
    if ctx.cursor < len(ctx.decisions):
        d = ctx.decisions[ctx.cursor]
        ctx.cursor += 1
        return d
    try:
        # frame 0 = here, 1 = Tensor.__bool__, 2 = the bool() call site
        f = sys._getframe(2)
        site = (id(f.f_code), f.f_lasti)
    except Exception:
        site = None
    raise Fork(jnp.asarray(value).reshape(()).astype(bool), site)


def explore(thunk: Callable[[], Any], max_paths: int = 16,
            max_while_iters: int | None = None):
    """Run ``thunk`` under bool-capture; return its output with every
    data-dependent branch folded into ``lax.cond``.

    ``max_while_iters`` (round 5): a ``while tensor:`` loop forks at the
    SAME bool site once per iteration — an all-True spine that would
    otherwise explore forever and overflow. When a single site has been
    forced True ``max_while_iters`` times along a path, the next fork at
    that site is TRUNCATED: the False branch is taken unconditionally and
    a runtime check (jax.debug.callback) errors if that path is live with
    the predicate still True — so a loop that respects the bound compiles
    exactly (and differentiably, via the lax.cond fold), and one that
    exceeds it at runtime errors loudly instead of silently truncating.

    Zero overhead when no fork occurs (single run, returned as-is)."""

    n_runs = 0
    # a full binary tree with max_paths leaves takes 2*max_paths - 1 runs;
    # bounding RUNS (not just completed leaves) also catches the
    # non-terminating case — a data-dependent `while tensor:` at an
    # unrecognizable site (site=None) forks on an all-True spine forever
    # and never completes a single leaf
    max_runs = 2 * max_paths

    def run(decisions: List[bool]):
        nonlocal n_runs
        n_runs += 1
        if n_runs > max_runs:
            raise CaptureOverflow(
                f"data-dependent branch capture exceeded {max_runs} "
                f"exploration runs (budget {max_paths} paths) — an "
                f"unbounded `while tensor:` loop cannot be captured; "
                f"use paddle.static.nn.while_loop")
        ctx = CaptureContext(list(decisions))
        _stack.append(ctx)
        try:
            return ("leaf", thunk())
        except Fork as f:
            return ("fork", f.pred, f.site)
        finally:
            _stack.pop()

    n_leaves = 0

    def build(prefix: List[bool], spine: dict):
        # spine: per-site count of True decisions along this path
        nonlocal n_leaves
        r = run(prefix)
        if r[0] == "leaf":
            n_leaves += 1
            if n_leaves > max_paths:
                raise CaptureOverflow(
                    f"data-dependent branch capture exceeded "
                    f"{max_paths} paths")
            return r
        pred, site = r[1], r[2]
        from paddle_tpu.framework.monitor import stat_add
        if (max_while_iters is not None and site is not None
                and spine.get(site, 0) >= max_while_iters):
            if not _callbacks_supported():
                # the truncation contract needs the runtime check; without
                # host callbacks (axon tunnel) fall back to the round-4
                # graph-break -> eager path, which is always correct
                raise CaptureOverflow(
                    "`while tensor:` exceeded to_static_max_while_iters "
                    "during capture and this backend has no host "
                    "callbacks for the runtime bound check")
            stat_add("to_static_while_truncations")
            # the forced False is a loop EXIT at this site too: reset its
            # spine count so a later sequential loop at the same site gets
            # a fresh iteration budget instead of truncating at iter 0
            return ("trunc", pred, build(prefix + [False], {**spine, site: 0}))
        stat_add("to_static_cond_captures")
        # True extends this site's spine; False is a loop EXIT at this
        # site — reset its count so a later, sequential loop at the same
        # site gets a fresh iteration budget
        return ("node", pred,
                build(prefix + [True], {**spine, site: spine.get(site, 0) + 1}),
                build(prefix + [False], {**spine, site: 0}))

    return _combine(build([], {}))


def _callbacks_supported() -> bool:
    # the axon PJRT tunnel does not implement host send/recv callbacks
    # (io_callback / pure_callback / debug.callback); cpu/tpu/gpu do
    return jax.default_backend() in ("cpu", "tpu", "gpu", "cuda", "rocm")


def _trunc_check(violation):
    if bool(violation):
        raise RuntimeError(
            "to_static: a captured `while tensor:` loop exceeded the "
            "to_static_max_while_iters bound at runtime — its result was "
            "truncated. Raise paddle.set_flags({'to_static_max_while_iters'"
            ": N}) above the loop's true trip count, or use "
            "paddle.static.nn.while_loop(max_iters=...).")


def _combine(tree, path_pred=None):
    if tree[0] == "leaf":
        return tree[1]
    if tree[0] == "trunc":
        _, pred, sub = tree
        viol = pred if path_pred is None else jnp.logical_and(path_pred, pred)
        jax.debug.callback(_trunc_check, viol)
        return _combine(sub, path_pred)
    _, pred, t, f = tree
    tv, tdef = jax.tree_util.tree_flatten(
        _combine(t, pred if path_pred is None
                 else jnp.logical_and(path_pred, pred)))
    fv, fdef = jax.tree_util.tree_flatten(
        _combine(f, jnp.logical_not(pred) if path_pred is None
                 else jnp.logical_and(path_pred, jnp.logical_not(pred))))
    if tdef != fdef:
        raise CaptureMismatch(
            f"branches produced different pytree structures: {tdef} vs "
            f"{fdef}")
    for a, b in zip(tv, fv):
        sa = (jnp.shape(a), jnp.result_type(a))
        sb = (jnp.shape(b), jnp.result_type(b))
        if sa != sb:
            raise CaptureMismatch(
                f"branches produced mismatched leaves: {sa} vs {sb}")
    try:
        outs = jax.lax.cond(pred, lambda: tuple(tv), lambda: tuple(fv))
    except jax.errors.UnexpectedTracerError as e:
        # the bool site was hit inside an INNER trace (a static.nn.cond
        # branch / lax loop body): its predicate tracer is dead out here.
        # Surface as a capture failure so to_static graph-breaks cleanly.
        raise CaptureMismatch(
            "data-dependent bool inside a nested traced region cannot be "
            f"captured ({e})") from e
    return jax.tree_util.tree_unflatten(tdef, list(outs))
