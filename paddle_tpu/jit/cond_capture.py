"""Capture Python ``if tensor:`` branches into ``lax.cond`` under tracing.

Round-4 answer to the reference's first-class IR control flow
(paddle/fluid/pir/dialect/operator/ir/control_flow_op.h) + SOT branch
handling (python/paddle/jit/sot/): when a jit trace hits ``bool()`` on a
traced tensor, instead of graph-breaking to eager, ``to_static`` now
RE-RUNS the function once per outcome of each data-dependent bool — a
decision-tree exploration — and combines the per-path results with
``lax.cond`` on the recorded predicates. The whole function stays one
compiled XLA program with zero graph breaks.

Mechanics. ``Tensor.__bool__`` consults the active :class:`CaptureContext`
when its value is a tracer. If the context has a forced decision for this
bool site, it returns it; otherwise it raises :class:`Fork` carrying the
predicate. :func:`explore` drives the runs depth-first, forcing ``True``
then ``False`` at each newly discovered site, and folds the leaves back
together bottom-up.

Semantics and limits (documented fallback rules — violating any of these
falls back to the round-3 eager graph-break, observable via the
``to_static_graph_breaks`` STAT):

- branch purity: every path is executed during tracing, so branch side
  effects (Python state mutation, appends) happen for ALL paths;
- matching outputs: all paths must produce the same pytree structure,
  shapes and dtypes (:class:`CaptureMismatch` otherwise);
- path budget: at most ``flags.to_static_max_cond_paths`` leaf paths
  (:class:`CaptureOverflow` beyond it) — each data-dependent bool doubles
  the count, so deeply branchy functions belong on
  ``paddle.static.nn.cond`` instead;
- the function must be deterministic across re-runs (same bools hit in
  the same order); the RNG trace key is re-pushed per run so random ops
  replay identically;
- both sides of every branch are computed and the result selected
  (select semantics, like ``paddle.where``) — pick static.nn.cond for
  lazy single-branch execution of expensive branches.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax
import jax.numpy as jnp

__all__ = ["explore", "resolve_traced_bool", "CaptureOverflow",
           "CaptureMismatch", "Fork"]


class Fork(Exception):
    """A new data-dependent bool site was hit; carries the predicate."""

    def __init__(self, pred):
        super().__init__("data-dependent bool (capture fork)")
        self.pred = pred


class CaptureOverflow(Exception):
    """More leaf paths than the flags.to_static_max_cond_paths budget."""


class CaptureMismatch(Exception):
    """Paths produced different pytree structures/shapes/dtypes."""


class CaptureContext:
    __slots__ = ("decisions", "cursor", "trace_state")

    def __init__(self, decisions: List[bool]):
        self.decisions = decisions
        self.cursor = 0
        # identity of the trace explore() runs under: bool sites hit in a
        # DEEPER trace (a lax.cond branch / loop body) cannot be captured
        # here — their predicate tracer would be dead at our combine level
        self.trace_state = jax.core.get_opaque_trace_state()


_stack: List[CaptureContext] = []


def resolve_traced_bool(value) -> bool:
    """Called by ``Tensor.__bool__`` on a traced value. Returns the forced
    decision for this site, raises :class:`Fork` at a new site, or returns
    ``None`` when no capture is active / the value is not a scalar (the
    caller then falls through to the plain concretization error)."""
    if not _stack:
        return None
    aval = getattr(value, "aval", None)
    if aval is None or getattr(aval, "size", None) != 1:
        return None
    ctx = _stack[-1]
    if jax.core.get_opaque_trace_state() != ctx.trace_state:
        # nested traced region: fall through to the ordinary
        # concretization error -> to_static graph-breaks cleanly
        return None
    if ctx.cursor < len(ctx.decisions):
        d = ctx.decisions[ctx.cursor]
        ctx.cursor += 1
        return d
    raise Fork(jnp.asarray(value).reshape(()).astype(bool))


def explore(thunk: Callable[[], Any], max_paths: int = 16):
    """Run ``thunk`` under bool-capture; return its output with every
    data-dependent branch folded into ``lax.cond``.

    Zero overhead when no fork occurs (single run, returned as-is)."""

    n_runs = 0
    # a full binary tree with max_paths leaves takes 2*max_paths - 1 runs;
    # bounding RUNS (not just completed leaves) also catches the
    # non-terminating case — a data-dependent `while tensor:` forks on an
    # all-True spine forever and never completes a single leaf
    max_runs = 2 * max_paths

    def run(decisions: List[bool]):
        nonlocal n_runs
        n_runs += 1
        if n_runs > max_runs:
            raise CaptureOverflow(
                f"data-dependent branch capture exceeded {max_runs} "
                f"exploration runs (budget {max_paths} paths) — an "
                f"unbounded `while tensor:` loop cannot be captured; "
                f"use paddle.static.nn.while_loop")
        ctx = CaptureContext(list(decisions))
        _stack.append(ctx)
        try:
            return ("leaf", thunk())
        except Fork as f:
            return ("fork", f.pred)
        finally:
            _stack.pop()

    n_leaves = 0

    def build(prefix: List[bool]):
        nonlocal n_leaves
        r = run(prefix)
        if r[0] == "leaf":
            n_leaves += 1
            if n_leaves > max_paths:
                raise CaptureOverflow(
                    f"data-dependent branch capture exceeded "
                    f"{max_paths} paths")
            return r
        pred = r[1]
        from paddle_tpu.framework.monitor import stat_add
        stat_add("to_static_cond_captures")
        return ("node", pred,
                build(prefix + [True]), build(prefix + [False]))

    return _combine(build([]))


def _combine(tree):
    if tree[0] == "leaf":
        return tree[1]
    _, pred, t, f = tree
    tv, tdef = jax.tree_util.tree_flatten(_combine(t))
    fv, fdef = jax.tree_util.tree_flatten(_combine(f))
    if tdef != fdef:
        raise CaptureMismatch(
            f"branches produced different pytree structures: {tdef} vs "
            f"{fdef}")
    for a, b in zip(tv, fv):
        sa = (jnp.shape(a), jnp.result_type(a))
        sb = (jnp.shape(b), jnp.result_type(b))
        if sa != sb:
            raise CaptureMismatch(
                f"branches produced mismatched leaves: {sa} vs {sb}")
    try:
        outs = jax.lax.cond(pred, lambda: tuple(tv), lambda: tuple(fv))
    except jax.errors.UnexpectedTracerError as e:
        # the bool site was hit inside an INNER trace (a static.nn.cond
        # branch / lax loop body): its predicate tracer is dead out here.
        # Surface as a capture failure so to_static graph-breaks cleanly.
        raise CaptureMismatch(
            "data-dependent bool inside a nested traced region cannot be "
            f"captured ({e})") from e
    return jax.tree_util.tree_unflatten(tdef, list(outs))
