"""paddle_tpu.jit — to_static trace-compile-and-cache.

Redesign of the reference's dy2static (python/paddle/jit/dy2static/
``ProgramTranslator``/``StaticFunction``) and SOT bytecode translator
(python/paddle/jit/sot/): on TPU, *tracing is the execution model* —
``to_static`` wraps a function or Layer so calls are captured once per input
signature and replayed as a compiled XLA executable. Shape/dtype guards and
recompilation come from jax.jit's dispatch cache; no AST rewriting or frame
hooks are needed (SURVEY §7.1). Parameters are lifted to function inputs so
weight updates never trigger recompilation, and buffer mutations (BatchNorm
running stats) round-trip through the compiled function.
"""

from paddle_tpu.jit.to_static import to_static, StaticFunction, not_to_static  # noqa: F401
from paddle_tpu.jit.save_load import save, load, TranslatedLayer  # noqa: F401
from paddle_tpu.jit.api import ignore_module, enable_to_static  # noqa: F401
