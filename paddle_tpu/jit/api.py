"""Misc jit API surface (enable/disable switches)."""

from __future__ import annotations

_enabled = True


def enable_to_static(flag: bool) -> None:
    global _enabled
    _enabled = bool(flag)


def is_to_static_enabled() -> bool:
    return _enabled


def ignore_module(modules) -> None:
    """SOT skip-module registry analog — tracing already ignores non-tensor code."""
    return None
