"""jit.save / jit.load — deploy-format export.

Analog of ``paddle.jit.save/load`` (python/paddle/jit/api.py,
translated_layer.py) + the C++ ``jit::Layer`` loader (paddle/fluid/jit/).
TPU-native format: the traced function is serialized as a portable StableHLO
artifact via ``jax.export`` (the ProgramDesc+params directory analog), plus a
weights file. ``load`` returns a ``TranslatedLayer`` that replays the
executable — the AnalysisPredictor-style inference entry.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework import io as fio
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer

__all__ = ["save", "load", "TranslatedLayer", "InputSpec"]


class InputSpec:
    """paddle.static.InputSpec analog."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def to_struct(self):
        from paddle_tpu.framework.dtype import convert_dtype
        shape = tuple(1 if (s is None or s < 0) else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, convert_dtype(self.dtype))


def save(layer, path: str, input_spec: Optional[Sequence] = None, **config) -> None:
    """Export `layer` to {path}.pdmodel (StableHLO) + {path}.pdiparams (weights)."""
    from paddle_tpu.jit.to_static import StaticFunction

    if isinstance(layer, StaticFunction):
        inner = layer._layer
        if inner is None:
            raise ValueError("jit.save of a bare function requires a Layer")
        layer = inner
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer or to_static-wrapped Layer")
    if input_spec is None:
        raise ValueError("input_spec is required (shapes define the exported program)")

    layer.eval()
    state = dict(layer.state_dict())
    names = sorted(state.keys())
    values = [state[n].value for n in names]

    def pure(params, *inputs):
        from paddle_tpu.nn.utils import functional_call
        st = dict(zip(names, params))
        out, _ = functional_call(layer, st, tuple(Tensor(i) for i in inputs))
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    specs = [s.to_struct() if isinstance(s, InputSpec) else
             jax.ShapeDtypeStruct(tuple(s.shape), jnp.dtype(s.dtype)) for s in input_spec]
    param_specs = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in values]

    exported = jax.export.export(jax.jit(pure))(param_specs, *specs)
    blob = exported.serialize()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    fio.save({n: state[n] for n in names}, path + ".pdiparams")
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump({"param_names": names}, f)


class TranslatedLayer(Layer):
    """Replays an exported program (translated_layer.py analog)."""

    def __init__(self, exported, params, param_names):
        super().__init__()
        self._exported = exported
        self._param_values = [params[n].value for n in param_names]
        for n in param_names:
            from paddle_tpu.framework.tensor import Parameter
            self.add_parameter(n.replace(".", "__"), Parameter(params[n].value))
        self._param_names = param_names

    def forward(self, *inputs):
        vals = [i.value if isinstance(i, Tensor) else jnp.asarray(i) for i in inputs]
        out = self._exported.call(self._param_values, *vals)
        return jax.tree_util.tree_map(Tensor, out)


def load(path: str) -> TranslatedLayer:
    with open(path + ".pdmodel", "rb") as f:
        blob = f.read()
    exported = jax.export.deserialize(bytearray(blob))
    params = fio.load(path + ".pdiparams")
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    return TranslatedLayer(exported, params, meta["param_names"])
