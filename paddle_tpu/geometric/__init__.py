"""paddle_tpu.geometric — graph message passing (python/paddle/geometric/).

send_u_recv / send_ue_recv / segment_* as jax segment ops (XLA scatter);
the reference's fused GPU kernels (graph_send_recv) map to
jax.ops.segment_sum-style reductions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import register_op

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min"]

_REDUCE = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # built on sum
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment(vals, seg_ids, n, pool):
    if pool == "mean":
        s = jax.ops.segment_sum(vals, seg_ids, n)
        cnt = jax.ops.segment_sum(jnp.ones_like(seg_ids, vals.dtype), seg_ids, n)
        return s / jnp.maximum(cnt, 1.0)[..., None] if vals.ndim > 1 else \
            s / jnp.maximum(cnt, 1.0)
    return _REDUCE[pool](vals, seg_ids, n)


@register_op("send_u_recv", ref="python/paddle/geometric/message_passing/send_recv.py")
def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None):
    n = int(out_size) if out_size is not None else x.shape[0]
    gathered = x[src_index]
    return _segment(gathered, dst_index, n, reduce_op)


@register_op("send_ue_recv")
def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None):
    n = int(out_size) if out_size is not None else x.shape[0]
    m = x[src_index]
    if message_op == "add":
        m = m + y
    elif message_op == "mul":
        m = m * y
    else:
        raise ValueError(f"message_op {message_op!r}")
    return _segment(m, dst_index, n, reduce_op)


@register_op("send_uv")
def send_uv(x, y, src_index, dst_index, message_op: str = "add"):
    a = x[src_index]
    b = y[dst_index]
    return a + b if message_op == "add" else a * b


@register_op("segment_sum")
def segment_sum(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return jax.ops.segment_sum(data, segment_ids, n)


@register_op("segment_mean")
def segment_mean(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return _segment(data, segment_ids, n, "mean")


@register_op("segment_max")
def segment_max(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return jax.ops.segment_max(data, segment_ids, n)


@register_op("segment_min")
def segment_min(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return jax.ops.segment_min(data, segment_ids, n)
