"""paddle_tpu.geometric — graph message passing (python/paddle/geometric/).

send_u_recv / send_ue_recv / segment_* as jax segment ops (XLA scatter);
the reference's fused GPU kernels (graph_send_recv) map to
jax.ops.segment_sum-style reductions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import register_op

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min",
           "sample_neighbors", "weighted_sample_neighbors",
           "reindex_graph", "khop_sampler"]

_REDUCE = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # built on sum
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment(vals, seg_ids, n, pool):
    if pool == "mean":
        s = jax.ops.segment_sum(vals, seg_ids, n)
        cnt = jax.ops.segment_sum(jnp.ones_like(seg_ids, vals.dtype), seg_ids, n)
        return s / jnp.maximum(cnt, 1.0)[..., None] if vals.ndim > 1 else \
            s / jnp.maximum(cnt, 1.0)
    return _REDUCE[pool](vals, seg_ids, n)


@register_op("send_u_recv", ref="python/paddle/geometric/message_passing/send_recv.py")
def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None):
    n = int(out_size) if out_size is not None else x.shape[0]
    gathered = x[src_index]
    return _segment(gathered, dst_index, n, reduce_op)


@register_op("send_ue_recv")
def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None):
    n = int(out_size) if out_size is not None else x.shape[0]
    m = x[src_index]
    if message_op == "add":
        m = m + y
    elif message_op == "mul":
        m = m * y
    else:
        raise ValueError(f"message_op {message_op!r}")
    return _segment(m, dst_index, n, reduce_op)


@register_op("send_uv")
def send_uv(x, y, src_index, dst_index, message_op: str = "add"):
    a = x[src_index]
    b = y[dst_index]
    return a + b if message_op == "add" else a * b


@register_op("segment_sum")
def segment_sum(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return jax.ops.segment_sum(data, segment_ids, n)


@register_op("segment_mean")
def segment_mean(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return _segment(data, segment_ids, n, "mean")


@register_op("segment_max")
def segment_max(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return jax.ops.segment_max(data, segment_ids, n)


@register_op("segment_min")
def segment_min(data, segment_ids):
    n = int(jnp.max(segment_ids)) + 1 if segment_ids.shape[0] else 0
    return jax.ops.segment_min(data, segment_ids, n)


# ---------------------------------------------------------------------------
# sampling (python/paddle/geometric/sampling/neighbors.py + reindex.py).
# Neighbor sampling has data-dependent output sizes, so on TPU it is an
# input-pipeline (host) stage — these run eagerly on numpy and feed the
# compiled message-passing ops above (send_u_recv & co).
# ---------------------------------------------------------------------------

def _sample_neighbors_impl(row, colptr, input_nodes, sample_size, eids,
                           return_eids, edge_weight=None):
    """Shared core for (weighted_)sample_neighbors: per-node uniform or
    weight-proportional selection without replacement. Zero-weight edges
    are only drawn after every positive-weight edge (A-Res semantics of
    the reference kernel)."""
    import numpy as _np

    from paddle_tpu.framework import random as _rnd

    rowv = _np.asarray(row.numpy() if isinstance(row, Tensor) else row).ravel()
    cp = _np.asarray(colptr.numpy() if isinstance(colptr, Tensor)
                     else colptr).ravel()
    nodes = _np.asarray(input_nodes.numpy() if isinstance(input_nodes, Tensor)
                        else input_nodes).ravel()
    wv = None
    if edge_weight is not None:
        wv = _np.asarray(edge_weight.numpy() if isinstance(edge_weight, Tensor)
                         else edge_weight).ravel().astype(_np.float64)
    ev = None
    if eids is not None:
        ev = _np.asarray(eids.numpy() if isinstance(eids, Tensor)
                         else eids).ravel()
    if return_eids and ev is None:
        raise ValueError("return_eids=True requires eids")
    seed = int(_np.asarray(jax.random.randint(_rnd.split_key(), (), 0,
                                              2 ** 31 - 1)))
    rng = _np.random.default_rng(seed)
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = _np.arange(lo, hi)
        elif wv is None:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        else:
            w = wv[lo:hi]
            pos = _np.nonzero(w > 0)[0]
            if len(pos) >= sample_size:
                p = w[pos] / w[pos].sum()
                sel = lo + pos[rng.choice(len(pos), size=sample_size,
                                          replace=False, p=p)]
            else:
                # every positive-weight edge, then zero-weight fill
                zero = _np.nonzero(w <= 0)[0]
                fill = rng.choice(len(zero), size=sample_size - len(pos),
                                  replace=False)
                sel = lo + _np.concatenate([pos, zero[fill]])
        out_n.append(rowv[sel])
        out_c.append(len(sel))
        if ev is not None:
            out_e.append(ev[sel])
    neigh = _np.concatenate(out_n) if out_n else _np.zeros((0,), rowv.dtype)
    count = _np.asarray(out_c, dtype=rowv.dtype)
    res = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(count)))
    if return_eids:
        e = _np.concatenate(out_e) if out_e else _np.zeros((0,), ev.dtype)
        res = res + (Tensor(jnp.asarray(e)),)
    return res


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over a CSC graph (graph_sample_neighbors
    kernel analog). Returns (out_neighbors, out_count[, out_eids])."""
    return _sample_neighbors_impl(row, colptr, input_nodes, sample_size,
                                  eids, return_eids)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional neighbor sampling without replacement
    (weighted_sample_neighbors kernel analog)."""
    return _sample_neighbors_impl(row, colptr, input_nodes, sample_size,
                                  eids, return_eids,
                                  edge_weight=edge_weight)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact (x, sampled neighbors) into local ids (graph_reindex
    kernel analog). Returns (reindex_src, reindex_dst, out_nodes)."""
    import numpy as _np

    xv = _np.asarray(x.numpy() if isinstance(x, Tensor) else x).ravel()
    nb = _np.asarray(neighbors.numpy() if isinstance(neighbors, Tensor)
                     else neighbors).ravel()
    ct = _np.asarray(count.numpy() if isinstance(count, Tensor)
                     else count).ravel()
    out_nodes = list(xv)
    index = {int(v): i for i, v in enumerate(xv)}
    src = _np.empty(len(nb), _np.int64)
    for i, v in enumerate(nb):
        vi = int(v)
        if vi not in index:
            index[vi] = len(out_nodes)
            out_nodes.append(vi)
        src[i] = index[vi]
    dst = _np.repeat(_np.arange(len(xv)), ct)
    return (Tensor(jnp.asarray(src.astype(xv.dtype))),
            Tensor(jnp.asarray(dst.astype(xv.dtype))),
            Tensor(jnp.asarray(_np.asarray(out_nodes, xv.dtype))))


def khop_sampler(row, colptr, input_nodes, sample_sizes,
                 sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling (graph_khop_sampler analog): per-hop uniform
    sampling with GLOBAL deduplication across hops. Returns
    (edge_src, edge_dst, sample_index, reindex_x) — local edge ids into
    ``sample_index``; ``reindex_x`` are the input nodes' local ids."""
    import numpy as _np

    if return_eids or sorted_eids is not None:
        raise NotImplementedError(
            "khop_sampler: eids tracking is not implemented; call "
            "sample_neighbors(return_eids=True) per hop instead")
    xv = _np.asarray(input_nodes.numpy() if isinstance(input_nodes, Tensor)
                     else input_nodes).ravel()
    uniq = list(xv)
    index = {int(v): i for i, v in enumerate(xv)}
    frontier = xv
    src_l, dst_l = [], []
    for size in sample_sizes:
        if len(frontier) == 0:
            break
        neigh, count = sample_neighbors(row, colptr, frontier,
                                        sample_size=int(size))
        nb = neigh.numpy()
        ct = count.numpy()
        dst_global = _np.repeat(frontier, ct)
        new_nodes = []
        for v in nb:
            vi = int(v)
            if vi not in index:
                index[vi] = len(uniq)
                uniq.append(vi)
                new_nodes.append(vi)
        src_l.append(_np.asarray([index[int(v)] for v in nb], _np.int64))
        dst_l.append(_np.asarray([index[int(v)] for v in dst_global],
                                 _np.int64))
        frontier = _np.asarray(new_nodes, xv.dtype)
    es = _np.concatenate(src_l) if src_l else _np.zeros((0,), _np.int64)
    ed = _np.concatenate(dst_l) if dst_l else _np.zeros((0,), _np.int64)
    uniq_a = _np.asarray(uniq, xv.dtype)
    return (Tensor(jnp.asarray(es.astype(xv.dtype))),
            Tensor(jnp.asarray(ed.astype(xv.dtype))),
            Tensor(jnp.asarray(uniq_a)),
            Tensor(jnp.asarray(_np.arange(len(xv), dtype=xv.dtype))))
