"""Op-level cost model (python/paddle/cost_model/cost_model.py analog).

The reference profiles a Program on-device and returns per-op time tables
for the auto-parallel planner. TPU-native twist: the static analysis reads
the traced jaxpr (per-primitive FLOPs/bytes from shapes — what the
reference derives from OpDesc), and the measured pass uses XLA's own
compiled-module cost analysis plus a wall-clock run.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["CostModel", "estimate_jaxpr_cost"]


def _dot_flops(eqn) -> float:
    d = eqn.params.get("dimension_numbers")
    (lc, rc), (lb, rb) = d
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    m = np.prod([s for i, s in enumerate(a.shape)
                 if i not in set(lc) | set(lb)], initial=1)
    n = np.prod([s for i, s in enumerate(b.shape)
                 if i not in set(rc) | set(rb)], initial=1)
    k = np.prod([a.shape[i] for i in lc], initial=1)
    batch = np.prod([a.shape[i] for i in lb], initial=1)
    return float(2 * batch * m * n * k)


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    # 2 * output elements * (per-output dot length = in_ch/groups * prod(k))
    k_elems = np.prod(rhs.shape[2:], initial=1) * rhs.shape[1]
    return float(2 * np.prod(out.shape, initial=1) * k_elems)


def estimate_jaxpr_cost(jaxpr) -> List[Dict]:
    """Per-equation cost rows: primitive name, flops, bytes accessed."""
    rows = []
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        out_elems = sum(int(np.prod(v.aval.shape, initial=1))
                        for v in eqn.outvars if hasattr(v.aval, "shape"))
        in_bytes = sum(
            int(np.prod(v.aval.shape, initial=1)) * v.aval.dtype.itemsize
            for v in eqn.invars
            if hasattr(v, "aval") and hasattr(v.aval, "shape"))
        out_bytes = sum(
            int(np.prod(v.aval.shape, initial=1)) * v.aval.dtype.itemsize
            for v in eqn.outvars if hasattr(v.aval, "shape"))
        if prim == "dot_general":
            flops = _dot_flops(eqn)
        elif prim == "conv_general_dilated":
            flops = _conv_flops(eqn)
        elif prim in ("pjit", "custom_vjp_call", "custom_jvp_call",
                      "remat", "checkpoint", "closed_call", "scan",
                      "while", "cond"):
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if inner is not None:
                sub = estimate_jaxpr_cost(getattr(inner, "jaxpr", inner))
                mult = (eqn.params.get("length", 1)
                        if prim == "scan" else 1)
                flops = sum(r["flops"] for r in sub) * mult
                in_bytes = sum(r["bytes"] for r in sub) * mult
                out_bytes = 0
            else:
                flops = float(out_elems)
        else:
            flops = float(out_elems)  # elementwise-ish default
        rows.append({"op": prim, "flops": flops,
                     "bytes": in_bytes + out_bytes})
    return rows


class CostModel:
    """cost_model.CostModel analog: static per-op estimates + measured run."""

    def static_cost(self, fn: Callable, *example_args) -> List[Dict]:
        jaxpr = jax.make_jaxpr(fn)(*example_args)
        return estimate_jaxpr_cost(jaxpr.jaxpr)

    def profile_measure(self, main_program=None, startup_program=None,
                        device: str = "tpu",
                        fetch_cost_list: Sequence[str] = ("time",),
                        fn: Optional[Callable] = None,
                        example_args: Sequence = ()) -> Dict:
        """Measure a static Program (or raw callable): wall time, XLA cost
        analysis (flops / bytes accessed), and the static per-op table."""
        if fn is None:
            if main_program is None or main_program.fn is None:
                raise ValueError("profile_measure needs a traced Program "
                                 "or fn=")
            prog = main_program

            def fn(*args):
                from paddle_tpu.framework.tensor import Tensor
                outs = prog.fn(*[Tensor(a) for a in args])
                outs = outs if isinstance(outs, (tuple, list)) else [outs]
                return [o.value if hasattr(o, "value") else o for o in outs]

            example_args = [s.example().value for s in prog.input_specs]

        args = [jnp.asarray(a) for a in example_args]
        rows = self.static_cost(fn, *args)

        jitted = jax.jit(fn)
        lowered = jitted.lower(*args)
        compiled = lowered.compile()
        analysis = {}
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            analysis = {"flops": float(cost.get("flops", -1.0)),
                        "bytes_accessed": float(cost.get("bytes accessed",
                                                         -1.0))}
        except Exception:
            pass

        jax.tree_util.tree_map(
            lambda x: getattr(x, "block_until_ready", lambda: x)(),
            jitted(*args))
        t0 = time.perf_counter()
        out = jitted(*args)
        jax.tree_util.tree_map(
            lambda x: getattr(x, "block_until_ready", lambda: x)(), out)
        wall = time.perf_counter() - t0

        return {
            "op_name": [r["op"] for r in rows],
            "flops": [r["flops"] for r in rows],
            "bytes": [r["bytes"] for r in rows],
            "time": wall,
            "xla_cost_analysis": analysis,
            "total_static_flops": float(sum(r["flops"] for r in rows)),
        }
