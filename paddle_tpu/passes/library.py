"""Built-in rewrite rules: fusion routing, AMP insertion, decomposition.

These are the three pass families the reference implements over PIR —
fusion patterns (paddle/fluid/pir/transforms/gpu/fused_*_pass.cc), the AMP
pass (python/paddle/distributed/passes/auto_parallel_amp.py), and op
decomposition (python/paddle/decomposition/) — re-expressed as jaxpr
rewrite rules (see passes/rewrite.py for the engine).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import jax.extend.core as jex
from jax import lax

from paddle_tpu.passes.rewrite import EqnRule, MatchInfo, RewriteRule

__all__ = [
    "fuse_rms_norm_rule", "amp_cast_rules", "decompose_rule",
    "DEFAULT_DECOMPOSITIONS", "decomposition_rules",
    "decompose_fused", "FUSED_ROUTING_OFF",
]


# --------------------------------------------------------------------------
# fusion: rms_norm composition -> single custom-vjp unit
# --------------------------------------------------------------------------

def _rms_pattern(x, w):
    # the exact composition nn.functional.rms_norm emits (single source:
    # ops/fused_norm.rms_lax keeps matcher and emitter in sync)
    from paddle_tpu.ops.fused_norm import rms_lax
    return rms_lax(x, w, 1e-6)


def _rms_where(info: MatchInfo) -> bool:
    x_aval = info.captures[0].aval
    red = info.target_eqn("reduce_sum")
    if tuple(red.params.get("axes", ())) != (len(x_aval.shape) - 1,):
        return False
    div = info.target_eqn("div")
    d = div.invars[1]
    if not isinstance(d, jex.Literal):
        return False
    try:
        if float(d.val) != float(x_aval.shape[-1]):
            return False
    except TypeError:
        return False
    add = info.target_eqn("add")
    if not isinstance(add.invars[1], jex.Literal):
        return False
    # structural matching ignores params: the weight's broadcast must map it
    # onto the LAST axis (w[:, None]-style per-row scaling would otherwise
    # match on square activations and silently corrupt numerics)
    w_atom = info.captures[1]
    for _, te in info.eqns:
        if (te.primitive.name == "broadcast_in_dim"
                and any(v is w_atom for v in te.invars)):
            out_ndim = len(te.outvars[0].aval.shape)
            if tuple(te.params.get("broadcast_dimensions", ())) != \
                    (out_ndim - 1,):
                return False
    return True


def _rms_replace(info: MatchInfo) -> Callable:
    from paddle_tpu.ops.fused_norm import rms_norm_fused

    eps = float(info.target_eqn("add").invars[1].val)
    return lambda x, w: rms_norm_fused(x, w, eps)


def fuse_rms_norm_rule(hidden: int = 8) -> RewriteRule:
    """Match x * rsqrt(mean(x^2)+eps) * w (any eps, any trailing width) and
    replace it with ops.fused_norm.rms_norm_fused."""
    f32 = jax.ShapeDtypeStruct((4, hidden), jnp.float32)
    bf16 = jax.ShapeDtypeStruct((4, hidden), jnp.bfloat16)
    wf32 = jax.ShapeDtypeStruct((hidden,), jnp.float32)
    wbf16 = jax.ShapeDtypeStruct((hidden,), jnp.bfloat16)
    return RewriteRule(
        "fuse_rms_norm", _rms_pattern,
        examples=[(bf16, wbf16), (f32, wf32), (bf16, wf32)],
        replace=_rms_replace, where=_rms_where)


# --------------------------------------------------------------------------
# AMP: cast matmul/conv operands to a low-precision compute dtype
# --------------------------------------------------------------------------

def amp_cast_rules(compute_dtype: str = "bfloat16",
                   prims: Sequence[str] = ("dot_general",
                                           "conv_general_dilated")):
    """Rewrite f32 matmuls/convs to compute in ``compute_dtype`` on the MXU
    while keeping the f32 output dtype via preferred_element_type (the
    auto_parallel_amp pass analog; numerics match TPU mixed precision)."""
    dt = jnp.dtype(compute_dtype)

    def make(prim_name: str) -> EqnRule:
        def replace(eqn) -> Optional[Callable]:
            if any(not hasattr(v.aval, "dtype")
                   or v.aval.dtype != jnp.float32 for v in eqn.invars):
                return None
            out_dtype = eqn.outvars[0].aval.dtype
            params = dict(eqn.params)
            params["preferred_element_type"] = jnp.dtype(out_dtype)
            prim = eqn.primitive

            def build(*invals):
                cast = [v.astype(dt) for v in invals]
                out = prim.bind(*cast, **params)
                return out

            return build

        return EqnRule(f"amp_cast_{prim_name}", prim_name, replace)

    return [make(p) for p in prims]


# --------------------------------------------------------------------------
# decomposition: prim -> composition of simpler prims
# --------------------------------------------------------------------------

def decompose_rule(prim_name: str,
                   builder_from_params: Callable[[dict], Callable],
                   name: str = "") -> EqnRule:
    """EqnRule that replaces every ``prim_name`` equation with the traceable
    function ``builder_from_params(eqn.params)`` (python/paddle/decomposition
    analog; used by the ONNX exporter to lower to a portable prim set)."""
    return EqnRule(name or f"decompose_{prim_name}", prim_name,
                   lambda eqn: builder_from_params(dict(eqn.params)))


def _decomp_logistic(params):
    return lambda x: 1.0 / (1.0 + jnp.exp(-x))


def _decomp_softmax(params):
    axis = params.get("axis", (-1,))

    def f(x):
        m = jnp.max(x, axis=axis, keepdims=True)
        e = jnp.exp(x - lax.stop_gradient(m))
        return e / jnp.sum(e, axis=axis, keepdims=True)

    return f


def _decomp_integer_pow(params):
    y = params["y"]

    def f(x):
        if y == 0:
            return jnp.ones_like(x)
        inv = y < 0
        n = -y if inv else y
        out = x
        for _ in range(int(n) - 1):
            out = out * x
        return 1.0 / out if inv else out

    return f


def _decomp_rsqrt(params):
    return lambda x: 1.0 / jnp.sqrt(x)


DEFAULT_DECOMPOSITIONS: Dict[str, Callable[[dict], Callable]] = {
    "logistic": _decomp_logistic,
    "softmax": _decomp_softmax,
    "integer_pow": _decomp_integer_pow,
    "rsqrt": _decomp_rsqrt,
}


def decomposition_rules(table: Optional[Dict[str, Callable]] = None):
    table = DEFAULT_DECOMPOSITIONS if table is None else table
    return [decompose_rule(k, v) for k, v in table.items()]


# --------------------------------------------------------------------------
# fused-op decomposition mode (reference: paddle/fluid/primitive/composite/
# composite.h + python/paddle/decomposition/ — see-through for passes and
# exporters)
# --------------------------------------------------------------------------

# every fused/Pallas routing flag and the value that forces the canonical
# lax composition; plus the decompose_fused_ops master switch consumed by
# entries whose kernel is not flag-gated (chunked fused CE)
FUSED_ROUTING_OFF: Dict[str, object] = {
    "decompose_fused_ops": True,
    "use_fused_rms_norm": False,
    "use_fused_group_norm": False,
    "use_fused_attention": False,
    "use_fused_lm_ce": False,
    "use_fused_rope": False,
    "use_decode_attention": False,
}


class decompose_fused:
    """Context manager: inside it, every fused op (fused_rms_norm,
    fused GroupNorm+SiLU, flash/decode attention, fused rope, chunked
    fused lm-head CE, fused_linear_activation/swiglu) traces as its
    canonical base-prim composition — no pallas_call, no vocab-chunk
    scan. Routing happens at trace time, so wrapping a trace (NOT just a
    call) is what decomposes a jaxpr:

        with passes.decompose_fused():
            jaxpr = jax.make_jaxpr(fn)(*args)

    The ONNX exporter traces under this context; parity tests assert
    decomposed == fused numerics for every entry (test_passes.py).
    """

    def __enter__(self):
        from paddle_tpu.flags import flags, get_flags, set_flags
        self._old = {k: get_flags(k)[k] for k in FUSED_ROUTING_OFF}
        set_flags(dict(FUSED_ROUTING_OFF))
        return self

    def __exit__(self, *exc):
        from paddle_tpu.flags import set_flags
        set_flags(self._old)
        return False
