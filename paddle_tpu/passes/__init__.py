"""Graph pass / rewrite layer (reference pir::PassManager + DRR analog).

See rewrite.py for the engine and library.py for the built-in rules."""

from paddle_tpu.passes.rewrite import (EqnRule, MatchInfo, PassManager,
                                       RewriteRule, dce_jaxpr, rewrite,
                                       rewrite_jaxpr)
from paddle_tpu.passes.library import (DEFAULT_DECOMPOSITIONS,
                                       FUSED_ROUTING_OFF, amp_cast_rules,
                                       decompose_fused, decompose_rule,
                                       decomposition_rules,
                                       fuse_rms_norm_rule)

__all__ = [
    "EqnRule", "MatchInfo", "PassManager", "RewriteRule", "dce_jaxpr",
    "rewrite", "rewrite_jaxpr", "DEFAULT_DECOMPOSITIONS", "amp_cast_rules",
    "decompose_rule", "decomposition_rules", "fuse_rms_norm_rule",
    "decompose_fused", "FUSED_ROUTING_OFF",
]
