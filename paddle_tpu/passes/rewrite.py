"""Jaxpr pass/rewrite framework — the PIR transforms / DRR analog.

The reference carries a full IR pass infrastructure: a pass manager over PIR
(reference paddle/pir/include/pass/pass.h, paddle/fluid/pir/transforms/) and
a declarative rewrite-rule layer, DRR, where a source pattern and a result
pattern are both *described* and the engine does subgraph match + replace
(reference paddle/fluid/pir/drr/README.md). Fusion routing, AMP insertion
and op decomposition all ride that one mechanism.

TPU-native redesign: the IR is the jaxpr that jax tracing already produces —
we add the missing piece, a small pattern-match-and-rewrite engine over it.
Both the source pattern and the replacement are plain traceable Python
functions (the most natural "declarative" form in a functional tracer):

    rule = RewriteRule(
        "fuse_rms_norm",
        pattern=lambda x, w: my_rms_norm_composition(x, w),
        examples=[(f32[4, 8], f32[8])],      # avals to trace the pattern
        replace=lambda info: fused_rms_norm,  # builder, given match info
        where=check_axes,                     # optional semantic guard
    )
    fast_fn = rewrite(fn, [rule])             # or PassManager([...]).wrap(fn)

Matching is structural (primitive names + def-use topology, rooted at the
pattern's final equation); shapes and shape-dependent params are NOT
compared — a rule's ``where`` predicate checks the semantic bits that
matter (reduction axes, broadcast dims, literal values). Replacement splices
the traced builder jaxpr in place of the anchor equation; orphaned producer
equations are swept by a liveness DCE pass. Rewrites recurse into
sub-jaxprs (pjit / scan / cond bodies) so rules apply under jit.

Everything here is compile-time graph surgery on pure jax data structures;
the rewritten jaxpr is executed with ``jax.core.eval_jaxpr`` and remains
fully traceable (jit / grad / vmap compose on top).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.core as jcore
import jax.extend.core as jex
from jax.tree_util import tree_flatten, tree_structure, tree_unflatten

__all__ = [
    "RewriteRule", "EqnRule", "MatchInfo", "rewrite", "rewrite_jaxpr",
    "dce_jaxpr", "PassManager",
]


class MatchInfo:
    """What a successful pattern match captured.

    captures  — target atoms bound to the pattern's free inputs, in the
                pattern function's positional order.
    eqns      — list of (pattern_eqn, target_eqn) pairs, anchor first.
    literals  — list of (pattern_literal_value, target_literal_value) pairs
                in match order (e.g. to recover an eps constant).
    """

    def __init__(self):
        self.captures: List[Any] = []
        self.eqns: List[Tuple[Any, Any]] = []
        self.literals: List[Tuple[Any, Any]] = []

    def target_eqn(self, prim_name: str, index: int = 0):
        """The index-th matched target eqn with the given primitive name."""
        hits = [te for pe, te in self.eqns if te.primitive.name == prim_name]
        if index >= len(hits):
            raise KeyError(f"no matched eqn #{index} for primitive {prim_name!r}")
        return hits[index]


class RewriteRule:
    """Subgraph rewrite: ``pattern`` (a traceable fn) -> ``replace`` builder.

    pattern   — pure function of N arrays; its trace (over each ``examples``
                entry) is the source pattern. Must return a single array.
    examples  — sequence of example-argument tuples (arrays or
                ShapeDtypeStructs); one pattern variant is traced per entry
                (e.g. a bf16 and an f32 variant differ by convert ops).
    replace   — ``replace(info) -> callable(*captured_arrays)``; the callable
                is traced at the match site and spliced in. Its output count
                and avals must equal the anchor equation's.
    where     — optional ``where(info) -> bool`` semantic guard.
    """

    def __init__(self, name: str, pattern: Callable, examples: Sequence[tuple],
                 replace: Callable[[MatchInfo], Callable], where=None):
        self.name = name
        self.replace = replace
        self.where = where
        self.hits = 0  # successful applications (observability/tests)
        self.patterns: List[Any] = []  # list of ClosedJaxpr
        for ex in examples:
            closed = jax.make_jaxpr(pattern)(*[_as_sds(a) for a in ex])
            if len(closed.jaxpr.outvars) != 1:
                raise ValueError(
                    f"rule {name!r}: pattern must return a single array")
            out = closed.jaxpr.outvars[0]
            if not closed.jaxpr.eqns or not any(
                    out is o for o in closed.jaxpr.eqns[-1].outvars):
                raise ValueError(
                    f"rule {name!r}: pattern output must come from its last "
                    "equation (the match anchor)")
            self.patterns.append(closed)


class EqnRule:
    """Single-equation rewrite keyed by primitive name (decompose/AMP form).

    replace — ``replace(eqn) -> callable(*invals)`` traced and spliced in
              place of the equation; None to leave this site untouched.
    """

    def __init__(self, name: str, prim_name: str,
                 replace: Callable[[Any], Optional[Callable]], where=None):
        self.name = name
        self.prim_name = prim_name
        self.replace = replace
        self.where = where
        self.hits = 0  # successful applications (observability/tests)


def _as_sds(a):
    if isinstance(a, jax.ShapeDtypeStruct):
        return a
    return jax.ShapeDtypeStruct(jax.numpy.shape(a), jax.numpy.asarray(a).dtype)


def _same_atom(a, b) -> bool:
    if isinstance(a, jex.Literal) or isinstance(b, jex.Literal):
        if not (isinstance(a, jex.Literal) and isinstance(b, jex.Literal)):
            return False
        try:
            return bool(a.val == b.val)
        except Exception:
            return False
    return a is b


class _GraphView:
    def __init__(self, eqns):
        self.producer: Dict[Any, int] = {}
        for i, e in enumerate(eqns):
            for o in e.outvars:
                self.producer[o] = i


def _prims_compatible(pe, te) -> bool:
    """Structural primitive equality, plus known same-semantics spellings
    (jnp.square traces to `square`, x**2 to `integer_pow[y=2]`)."""
    pn, tn = pe.primitive.name, te.primitive.name
    if len(pe.invars) != len(te.invars) or len(pe.outvars) != len(te.outvars):
        return False
    if pn == tn:
        return True
    if {pn, tn} == {"square", "integer_pow"}:
        ip = pe if pn == "integer_pow" else te
        return ip.params.get("y") == 2
    return False


def _match_at(pat_jaxpr, gv: _GraphView, eqns, anchor_idx: int) -> Optional[MatchInfo]:
    """Unify the pattern (rooted at its last eqn) against eqns[anchor_idx]."""
    pat_producer = {}
    for e in pat_jaxpr.eqns:
        for o in e.outvars:
            pat_producer[o] = e
    info = MatchInfo()
    var_map: Dict[Any, Any] = {}
    eqn_map: Dict[int, int] = {}

    def unify_atom(pv, tv) -> bool:
        if isinstance(pv, jex.Literal):
            if not isinstance(tv, jex.Literal):
                return False
            info.literals.append((pv.val, tv.val))
            return True
        if pv in var_map:
            return _same_atom(var_map[pv], tv)
        pe = pat_producer.get(pv)
        if pe is None:  # free pattern input: wildcard capture
            var_map[pv] = tv
            return True
        if isinstance(tv, jex.Literal):
            return False
        ti = gv.producer.get(tv)
        if ti is None:  # target var is a graph input; pattern expects a producer
            return False
        var_map[pv] = tv
        return unify_eqn(pe, ti)

    def unify_eqn(pe, ti: int) -> bool:
        te = eqns[ti]
        if not _prims_compatible(pe, te):
            return False
        if id(pe) in eqn_map:
            return eqn_map[id(pe)] == ti
        eqn_map[id(pe)] = ti
        info.eqns.append((pe, te))
        return all(unify_atom(pv, tv) for pv, tv in zip(pe.invars, te.invars))

    if not unify_eqn(pat_jaxpr.eqns[-1], anchor_idx):
        return None
    # captures in pattern-invar order; a pattern input the trace dropped
    # (unused) stays None
    info.captures = [var_map.get(v) for v in pat_jaxpr.invars]
    if any(c is None for c in info.captures):
        return None
    return info


def _trace_builder(builder, captured):
    avals = [jax.ShapeDtypeStruct(a.aval.shape, a.aval.dtype) for a in captured]
    return jax.make_jaxpr(builder)(*avals)


def _splice(builder_closed, captured, anchor_outvars):
    """Return (eqns, constvars, consts) for the builder wired into the graph."""
    bj = builder_closed.jaxpr
    sub: Dict[Any, Any] = {}
    for v, atom in zip(bj.invars, captured):
        sub[v] = atom
    if len(bj.outvars) != len(anchor_outvars):
        raise ValueError("builder output arity != anchor output arity")
    for bo, ao in zip(bj.outvars, anchor_outvars):
        if not isinstance(bo, jex.Var) or bo in sub or bo not in _produced(bj):
            # identity/passthrough builders can't be spliced in place
            raise ValueError("builder outputs must be produced by builder eqns")
        if tuple(bo.aval.shape) != tuple(ao.aval.shape) or \
                bo.aval.dtype != ao.aval.dtype:
            raise ValueError(
                f"builder output aval {bo.aval} != anchor aval {ao.aval}")
        sub[bo] = ao

    def s(atom):
        return sub.get(atom, atom) if isinstance(atom, jex.Var) else atom

    new_eqns = []
    for e in bj.eqns:
        new_eqns.append(e.replace(invars=[s(v) for v in e.invars],
                                  outvars=[s(v) for v in e.outvars]))
    return new_eqns, list(bj.constvars), list(builder_closed.consts)


def _produced(jaxpr):
    out = set()
    for e in jaxpr.eqns:
        out.update(v for v in e.outvars if isinstance(v, jex.Var))
    return out


def _sub_jaxpr_params(params: dict):
    """Yield (key, value) for params holding jaxprs (directly or in tuples)."""
    for k, v in params.items():
        if isinstance(v, (jex.ClosedJaxpr, jex.Jaxpr)):
            yield k, v
        elif isinstance(v, (tuple, list)) and v and all(
                isinstance(x, (jex.ClosedJaxpr, jex.Jaxpr)) for x in v):
            yield k, v


def rewrite_jaxpr(closed, rules, recurse: bool = True, max_rounds: int = 10):
    """Apply rewrite rules to a ClosedJaxpr until fixpoint; DCE at the end."""
    jaxpr = closed.jaxpr
    consts = list(closed.consts)
    constvars = list(jaxpr.constvars)
    eqns = list(jaxpr.eqns)

    for _ in range(max_rounds):
        changed = False
        gv = _GraphView(eqns)
        out: List[Any] = []
        extra_constvars: List[Any] = []
        extra_consts: List[Any] = []
        for i, eqn in enumerate(eqns):
            repl = _try_rules(rules, gv, eqns, i)
            if repl is None:
                out.append(eqn)
                continue
            rule, builder, captured = repl
            try:
                bclosed = _trace_builder(builder, captured)
                new_eqns, cvars, cvals = _splice(bclosed, captured, eqn.outvars)
            except ValueError:
                out.append(eqn)
                continue
            rule.hits += 1
            out.extend(new_eqns)
            extra_constvars.extend(cvars)
            extra_consts.extend(cvals)
            changed = True
        eqns = out
        constvars += extra_constvars
        consts += extra_consts
        if not changed:
            break

    if recurse:
        eqns = [_rewrite_sub_jaxprs(e, rules) for e in eqns]

    new_jaxpr = _rebuild(jaxpr, constvars, eqns)
    closed2 = jex.ClosedJaxpr(new_jaxpr, consts)
    return dce_jaxpr(closed2)


def _try_rules(rules, gv, eqns, i):
    eqn = eqns[i]
    for rule in rules:
        if isinstance(rule, EqnRule):
            if eqn.primitive.name != rule.prim_name:
                continue
            if rule.where is not None and not rule.where(eqn):
                continue
            builder = rule.replace(eqn)
            if builder is None:
                continue
            return rule, builder, list(eqn.invars)
        for pat in rule.patterns:
            info = _match_at(pat.jaxpr, gv, eqns, i)
            if info is None:
                continue
            if rule.where is not None and not rule.where(info):
                continue
            builder = rule.replace(info)
            if builder is None:
                continue
            return rule, builder, info.captures
    return None


def _rewrite_sub_jaxprs(eqn, rules):
    # never rewrite inside custom-differentiation bodies: their fwd/bwd pair
    # must stay consistent, and a rule whose replacement falls back to the
    # very composition it matched would re-fuse its own body forever
    if eqn.primitive.name.startswith("custom_"):
        return eqn
    updates = {}
    for k, v in _sub_jaxpr_params(eqn.params):
        if isinstance(v, jex.ClosedJaxpr):
            updates[k] = rewrite_jaxpr(v, rules)
        elif isinstance(v, jex.Jaxpr):
            updates[k] = rewrite_jaxpr(jex.ClosedJaxpr(v, []), rules).jaxpr
        else:
            updates[k] = type(v)(
                rewrite_jaxpr(x, rules) if isinstance(x, jex.ClosedJaxpr)
                else rewrite_jaxpr(jex.ClosedJaxpr(x, []), rules).jaxpr
                for x in v)
    if not updates:
        return eqn
    params = dict(eqn.params)
    params.update(updates)
    return eqn.replace(params=params)


def _rebuild(template_jaxpr, constvars, eqns):
    effects = frozenset().union(*[e.effects for e in eqns]) if eqns else frozenset()
    return jex.Jaxpr(constvars, template_jaxpr.invars, template_jaxpr.outvars,
                     eqns, effects=effects,
                     debug_info=template_jaxpr.debug_info)


def dce_jaxpr(closed):
    """Liveness sweep: drop equations whose outputs are never used (keeps
    effectful equations)."""
    jaxpr = closed.jaxpr
    live = {v for v in jaxpr.outvars if isinstance(v, jex.Var)}
    kept = []
    for eqn in reversed(jaxpr.eqns):
        if eqn.effects or any(o in live for o in eqn.outvars):
            kept.append(eqn)
            live.update(v for v in eqn.invars if isinstance(v, jex.Var))
    kept.reverse()
    # drop now-unused consts too
    constvars, consts = [], []
    for v, c in zip(jaxpr.constvars, closed.consts):
        if v in live:
            constvars.append(v)
            consts.append(c)
    return jex.ClosedJaxpr(_rebuild(jaxpr, constvars, kept), consts)


def rewrite(fn: Callable, rules: Sequence, recurse: bool = True) -> Callable:
    """Wrap ``fn`` so every trace of it goes through the rewrite rules.

    The wrapper traces ``fn`` to a jaxpr, rewrites it, and evaluates the
    result; composing with jit/grad/vmap re-traces through this machinery,
    so the rules always apply to the final program.
    """

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        flat, in_tree = tree_flatten((args, kwargs))

        def flat_fn(*leaves):
            a, k = tree_unflatten(in_tree, leaves)
            return fn(*a, **k)

        closed, out_shape = jax.make_jaxpr(flat_fn, return_shape=True)(*flat)
        closed = rewrite_jaxpr(closed, rules, recurse=recurse)
        outs = jcore.eval_jaxpr(closed.jaxpr, closed.consts, *flat)
        return tree_unflatten(tree_structure(out_shape), outs)

    return wrapped


class PassManager:
    """Ordered pass pipeline (reference pir::PassManager analog): each entry
    is a list of rules applied to fixpoint before the next entry runs."""

    def __init__(self, stages: Sequence[Sequence]):
        # accept a flat rule list or a list of stages
        if stages and not isinstance(stages[0], (list, tuple)):
            stages = [list(stages)]
        self.stages = [list(s) for s in stages]

    def run(self, closed):
        for stage in self.stages:
            closed = rewrite_jaxpr(closed, stage)
        return closed

    def wrap(self, fn: Callable) -> Callable:
        out = fn
        for stage in self.stages:
            out = rewrite(out, stage)
        return out
