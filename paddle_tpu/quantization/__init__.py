"""paddle_tpu.quantization — PTQ/QAT framework.

Analog of python/paddle/quantization/ (quantize.py, observers, QAT layer
wrappers): observers watch activations/weights during calibration, PTQ
replaces Linear/Conv with quant-simulating layers, QAT uses fake-quant
(straight-through estimator) during training. Int8 matmuls on TPU run as
int8 MXU ops via XLA when dtypes allow; the simulation path keeps f32
compute with quantize/dequantize rounding (the reference's
QuantizeLinear/DequantizeLinear semantics).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import register_op

__all__ = ["QuantConfig", "PTQ", "QAT", "AbsmaxObserver", "quantize",
           "dequantize", "fake_quantize", "QuantedLinear", "QuantedConv2D"]


@register_op("quantize_linear")
def quantize(x, scale, zero_point=0, bit_length: int = 8):
    qmax = 2 ** (bit_length - 1) - 1
    return jnp.clip(jnp.round(x / scale) + zero_point, -qmax - 1, qmax)


@register_op("dequantize_linear")
def dequantize(x, scale, zero_point=0, bit_length: int = 8):
    return (x - zero_point) * scale


@register_op("fake_quantize")
def fake_quantize(x, scale, bit_length: int = 8):
    """Quantize-dequantize with straight-through gradient."""
    qmax = 2 ** (bit_length - 1) - 1

    @jax.custom_vjp
    def ste(v):
        return jnp.clip(jnp.round(v / scale), -qmax - 1, qmax) * scale

    def fwd(v):
        return ste(v), None

    def bwd(_, g):
        return (g,)

    ste.defvjp(fwd, bwd)
    return ste(x)


class AbsmaxObserver:
    """abs-max range observer (quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        import numpy as np
        v = float(np.max(np.abs(np.asarray(
            x.value if isinstance(x, Tensor) else x))))
        self._absmax = max(self._absmax, v)

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._absmax, 1e-8) / qmax


class QuantConfig:
    """quantization/config.py analog: which layers get which quanter."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or (lambda: AbsmaxObserver())
        self.weight = weight or (lambda: AbsmaxObserver())
        self._layer_types = (nn.Linear, nn.Conv2D)

    def add_layer_config(self, layer_types, activation=None, weight=None):
        self._layer_types = tuple(layer_types)


class _QuantedBase(nn.Layer):
    """Shared fake-quant wrapper state (QAT/PTQ simulation)."""

    def __init__(self, inner, w_scale: float, a_observer, bits: int = 8):
        super().__init__()
        self.inner = inner
        self.w_scale = w_scale
        self.a_observer = a_observer
        self.bits = bits
        self.calibrating = True
        self.int8_kernel = False

    def _a_scale(self, x):
        if self.calibrating:
            self.a_observer.observe(x)
        return self.a_observer.scale()


class QuantedLinear(_QuantedBase):
    """Linear with fake-quantized weight+activation; after convert() with
    ``int8_kernel`` the matmul really runs int8 x int8 -> int32 on the MXU
    (the deployment path, not just simulation)."""

    def _freeze_int8(self):
        """Build the frozen-scale int8 op ONCE at convert() time (scales
        stop moving then; rebuilding per forward is hot-path garbage)."""
        from paddle_tpu.ops.registry import OpDef
        ws, ascale = self.w_scale, self.a_observer.scale()
        qmax = 2 ** (self.bits - 1) - 1

        def impl(xv, wv):
            xq = jnp.clip(jnp.round(xv / ascale), -qmax - 1,
                          qmax).astype(jnp.int8)
            wq = jnp.clip(jnp.round(wv / ws), -qmax - 1,
                          qmax).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, wq, (((xv.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return acc.astype(jnp.float32) * (ascale * ws)

        self._int8_op = OpDef("int8_linear", impl, differentiable=False)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        a_scale = self._a_scale(x)
        if self.int8_kernel and not self.calibrating:
            from paddle_tpu.ops.registry import apply_op
            out = apply_op(self._int8_op, (x, self.inner.weight), {})
            return out + self.inner.bias if self.inner.bias is not None else out
        xq = fake_quantize(x, a_scale, self.bits)
        wq = fake_quantize(self.inner.weight, self.w_scale, self.bits)
        return F.linear(xq, wq, self.inner.bias)


class QuantedConv2D(_QuantedBase):
    """Conv2D with fake-quantized weight+activation
    (quantization/imperative quantized conv analog)."""

    def _freeze_int8(self):
        from paddle_tpu.ops.registry import OpDef
        ws, ascale = self.w_scale, self.a_observer.scale()
        qmax = 2 ** (self.bits - 1) - 1
        c = self.inner

        def impl(xv, wv):
            import paddle_tpu.nn.functional as FN
            xq = jnp.clip(jnp.round(xv / ascale), -qmax - 1, qmax)
            wq = jnp.clip(jnp.round(wv / ws), -qmax - 1, qmax)
            # int8 conv: quantized integer grids; XLA keeps the MXU layout.
            # Accumulate in f32 (conv transpose rule forbids a widened
            # preferred_element_type; values are exact integers < 2^21)
            out = FN.conv2d.op.impl(xq, wq, None, stride=c.stride,
                                    padding=c.padding, dilation=c.dilation,
                                    groups=c.groups)
            return out * (ascale * ws)

        self._int8_op = OpDef("int8_conv2d", impl, differentiable=False)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        a_scale = self._a_scale(x)
        c = self.inner
        if self.int8_kernel and not self.calibrating:
            from paddle_tpu.ops.registry import apply_op
            out = apply_op(self._int8_op, (x, c.weight), {})
            if c.bias is not None:
                out = out + paddle_reshape_bias(c.bias, out.ndim)
            return out
        xq = fake_quantize(x, a_scale, self.bits)
        wq = fake_quantize(self.inner.weight, self.w_scale, self.bits)
        return F.conv2d(xq, wq, c.bias, stride=c.stride, padding=c.padding,
                        dilation=c.dilation, groups=c.groups)


_WRAPPERS = {}  # filled below: inner layer type -> quanted wrapper


def _swap_quanted(model: nn.Layer, config: QuantConfig):
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, config._layer_types):
            cls = next((w for t, w in _WRAPPERS.items()
                        if isinstance(sub, t)), None)
            if cls is None:
                raise NotImplementedError(
                    f"no quantized wrapper for {type(sub).__name__}; "
                    f"supported: {[t.__name__ for t in _WRAPPERS]}")
            obs = config.weight()
            obs.observe(sub.weight)
            model._sub_layers[name] = cls(sub, obs.scale(),
                                          config.activation())
        else:
            _swap_quanted(sub, config)


class PTQ:
    """Post-training quantization driver (quantization/ptq.py)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: nn.Layer, inplace: bool = False):
        import copy
        m = model if inplace else copy.deepcopy(model)
        _swap_quanted(m, self.config)
        return m

    def convert(self, model: nn.Layer, inplace: bool = True,
                int8_kernel: bool = False):
        for _, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, _QuantedBase):
                sub.calibrating = False
                sub.int8_kernel = int8_kernel
                if int8_kernel:
                    sub._freeze_int8()
        return model


_WRAPPERS.update({nn.Conv2D: QuantedConv2D, nn.Linear: QuantedLinear})


def paddle_reshape_bias(bias, ndim):
    shape = [1] * ndim
    shape[1] = bias.shape[0]
    import paddle_tpu as paddle
    return paddle.reshape(bias, shape)


class QAT(PTQ):
    """Quant-aware training: same wrappers, calibration stays live so the
    STE fake-quant trains through (quantization/qat.py)."""
