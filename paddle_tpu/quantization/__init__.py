"""paddle_tpu.quantization — PTQ/QAT framework.

Analog of python/paddle/quantization/ (quantize.py, observers, QAT layer
wrappers): observers watch activations/weights during calibration, PTQ
replaces Linear/Conv with quant-simulating layers, QAT uses fake-quant
(straight-through estimator) during training. Int8 matmuls on TPU run as
int8 MXU ops via XLA when dtypes allow; the simulation path keeps f32
compute with quantize/dequantize rounding (the reference's
QuantizeLinear/DequantizeLinear semantics).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import register_op

__all__ = ["weight_quantize", "weight_only_linear", "llm_int8_linear",
           "QuantConfig", "PTQ", "QAT", "AbsmaxObserver", "EMAObserver",
           "HistogramObserver", "KLObserver", "quantize",
           "dequantize", "fake_quantize", "QuantedLinear", "QuantedConv2D"]


@register_op("quantize_linear")
def quantize(x, scale, zero_point=0, bit_length: int = 8):
    qmax = 2 ** (bit_length - 1) - 1
    return jnp.clip(jnp.round(x / scale) + zero_point, -qmax - 1, qmax)


@register_op("dequantize_linear")
def dequantize(x, scale, zero_point=0, bit_length: int = 8):
    return (x - zero_point) * scale


@register_op("fake_quantize")
def fake_quantize(x, scale, bit_length: int = 8):
    """Quantize-dequantize with straight-through gradient."""
    qmax = 2 ** (bit_length - 1) - 1

    @jax.custom_vjp
    def ste(v):
        return jnp.clip(jnp.round(v / scale), -qmax - 1, qmax) * scale

    def fwd(v):
        return ste(v), None

    def bwd(_, g):
        return (g,)

    ste.defvjp(fwd, bwd)
    return ste(x)


class AbsmaxObserver:
    """abs-max range observer (quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        import numpy as np
        v = float(np.max(np.abs(np.asarray(
            x.value if isinstance(x, Tensor) else x))))
        self._absmax = max(self._absmax, v)

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._absmax, 1e-8) / qmax


class EMAObserver:
    """Exponential-moving-average abs-max observer (round-5 VERDICT 6).

    Smooths per-batch range spikes during calibration: scale follows
    ``ema = m * ema + (1 - m) * batch_absmax`` instead of the running
    max, so one outlier batch doesn't pin the range forever (the
    reference's EMA/moving-average observer capability)."""

    def __init__(self, quant_bits: int = 8, momentum: float = 0.9):
        self.quant_bits = quant_bits
        self.momentum = momentum
        self._ema: Optional[float] = None

    def observe(self, x):
        import numpy as np
        v = float(np.max(np.abs(np.asarray(
            x.value if isinstance(x, Tensor) else x))))
        self._ema = v if self._ema is None else (
            self.momentum * self._ema + (1.0 - self.momentum) * v)

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._ema or 0.0, 1e-8) / qmax


class HistogramObserver:
    """Histogram-of-|x| observer; scale from a coverage percentile.

    Accumulates a fixed-bin histogram of absolute values, widening (and
    re-binning) when a batch exceeds the current range; ``scale()`` clips
    at the smallest threshold covering ``percent`` of the observed mass —
    robust to the long activation tails that break abs-max calibration."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048,
                 percent: float = 0.9999):
        self.quant_bits = quant_bits
        self.bins = bins
        self.percent = percent
        self._hist = None
        self._limit = 0.0

    def observe(self, x):
        import numpy as np
        v = np.abs(np.asarray(
            x.value if isinstance(x, Tensor) else x, np.float32)).ravel()
        vmax = float(v.max()) if v.size else 0.0
        if self._hist is None:
            self._limit = max(vmax, 1e-8)
            self._hist = np.zeros(self.bins, np.float64)
        elif vmax > self._limit:
            # widen: fold the existing histogram into the new binning
            new_limit = vmax
            ratio = self._limit / new_limit
            old_edges = np.linspace(0, ratio * self.bins, self.bins + 1)
            idx = np.clip(((old_edges[:-1] + old_edges[1:]) / 2).astype(int),
                          0, self.bins - 1)
            folded = np.zeros(self.bins, np.float64)
            np.add.at(folded, idx, self._hist)
            self._hist = folded
            self._limit = new_limit
        h, _ = np.histogram(v, bins=self.bins, range=(0.0, self._limit))
        self._hist += h

    def _threshold(self) -> float:
        import numpy as np
        if self._hist is None or self._hist.sum() == 0:
            return 1e-8
        cdf = np.cumsum(self._hist) / self._hist.sum()
        bin_i = int(np.searchsorted(cdf, self.percent))
        return (bin_i + 1) / self.bins * self._limit

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._threshold(), 1e-8) / qmax


class KLObserver(HistogramObserver):
    """KL-divergence calibration (the TensorRT / reference 'mse/kl'
    observer family): picks the clip threshold whose quantized
    distribution is closest (min KL) to the clipped reference
    distribution, trading outlier clipping against resolution."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048):
        super().__init__(quant_bits=quant_bits, bins=bins)

    def _threshold(self) -> float:
        import numpy as np
        if self._hist is None or self._hist.sum() == 0:
            return 1e-8
        nlevels = 2 ** (self.quant_bits - 1)        # 128 for int8
        hist = self._hist.astype(np.float64)
        best_i, best_kl = self.bins, np.inf
        for i in range(nlevels, self.bins + 1, max(1, self.bins // 128)):
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()                 # clip tail into last bin
            if p.sum() == 0:
                continue
            # quantize the i bins down to nlevels, then expand back
            chunks = np.array_split(p, nlevels)
            q = np.concatenate([
                np.full(len(c), c.sum() / max((c > 0).sum(), 1))
                * (c > 0) for c in chunks])
            pn = p / p.sum()
            qs = q.sum()
            if qs == 0:
                continue
            qn = q / qs
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(
                pn[mask] / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        return best_i / self.bins * self._limit


class QuantConfig:
    """quantization/config.py analog: which layers get which quanter."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or (lambda: AbsmaxObserver())
        self.weight = weight or (lambda: AbsmaxObserver())
        self._layer_types = (nn.Linear, nn.Conv2D)

    def add_layer_config(self, layer_types, activation=None, weight=None):
        self._layer_types = tuple(layer_types)


class _QuantedBase(nn.Layer):
    """Shared fake-quant wrapper state (QAT/PTQ simulation)."""

    def __init__(self, inner, w_scale: float, a_observer, bits: int = 8):
        super().__init__()
        self.inner = inner
        self.w_scale = w_scale
        self.a_observer = a_observer
        self.bits = bits
        self.calibrating = True
        self.int8_kernel = False

    def _a_scale(self, x):
        if self.calibrating:
            self.a_observer.observe(x)
        return self.a_observer.scale()


class QuantedLinear(_QuantedBase):
    """Linear with fake-quantized weight+activation; after convert() with
    ``int8_kernel`` the matmul really runs int8 x int8 -> int32 on the MXU
    (the deployment path, not just simulation)."""

    def _freeze_int8(self):
        """Build the frozen-scale int8 op ONCE at convert() time (scales
        stop moving then; rebuilding per forward is hot-path garbage)."""
        from paddle_tpu.ops.registry import OpDef
        ws, ascale = self.w_scale, self.a_observer.scale()
        qmax = 2 ** (self.bits - 1) - 1

        def impl(xv, wv):
            xq = jnp.clip(jnp.round(xv / ascale), -qmax - 1,
                          qmax).astype(jnp.int8)
            wq = jnp.clip(jnp.round(wv / ws), -qmax - 1,
                          qmax).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, wq, (((xv.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return acc.astype(jnp.float32) * (ascale * ws)

        self._int8_op = OpDef("int8_linear", impl, differentiable=False)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        a_scale = self._a_scale(x)
        if self.int8_kernel and not self.calibrating:
            from paddle_tpu.ops.registry import apply_op
            out = apply_op(self._int8_op, (x, self.inner.weight), {})
            return out + self.inner.bias if self.inner.bias is not None else out
        xq = fake_quantize(x, a_scale, self.bits)
        wq = fake_quantize(self.inner.weight, self.w_scale, self.bits)
        return F.linear(xq, wq, self.inner.bias)


class QuantedConv2D(_QuantedBase):
    """Conv2D with fake-quantized weight+activation
    (quantization/imperative quantized conv analog)."""

    def _freeze_int8(self):
        from paddle_tpu.ops.registry import OpDef
        ws, ascale = self.w_scale, self.a_observer.scale()
        qmax = 2 ** (self.bits - 1) - 1
        c = self.inner

        def impl(xv, wv):
            import paddle_tpu.nn.functional as FN
            xq = jnp.clip(jnp.round(xv / ascale), -qmax - 1, qmax)
            wq = jnp.clip(jnp.round(wv / ws), -qmax - 1, qmax)
            # int8 conv: quantized integer grids; XLA keeps the MXU layout.
            # Accumulate in f32 (conv transpose rule forbids a widened
            # preferred_element_type; values are exact integers < 2^21)
            out = FN.conv2d.op.impl(xq, wq, None, stride=c.stride,
                                    padding=c.padding, dilation=c.dilation,
                                    groups=c.groups)
            return out * (ascale * ws)

        self._int8_op = OpDef("int8_conv2d", impl, differentiable=False)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        a_scale = self._a_scale(x)
        c = self.inner
        if self.int8_kernel and not self.calibrating:
            from paddle_tpu.ops.registry import apply_op
            out = apply_op(self._int8_op, (x, c.weight), {})
            if c.bias is not None:
                out = out + paddle_reshape_bias(c.bias, out.ndim)
            return out
        xq = fake_quantize(x, a_scale, self.bits)
        wq = fake_quantize(self.inner.weight, self.w_scale, self.bits)
        return F.conv2d(xq, wq, c.bias, stride=c.stride, padding=c.padding,
                        dilation=c.dilation, groups=c.groups)


_WRAPPERS = {}  # filled below: inner layer type -> quanted wrapper


def _swap_quanted(model: nn.Layer, config: QuantConfig):
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, config._layer_types):
            cls = next((w for t, w in _WRAPPERS.items()
                        if isinstance(sub, t)), None)
            if cls is None:
                raise NotImplementedError(
                    f"no quantized wrapper for {type(sub).__name__}; "
                    f"supported: {[t.__name__ for t in _WRAPPERS]}")
            obs = config.weight()
            obs.observe(sub.weight)
            model._sub_layers[name] = cls(sub, obs.scale(),
                                          config.activation())
        else:
            _swap_quanted(sub, config)


class PTQ:
    """Post-training quantization driver (quantization/ptq.py)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: nn.Layer, inplace: bool = False):
        import copy
        m = model if inplace else copy.deepcopy(model)
        _swap_quanted(m, self.config)
        return m

    def convert(self, model: nn.Layer, inplace: bool = True,
                int8_kernel: bool = False):
        for _, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, _QuantedBase):
                sub.calibrating = False
                sub.int8_kernel = int8_kernel
                if int8_kernel:
                    sub._freeze_int8()
        return model


_WRAPPERS.update({nn.Conv2D: QuantedConv2D, nn.Linear: QuantedLinear})


def paddle_reshape_bias(bias, ndim):
    shape = [1] * ndim
    shape[1] = bias.shape[0]
    import paddle_tpu as paddle
    return paddle.reshape(bias, shape)


class QAT(PTQ):
    """Quant-aware training: same wrappers, calibration stays live so the
    STE fake-quant trains through (quantization/qat.py)."""


# --------------------------------------------------------------------------
# weight-only quantization for inference (paddle.nn.quant analogs:
# ops.yaml weight_quantize / weight_only_linear / llm_int8_linear)
# --------------------------------------------------------------------------

@register_op("weight_quantize",
             ref="paddle/phi/ops/yaml/ops.yaml:weight_quantize",
             n_outputs=2, differentiable=False)
def weight_quantize(w, algo="weight_only_int8"):
    """Per-output-channel int8 quantization of a (in, out) weight matrix.
    Returns (int8 weight, f32 per-channel scale)."""
    if algo not in ("weight_only_int8", "llm.int8"):
        raise NotImplementedError(f"weight_quantize algo {algo!r}")
    scale = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale


@register_op("weight_only_linear",
             ref="paddle/phi/ops/yaml/ops.yaml:weight_only_linear")
def weight_only_linear(x, weight, weight_scale, bias=None,
                       weight_dtype="int8"):
    """x @ dequant(int8 weight): weights stay int8 in HBM (half the
    memory traffic of bf16 — the decode-bandwidth lever the reference's
    weight_only_linear kernel exists for). Per-output-channel scales apply
    AFTER the matmul, so no dequantized weight copy is materialized (the
    same form inference/generate._mm uses)."""
    out = jnp.matmul(x, weight.astype(x.dtype)) \
        * weight_scale.astype(x.dtype)
    if bias is not None:
        out = out + bias
    return out


@register_op("llm_int8_linear",
             ref="paddle/phi/ops/yaml/ops.yaml:llm_int8_linear")
def llm_int8_linear(x, weight, weight_scale, bias=None, threshold=6.0):
    """LLM.int8()-style mixed decomposition (x (..., in) @ int8 (in, out)):
    inlier input-feature columns quantize per-row to int8 and run an
    int8 x int8 matmul with int32 accumulation (the MXU int8 path);
    outlier columns (any |x| > threshold) run in f32 against the
    dequantized weight rows, and the two halves sum."""
    xf = x.astype(jnp.float32)
    lead = tuple(range(x.ndim - 1))
    outlier = jnp.any(jnp.abs(xf) > threshold, axis=lead)      # (in,)
    x_main = jnp.where(outlier, 0.0, xf)
    x_scale = jnp.max(jnp.abs(x_main), axis=-1, keepdims=True) / 127.0
    x_scale = jnp.maximum(x_scale, 1e-8)
    xq = jnp.clip(jnp.round(x_main / x_scale), -127, 127).astype(jnp.int8)
    main = jnp.matmul(xq, weight, preferred_element_type=jnp.int32)
    main = main.astype(jnp.float32) * x_scale \
        * weight_scale.astype(jnp.float32)[None, :]
    x_out = jnp.where(outlier, xf, 0.0)
    wf = weight.astype(jnp.float32) * weight_scale.astype(jnp.float32)[None, :]
    out = main + jnp.matmul(x_out, wf)
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)
