"""Int8 KV-cache quantization for the decode stack (the ``int8wk`` recipe).

Pope et al. (PAPERS.md): small-batch decode is bound by HBM reads of the
weights AND the KV cache — every decoded token re-reads the whole valid
prefix of K/V. Storing the cache int8 cuts that stream ~4x vs f32 (~2x
vs bf16) at the cost of one dequant multiply that fuses into the
attention matmuls (dequant-on-load feeding the MXU; LLM.int8/AWQ
weight-only lineage, PAPERS.md).

Representation: a quantized cache buffer is a plain ``{"q", "s"}`` dict
(a standard pytree — it flows through jit carries, ``jax.export``
bundle entries, the serving engine's admission row-scatter and the
prefix-cache slab ops without any custom-node registration):

- ``q``: int8, the same shape the unquantized cache buffer had;
- ``s``: f32 per-position-per-head scales with a KEPT last dim of 1
  (``q.shape[:-1] + (1,)``), so every structural transform that indexes
  "the rank-relative batch/length axis" (``ndim - 4`` in the engine
  scatter and SlabOps) lands on the same axis for both leaves.

Each written K/V row quantizes by its own absmax over the head dim —
scales travel WITH their rows, so chunk re-entry, admission scatter and
prefix-slab extract/load stay bit-exact with run-to-completion (the
quantize/dequantize of a row depends only on that row's values).
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["QuantMismatchError", "canonical_quant", "resolve_decode_quant",
           "is_quantized_kv", "quantize_kv_rows", "dequantize_kv",
           "quant_kv_zeros", "QUANT_RECIPES"]

#: the decode dtype recipes: int8w = per-channel absmax int8 weights
#: (fp32 scales), int8wk = int8w + int8 KV cache (per-row absmax scales)
QUANT_RECIPES = ("int8w", "int8wk")

_NONE_ASKS = ("", "none", "fp32", "float32", "bf16", "bfloat16")


class QuantMismatchError(ValueError):
    """A quantization contract violation: an unquantized decoder/bundle
    asked to serve a quantized recipe, a quantized bundle asked to serve
    a different recipe (or fp32), or conflicting ``quant=`` /
    ``weight_dtype=`` arguments. Typed so callers refuse up front
    instead of silently serving the wrong dtype recipe."""


def canonical_quant(quant) -> Optional[str]:
    """Normalize a quant ask: ``None``/``""``/``"none"``/``"fp32"`` ->
    ``None`` (unquantized); ``"int8w"``/``"int8wk"`` -> themselves;
    anything else is a typed refusal."""
    if quant is None:
        return None
    q = str(quant).strip().lower()
    if q in _NONE_ASKS:
        return None
    if q not in QUANT_RECIPES:
        raise QuantMismatchError(
            f"unknown decode quant recipe {quant!r}; expected one of "
            f"{QUANT_RECIPES} (or none/fp32 for the unquantized path)")
    return q


def resolve_decode_quant(quant=None, weight_dtype=None) -> Optional[str]:
    """The decoder-init recipe resolution: an explicit ``quant=`` wins;
    the legacy ``weight_dtype="int8"`` aliases ``"int8w"``; with neither,
    the ``PADDLE_TPU_DECODE_QUANT`` env / ``FLAGS_decode_quant`` default
    applies (empty = unquantized). Conflicting explicit arguments are a
    typed refusal."""
    if weight_dtype not in (None, "int8"):
        raise ValueError(f"weight_dtype must be None or 'int8', "
                         f"got {weight_dtype!r}")
    alias = "int8w" if weight_dtype == "int8" else None
    if quant is not None:
        q = canonical_quant(quant)
        if alias is not None and q is None:
            raise QuantMismatchError(
                f"quant={quant!r} contradicts weight_dtype='int8' "
                f"(pass one or the other)")
        return q
    if alias is not None:
        return alias
    env = os.environ.get("PADDLE_TPU_DECODE_QUANT", "").strip()
    if env:
        return canonical_quant(env)
    from paddle_tpu.flags import flags
    return canonical_quant(flags.decode_quant)


def is_quantized_kv(cache) -> bool:
    """True for one quantized cache buffer (the ``{"q", "s"}`` dict)."""
    return isinstance(cache, dict) and "q" in cache and "s" in cache


def quantize_kv_rows(t):
    """Quantize freshly computed K/V rows ``t (..., D)`` by per-row
    absmax over the head dim: returns ``{"q": int8 (..., D),
    "s": f32 (..., 1)}``. Deterministic and row-local — the property
    every re-entry/scatter bit-exactness claim rides on."""
    import jax.numpy as jnp
    x = t.astype(jnp.float32)
    s = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(x / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def dequantize_kv(cache, dtype):
    """Dequant-on-load: int8 rows times their per-row scales, in
    ``dtype``. Unquantized buffers pass through untouched, so attention
    code can call this unconditionally."""
    if not is_quantized_kv(cache):
        return cache
    return cache["q"].astype(dtype) * cache["s"].astype(dtype)


def quant_kv_zeros(shape, jnp=None):
    """An empty quantized cache buffer of the given (unquantized) cache
    shape."""
    if jnp is None:
        import jax.numpy as jnp
    return {"q": jnp.zeros(shape, jnp.int8),
            "s": jnp.zeros(tuple(shape[:-1]) + (1,), jnp.float32)}
