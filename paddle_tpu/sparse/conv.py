"""Sparse convolution family (round-5 VERDICT item 5).

Capability analog of python/paddle/sparse/nn/layer/conv.py (Conv3D /
SubmConv3D / Conv2D / SubmConv2D) and pooling.py (MaxPool3D) over the
reference's rulebook kernels (paddle/phi/kernels/sparse/gpu/conv_kernel.cu).

TPU-native formulation: the rulebook — per kernel offset, the (input
point, output point) pair list — is built ON HOST from the concrete COO
indices (the same dynamic-shape step the reference runs as a GPU kernel;
under XLA dynamic result sizes cannot live on device), and the compute is
a pure gather → (nnz_k, Cin) @ (Cin, Cout) matmul → scatter-add per
offset, which XLA maps onto the MXU. Gradients flow through a values
Tensor recorded on the autograd tape (``_values_tensor``), so stacked
sparse convs backprop end-to-end into weights, biases, and input values.

Layout contract (reference conv layout): a SparseCooTensor of shape
(N, *spatial, C) whose BCOO carries the batch+spatial axes as sparse
index columns and the channel axis DENSE — values (nnz, C), indices
(nnz, 1 + ndim). ``sparse.sparse_coo_tensor(indices_(1+nd, nnz),
values_(nnz, C), shape)`` builds exactly this.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import OpDef, apply_op

__all__ = ["conv3d", "subm_conv3d", "conv2d", "subm_conv2d",
           "max_pool3d", "avg_pool3d"]


def _tuple(v, nd: int) -> Tuple[int, ...]:
    if isinstance(v, (list, tuple)):
        if len(v) != nd:
            raise ValueError(f"expected {nd} entries, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * nd


def _coo_parts(x):
    """(np indices (nnz, 1+nd), values Tensor (nnz, C), shape) from a
    conv-layout sparse tensor; validates the dense-channel contract."""
    m = x._value
    if not isinstance(m, jsparse.BCOO):
        raise TypeError("sparse conv expects a SparseCooTensor input")
    if m.data.ndim != 2:
        raise ValueError(
            "sparse conv expects the conv layout — values (nnz, C) with "
            "batch+spatial sparse and channels dense; build the input "
            "with sparse_coo_tensor(indices (1+ndim, nnz), values "
            "(nnz, C), (N, *spatial, C))")
    vt = getattr(x, "_values_tensor", None)
    if vt is None:
        vt = Tensor(m.data, stop_gradient=x.stop_gradient)
    idx = np.asarray(jax.device_get(m.indices))
    return idx, vt, tuple(m.shape)


def _wrap_out(vals_t: Tensor, out_idx: np.ndarray, shape) -> "Tensor":
    from paddle_tpu.sparse import SparseCooTensor
    t = SparseCooTensor(0.0, stop_gradient=vals_t.stop_gradient)
    t._value = jsparse.BCOO((vals_t._value, jnp.asarray(out_idx)),
                            shape=tuple(shape))
    t._values_tensor = vals_t   # autograd linkage for stacked sparse ops
    return t


def _coord_ids(a: np.ndarray, b: np.ndarray):
    """Map each row of ``b`` to its row index in ``a`` (-1 if absent)."""
    both = np.concatenate([a, b], axis=0)
    uniq, inv = np.unique(both, axis=0, return_inverse=True)
    lut = np.full(len(uniq), -1, np.int64)
    lut[inv[:len(a)]] = np.arange(len(a))
    return lut[inv[len(a):]]


def _out_spatial(spatial, ksize, stride, padding, dilation):
    return tuple(
        (s + 2 * p - d * (k - 1) - 1) // st + 1
        for s, k, st, p, d in zip(spatial, ksize, stride, padding, dilation))


def _rulebook(idx: np.ndarray, spatial, ksize, stride, padding, dilation,
              subm: bool):
    """Per-kernel-offset (input row, output row) pair lists + out indices.

    subm: output pattern == input pattern (stride 1, odd kernel);
    regular: output pattern = the set of output coords any input reaches.
    """
    nd = len(ksize)
    offsets = list(np.ndindex(*ksize))
    coords = idx[:, 1:].astype(np.int64)
    batch = idx[:, :1].astype(np.int64)

    if subm:
        center = np.array([(k - 1) // 2 for k in ksize], np.int64)
        pairs = []
        full = np.concatenate([batch, coords], axis=1)
        for off in offsets:
            src = coords + (np.asarray(off, np.int64) - center) \
                * np.asarray(dilation, np.int64)
            cand = np.concatenate([batch, src], axis=1)
            m = _coord_ids(full, cand)
            oo = np.where(m >= 0)[0]
            pairs.append((m[oo], oo))
        return idx, pairs

    out_sp = _out_spatial(spatial, ksize, stride, padding, dilation)
    st = np.asarray(stride, np.int64)
    pad = np.asarray(padding, np.int64)
    dil = np.asarray(dilation, np.int64)
    contrib_in, contrib_k, contrib_coord = [], [], []
    for k, off in enumerate(offsets):
        num = coords + pad - np.asarray(off, np.int64) * dil
        ok = (num % st == 0).all(axis=1)
        oc = num // st
        ok &= ((oc >= 0) & (oc < np.asarray(out_sp, np.int64))).all(axis=1)
        sel = np.where(ok)[0]
        if len(sel):
            contrib_in.append(sel)
            contrib_k.append(np.full(len(sel), k, np.int64))
            contrib_coord.append(
                np.concatenate([batch[sel], oc[sel]], axis=1))
    if not contrib_in:
        out_idx = np.zeros((0, 1 + nd), idx.dtype)
        return out_idx, [(np.zeros(0, np.int64),) * 2 for _ in offsets]
    all_in = np.concatenate(contrib_in)
    all_k = np.concatenate(contrib_k)
    all_coord = np.concatenate(contrib_coord, axis=0)
    out_idx, inv = np.unique(all_coord, axis=0, return_inverse=True)
    pairs = []
    for k in range(len(offsets)):
        sel = np.where(all_k == k)[0]
        pairs.append((all_in[sel], inv[sel]))
    return out_idx.astype(idx.dtype), pairs


def _sparse_conv(x, weight, bias, stride, padding, dilation, subm,
                 name: str):
    idx, vals_t, shape = _coo_parts(x)
    nd = len(shape) - 2
    ksize = tuple(int(s) for s in weight.shape[:nd])
    cin, cout = int(weight.shape[nd]), int(weight.shape[nd + 1])
    if cin != shape[-1]:
        raise ValueError(f"in_channels {cin} != input channels {shape[-1]}")
    stride = _tuple(stride, nd)
    padding = _tuple(padding, nd)
    dilation = _tuple(dilation, nd)
    if subm:
        if any(s != 1 for s in stride):
            raise ValueError("submanifold conv requires stride=1 "
                             "(it preserves the input pattern)")
        if any(k % 2 == 0 for k in ksize):
            raise ValueError("submanifold conv requires odd kernel sizes")
        out_sp = tuple(shape[1:-1])
    else:
        out_sp = _out_spatial(shape[1:-1], ksize, stride, padding, dilation)
    out_idx, pairs = _rulebook(idx, shape[1:-1], ksize, stride, padding,
                               dilation, subm)
    n_out = len(out_idx)
    K = int(np.prod(ksize))
    # freeze pair arrays as device constants once (they are static data)
    jpairs = [(jnp.asarray(ii), jnp.asarray(oo)) for ii, oo in pairs
              if len(ii)]
    kidx = [k for k, (ii, _) in enumerate(pairs) if len(ii)]

    def impl(vals, w, *maybe_b):
        w2 = w.reshape(K, cin, cout)
        dt = jnp.result_type(vals.dtype, w.dtype)
        out = jnp.zeros((n_out, cout), dt)
        for k, (ii, oo) in zip(kidx, jpairs):
            out = out.at[oo].add(
                jax.lax.dot_general(vals[ii], w2[k],
                                    (((1,), (0,)), ((), ()))))
        if maybe_b:
            out = out + maybe_b[0]
        return out

    opdef = OpDef(name, impl,
                  ref="paddle/phi/kernels/sparse/gpu/conv_kernel.cu")
    args = (vals_t, weight) + ((bias,) if bias is not None else ())
    out_vals = apply_op(opdef, args, {})
    return _wrap_out(out_vals, out_idx,
                     (shape[0],) + tuple(out_sp) + (cout,))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Sparse 3D convolution; output pattern is the reachable-coord set.
    Parity: python/paddle/sparse/nn/functional/conv.py::conv3d."""
    if groups != 1:
        raise NotImplementedError("sparse conv: groups=1 only")
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d is channels-last (NDHWC)")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=False, name="sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse 3D conv: output pattern == input pattern, so
    stacking preserves sparsity (the point-cloud workhorse).
    Parity: python/paddle/sparse/nn/functional/conv.py::subm_conv3d."""
    if groups != 1:
        raise NotImplementedError("sparse conv: groups=1 only")
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d is channels-last (NDHWC)")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=True, name="sparse_subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", name=None):
    """Sparse 2D convolution (NHWC)."""
    if groups != 1:
        raise NotImplementedError("sparse conv: groups=1 only")
    if data_format != "NHWC":
        raise ValueError("sparse conv2d is channels-last (NHWC)")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=False, name="sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    """Submanifold sparse 2D conv (NHWC)."""
    if groups != 1:
        raise NotImplementedError("sparse conv: groups=1 only")
    if data_format != "NHWC":
        raise ValueError("sparse subm_conv2d is channels-last (NHWC)")
    return _sparse_conv(x, weight, bias, stride, padding, dilation,
                        subm=True, name="sparse_subm_conv2d")


def _sparse_pool(x, kernel_size, stride, padding, mode: str):
    idx, vals_t, shape = _coo_parts(x)
    nd = len(shape) - 2
    ksize = _tuple(kernel_size, nd)
    stride = _tuple(stride if stride is not None else kernel_size, nd)
    padding = _tuple(padding, nd)
    dilation = (1,) * nd
    out_sp = _out_spatial(shape[1:-1], ksize, stride, padding, dilation)
    out_idx, pairs = _rulebook(idx, shape[1:-1], ksize, stride, padding,
                               dilation, subm=False)
    n_out = len(out_idx)
    all_ii = np.concatenate([ii for ii, _ in pairs]) if pairs else \
        np.zeros(0, np.int64)
    all_oo = np.concatenate([oo for _, oo in pairs]) if pairs else \
        np.zeros(0, np.int64)
    jii, joo = jnp.asarray(all_ii), jnp.asarray(all_oo)

    def impl(vals):
        g = vals[jii]                       # (P, C)
        if mode == "max":
            return jax.ops.segment_max(g, joo, num_segments=n_out)
        s = jax.ops.segment_sum(g, joo, num_segments=n_out)
        cnt = jax.ops.segment_sum(jnp.ones((g.shape[0], 1), g.dtype), joo,
                                  num_segments=n_out)
        return s / jnp.maximum(cnt, 1.0)

    opdef = OpDef(f"sparse_{mode}_pool{nd}d", impl,
                  ref="paddle/phi/kernels/sparse/gpu/pool_kernel.cu")
    out_vals = apply_op(opdef, (vals_t,), {})
    return _wrap_out(out_vals, out_idx,
                     (shape[0],) + tuple(out_sp) + (shape[-1],))


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling over the STORED points per window (implicit
    zeros are absent, matching the reference's sparse maxpool).
    Parity: python/paddle/sparse/nn/functional/pooling.py::max_pool3d."""
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d is channels-last (NDHWC)")
    return _sparse_pool(x, kernel_size, stride, padding, "max")


def avg_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse average pooling (mean over stored points per window)."""
    if data_format != "NDHWC":
        raise ValueError("sparse avg_pool3d is channels-last (NDHWC)")
    return _sparse_pool(x, kernel_size, stride, padding, "avg")
