"""paddle_tpu.sparse — COO/CSR sparse tensors (python/paddle/sparse/ analog).

Built on jax.experimental.sparse BCOO/BCSR: sparse tensors stay jax
pytrees, matmul lowers to XLA gather/scatter (TPU has no sparse MXU path,
so like the reference's cuSPARSE fallback this is bandwidth-bound — the
structured 2:4 path lives in incubate.asp).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import OpDef, apply_op

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "subtract", "multiply", "matmul", "masked_matmul",
           "relu", "to_dense", "to_sparse_coo", "coalesce", "nnz", "transpose"]


class SparseCooTensor(Tensor):
    """Tensor whose _value is a BCOO array; dense ops densify explicitly."""

    @property
    def indices_t(self):
        return Tensor(jnp.asarray(self._value.indices).T)

    def indices(self):
        return self.indices_t

    def values(self):
        # conv-layout tensors carry a tape-linked values Tensor (see
        # sparse/conv.py): return it so backward() reaches the producers
        vt = getattr(self, "_values_tensor", None)
        return vt if vt is not None else Tensor(self._value.data)

    def to_dense(self):
        return Tensor(self._value.todense())

    def nnz(self):
        return int(self._value.nse)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True) -> SparseCooTensor:
    idx = jnp.asarray(indices.value if isinstance(indices, Tensor) else indices)
    vals = jnp.asarray(values.value if isinstance(values, Tensor) else values,
                       dtype=dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in jnp.max(idx, axis=1))
    mat = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    t = SparseCooTensor(0.0, stop_gradient=stop_gradient)
    t._value = mat
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """CSR input surface; stored as BCOO internally (one generation of
    sparse kernels — reference keeps separate Coo/Csr kernel sets)."""
    import numpy as np
    crows = np.asarray(crows.value if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.value if isinstance(cols, Tensor) else cols)
    vals = np.asarray(values.value if isinstance(values, Tensor) else values)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = np.stack([rows, cols])
    return sparse_coo_tensor(idx, vals, shape, dtype=dtype,
                             stop_gradient=stop_gradient)


def _sp(x):
    return x._value if isinstance(x, Tensor) else x


def is_same_shape(x, y) -> bool:
    return tuple(_sp(x).shape) == tuple(_sp(y).shape)


def _wrap_sparse(mat) -> SparseCooTensor:
    t = SparseCooTensor(0.0)
    t._value = mat
    return t


def add(x, y):
    # residual connections between conv-layout tensors with IDENTICAL
    # patterns keep the tape chain; other pattern combinations go through
    # BCOO addition (correct values, no values-tape linkage)
    xt = getattr(x, "_values_tensor", None)
    yt = getattr(y, "_values_tensor", None)
    if (xt is not None and yt is not None
            and not (xt.stop_gradient and yt.stop_gradient)):
        import numpy as _np
        xm, ym = _sp(x), _sp(y)
        if (xm.indices.shape == ym.indices.shape
                and bool(jnp.all(xm.indices == ym.indices))):
            out_t = apply_op(OpDef("sparse_add", lambda a, b: a + b),
                             (xt, yt), {})
            t = _wrap_sparse(jsparse.BCOO((out_t._value, xm.indices),
                                          shape=xm.shape))
            t._values_tensor = out_t
            t.stop_gradient = out_t.stop_gradient
            return t
    r = _sp(x) + _sp(y)
    return _wrap_sparse(r) if isinstance(r, jsparse.BCOO) else Tensor(r)


def subtract(x, y):
    r = _sp(x) + (-1.0) * _sp(y)
    return _wrap_sparse(r) if isinstance(r, jsparse.BCOO) else Tensor(r)


def multiply(x, y):
    xm = _sp(x)
    if isinstance(xm, jsparse.BCOO):
        ym = _sp(y)
        yd = ym.todense() if isinstance(ym, jsparse.BCOO) else ym
        picked = yd[tuple(xm.indices.T)]
        return _wrap_sparse(jsparse.BCOO((xm.data * picked, xm.indices),
                                         shape=xm.shape))
    return Tensor(xm * _sp(y))


def matmul(x, y):
    """sparse @ dense (phi sparse matmul kernel analog); differentiable."""
    xm, ym = _sp(x), _sp(y)

    def impl(dense):
        return xm @ dense

    if isinstance(ym, jsparse.BCOO):
        return _wrap_sparse(xm @ ym)
    if isinstance(y, Tensor):
        opdef = OpDef("sparse_matmul", impl)
        return apply_op(opdef, (y,), {})
    return Tensor(xm @ jnp.asarray(ym))


def masked_matmul(x, y, mask):
    """(dense @ dense) sampled at mask's sparsity (SDDMM)."""
    xd, yd, mm = _sp(x), _sp(y), _sp(mask)
    idx = mm.indices
    rows = xd[idx[:, 0]]
    cols = yd[:, idx[:, 1]].T
    vals = jnp.sum(rows * cols, axis=-1)
    return _wrap_sparse(jsparse.BCOO((vals, idx), shape=mm.shape))


def _apply_valuewise(x, name, fn, *args):
    """Sparsity-preserving value-wise op. Conv-layout tensors carry a
    tape-linked values Tensor (sparse/conv.py): route through the op
    registry so stacked sparse nets backprop through EVERY value-wise op,
    not just relu."""
    m = _sp(x)
    vt = getattr(x, "_values_tensor", None)
    if vt is not None and not vt.stop_gradient:
        out_t = apply_op(OpDef(name, lambda v: fn(v, *args)), (vt,), {})
        t = _wrap_sparse(jsparse.BCOO((out_t._value, m.indices),
                                      shape=m.shape))
        t._values_tensor = out_t
        t.stop_gradient = out_t.stop_gradient
        return t
    return _wrap_sparse(jsparse.BCOO((fn(m.data, *args), m.indices),
                                     shape=m.shape))


def relu(x):
    return _apply_valuewise(x, "sparse_relu", lambda v: jnp.maximum(v, 0))


def to_dense(x):
    return Tensor(_sp(x).todense())


def to_sparse_coo(x, sparse_dim=None):
    return _wrap_sparse(jsparse.BCOO.fromdense(_sp(x)))


def coalesce(x):
    return _wrap_sparse(_sp(x).sum_duplicates())


def nnz(x) -> int:
    return int(_sp(x).nse)


def transpose(x, perm):
    return _wrap_sparse(_sp(x).transpose(tuple(perm)))


# --------------------------------------------------------------------------
# value-wise unary math (sparsity-preserving; python/paddle/sparse/unary.py)
# --------------------------------------------------------------------------

def _valuewise(name, fn):
    def op(x, *args):
        return _apply_valuewise(x, f"sparse_{name}", fn, *args)

    op.__name__ = name
    op.__doc__ = (f"sparse.{name}: apply {name} to the stored values; "
                  "zero entries stay zero (sparsity-preserving unary, "
                  "python/paddle/sparse/unary.py analog).")
    globals()[name] = op
    __all__.append(name)
    return op


for _n, _f in [
    ("sin", jnp.sin), ("tan", jnp.tan), ("asin", jnp.arcsin),
    ("atan", jnp.arctan), ("sinh", jnp.sinh), ("tanh", jnp.tanh),
    ("asinh", jnp.arcsinh), ("atanh", jnp.arctanh), ("sqrt", jnp.sqrt),
    ("square", jnp.square), ("log1p", jnp.log1p), ("abs", jnp.abs),
    ("expm1", jnp.expm1), ("neg", lambda v: -v),
    ("leaky_relu", lambda v, slope=0.01: jnp.where(v >= 0, v, slope * v)),
    ("relu6", lambda v: jnp.clip(v, 0.0, 6.0)),
]:
    _valuewise(_n, _f)


def pow(x, factor):  # noqa: A001 - paddle API name
    m = _sp(x)
    return _wrap_sparse(jsparse.BCOO((m.data ** factor, m.indices),
                                     shape=m.shape))


def cast(x, index_dtype=None, value_dtype=None):
    m = _sp(x)
    data = m.data.astype(value_dtype) if value_dtype else m.data
    idx = m.indices.astype(index_dtype) if index_dtype else m.indices
    return _wrap_sparse(jsparse.BCOO((data, idx), shape=m.shape))


def divide(x, y):
    xm = _sp(x)
    ym = _sp(y)
    yd = ym.todense() if isinstance(ym, jsparse.BCOO) else jnp.asarray(ym)
    if jnp.ndim(yd) == 0:
        return _wrap_sparse(jsparse.BCOO((xm.data / yd, xm.indices),
                                         shape=xm.shape))
    picked = yd[tuple(xm.indices.T)]
    return _wrap_sparse(jsparse.BCOO((xm.data / picked, xm.indices),
                                     shape=xm.shape))


def softmax(x, axis=-1):
    """Row-wise softmax over the STORED entries only (implicit zeros are
    excluded), the reference's sparse softmax semantics
    (paddle/phi/kernels/sparse/cpu/softmax_kernel.cc). Rows are identified
    by ALL leading index dims (batched sparse inputs normalize per row,
    not per dim-0 slab)."""
    import jax
    m = _sp(x).sum_duplicates()
    if axis not in (-1, m.ndim - 1):
        raise NotImplementedError("sparse softmax: last axis only")
    lead = m.indices[:, :-1]                   # (nnz, ndim-1)
    strides = []
    acc = 1
    for d in m.shape[:-1][::-1]:
        strides.append(acc)
        acc *= d
    strides = jnp.asarray(strides[::-1], lead.dtype)
    rows = jnp.sum(lead * strides[None, :], axis=1) if lead.shape[1] else \
        jnp.zeros((m.indices.shape[0],), m.indices.dtype)
    n_rows = int(acc)
    row_max = jax.ops.segment_max(m.data, rows, num_segments=n_rows)
    shifted = jnp.exp(m.data - row_max[rows])
    denom = jax.ops.segment_sum(shifted, rows, num_segments=n_rows)
    return _wrap_sparse(jsparse.BCOO((shifted / denom[rows], m.indices),
                                     shape=m.shape))


__all__ += ["pow", "cast", "divide", "softmax", "matmul_values", "nn"]


def matmul_values(values, indices, shape, dense):
    """sparse @ dense, differentiable wrt the sparse VALUES (the sparse
    training story): ``values`` is a (possibly trainable Parameter) value
    vector, ``indices`` the (2, nnz) COO pattern closed over as static, so
    ``backward()`` lands grads directly on the persistent values tensor."""
    idx = jnp.asarray(_sp(indices)).T if jnp.ndim(_sp(indices)) == 2 and \
        jnp.shape(_sp(indices))[0] == 2 else jnp.asarray(_sp(indices))
    shape = tuple(shape)

    def impl(v, d):
        return jsparse.BCOO((v, idx), shape=shape) @ d

    opdef = OpDef("sparse_matmul_values", impl)
    return apply_op(opdef, (values, dense), {})


from paddle_tpu.sparse import nn  # noqa: E402,F401
