"""paddle_tpu.sparse — COO/CSR sparse tensors (python/paddle/sparse/ analog).

Built on jax.experimental.sparse BCOO/BCSR: sparse tensors stay jax
pytrees, matmul lowers to XLA gather/scatter (TPU has no sparse MXU path,
so like the reference's cuSPARSE fallback this is bandwidth-bound — the
structured 2:4 path lives in incubate.asp).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import OpDef, apply_op

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "subtract", "multiply", "matmul", "masked_matmul",
           "relu", "to_dense", "to_sparse_coo", "coalesce", "nnz", "transpose"]


class SparseCooTensor(Tensor):
    """Tensor whose _value is a BCOO array; dense ops densify explicitly."""

    @property
    def indices_t(self):
        return Tensor(jnp.asarray(self._value.indices).T)

    def indices(self):
        return self.indices_t

    def values(self):
        return Tensor(self._value.data)

    def to_dense(self):
        return Tensor(self._value.todense())

    def nnz(self):
        return int(self._value.nse)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True) -> SparseCooTensor:
    idx = jnp.asarray(indices.value if isinstance(indices, Tensor) else indices)
    vals = jnp.asarray(values.value if isinstance(values, Tensor) else values,
                       dtype=dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in jnp.max(idx, axis=1))
    mat = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    t = SparseCooTensor(0.0, stop_gradient=stop_gradient)
    t._value = mat
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """CSR input surface; stored as BCOO internally (one generation of
    sparse kernels — reference keeps separate Coo/Csr kernel sets)."""
    import numpy as np
    crows = np.asarray(crows.value if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.value if isinstance(cols, Tensor) else cols)
    vals = np.asarray(values.value if isinstance(values, Tensor) else values)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = np.stack([rows, cols])
    return sparse_coo_tensor(idx, vals, shape, dtype=dtype,
                             stop_gradient=stop_gradient)


def _sp(x):
    return x._value if isinstance(x, Tensor) else x


def is_same_shape(x, y) -> bool:
    return tuple(_sp(x).shape) == tuple(_sp(y).shape)


def _wrap_sparse(mat) -> SparseCooTensor:
    t = SparseCooTensor(0.0)
    t._value = mat
    return t


def add(x, y):
    r = _sp(x) + _sp(y)
    return _wrap_sparse(r) if isinstance(r, jsparse.BCOO) else Tensor(r)


def subtract(x, y):
    r = _sp(x) + (-1.0) * _sp(y)
    return _wrap_sparse(r) if isinstance(r, jsparse.BCOO) else Tensor(r)


def multiply(x, y):
    xm = _sp(x)
    if isinstance(xm, jsparse.BCOO):
        ym = _sp(y)
        yd = ym.todense() if isinstance(ym, jsparse.BCOO) else ym
        picked = yd[tuple(xm.indices.T)]
        return _wrap_sparse(jsparse.BCOO((xm.data * picked, xm.indices),
                                         shape=xm.shape))
    return Tensor(xm * _sp(y))


def matmul(x, y):
    """sparse @ dense (phi sparse matmul kernel analog); differentiable."""
    xm, ym = _sp(x), _sp(y)

    def impl(dense):
        return xm @ dense

    if isinstance(ym, jsparse.BCOO):
        return _wrap_sparse(xm @ ym)
    if isinstance(y, Tensor):
        opdef = OpDef("sparse_matmul", impl)
        return apply_op(opdef, (y,), {})
    return Tensor(xm @ jnp.asarray(ym))


def masked_matmul(x, y, mask):
    """(dense @ dense) sampled at mask's sparsity (SDDMM)."""
    xd, yd, mm = _sp(x), _sp(y), _sp(mask)
    idx = mm.indices
    rows = xd[idx[:, 0]]
    cols = yd[:, idx[:, 1]].T
    vals = jnp.sum(rows * cols, axis=-1)
    return _wrap_sparse(jsparse.BCOO((vals, idx), shape=mm.shape))


def relu(x):
    m = _sp(x)
    return _wrap_sparse(jsparse.BCOO((jnp.maximum(m.data, 0), m.indices),
                                     shape=m.shape))


def to_dense(x):
    return Tensor(_sp(x).todense())


def to_sparse_coo(x, sparse_dim=None):
    return _wrap_sparse(jsparse.BCOO.fromdense(_sp(x)))


def coalesce(x):
    return _wrap_sparse(_sp(x).sum_duplicates())


def nnz(x) -> int:
    return int(_sp(x).nse)


def transpose(x, perm):
    return _wrap_sparse(_sp(x).transpose(tuple(perm)))
