"""paddle_tpu.sparse.nn — layers over sparse tensors
(python/paddle/sparse/nn/ analog).

Activation layers apply sparsity-preserving value-wise ops; BatchNorm
normalizes the stored values per channel (last dim), matching the
reference's sparse BatchNorm semantics (statistics over non-zero entries,
paddle/phi/kernels/sparse/batch_norm_kernel.cc); Linear is a trainable
fixed-pattern sparse weight trained via sparse.matmul_values.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import paddle_tpu.sparse as sparse
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer

from paddle_tpu.sparse import conv as functional  # noqa: E402

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SparseLinear", "Conv3D", "SubmConv3D", "Conv2D", "SubmConv2D",
           "MaxPool3D", "AvgPool3D", "functional"]


class ReLU(Layer):
    def forward(self, x):
        return sparse.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return sparse.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return sparse.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return sparse.softmax(x, axis=self.axis)


class BatchNorm(Layer):
    """Normalize stored values per channel (the trailing dense dim of an
    (N, ..., C)-shaped sparse tensor)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        super().__init__()
        from paddle_tpu.nn import initializer as init
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        # gamma=1 / beta=0, the reference BatchNorm initialization
        self.weight = self.create_parameter(
            [num_features], default_initializer=init.Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        from jax.experimental import sparse as jsparse

        m = x._value
        if m.data.ndim == 2:
            return self._forward_dense_channels(x, m)
        ch = m.indices[:, -1]
        vals = m.data
        if self.training:
            mean = jnp.zeros((self.num_features,)).at[ch].add(vals)
            cnt = jnp.zeros((self.num_features,)).at[ch].add(1.0)
            mean = mean / jnp.maximum(cnt, 1.0)
            var = jnp.zeros((self.num_features,)).at[ch].add(
                (vals - mean[ch]) ** 2) / jnp.maximum(cnt, 1.0)
            self._mean._set_value(self.momentum * self._mean.value
                                  + (1 - self.momentum) * mean)
            self._variance._set_value(self.momentum * self._variance.value
                                      + (1 - self.momentum) * var)
        else:
            mean, var = self._mean.value, self._variance.value
        normed = (vals - mean[ch]) / jnp.sqrt(var[ch] + self.epsilon)
        out_vals = normed * self.weight.value[ch] + self.bias.value[ch]
        out = Tensor.__new__(type(x))
        Tensor.__init__(out, 0.0)
        out._value = jsparse.BCOO((out_vals, m.indices), shape=m.shape)
        return out

    def _forward_dense_channels(self, x, m):
        """Conv layout (values (nnz, C), channels dense): per-channel
        statistics over the stored points, tape-recorded so gradients
        flow through stacked sparse conv nets (sparse/conv.py)."""
        from jax.experimental import sparse as jsparse

        from paddle_tpu.ops.registry import OpDef, apply_op

        vt = getattr(x, "_values_tensor", None)
        if vt is None:
            vt = Tensor(m.data, stop_gradient=x.stop_gradient)
        eps = self.epsilon
        if int(m.data.shape[0]) == 0:
            # empty batch: no stats to take (unguarded mean/var would
            # poison the running buffers with NaN); identity transform
            out = Tensor.__new__(type(x))
            Tensor.__init__(out, 0.0)
            out._value = m
            out._values_tensor = vt
            out.stop_gradient = vt.stop_gradient
            return out
        if self.training:
            mean = jnp.mean(m.data, axis=0)
            var = jnp.var(m.data, axis=0)
            self._mean._set_value(self.momentum * self._mean.value
                                  + (1 - self.momentum) * mean)
            self._variance._set_value(self.momentum * self._variance.value
                                      + (1 - self.momentum) * var)

            def impl(v, w, b):
                mu = jnp.mean(v, axis=0)
                s2 = jnp.var(v, axis=0)
                return (v - mu) / jnp.sqrt(s2 + eps) * w + b
        else:
            mean, var = self._mean.value, self._variance.value

            def impl(v, w, b):
                return (v - mean) / jnp.sqrt(var + eps) * w + b

        out_t = apply_op(OpDef("sparse_batch_norm", impl),
                         (vt, self.weight, self.bias), {})
        out = Tensor.__new__(type(x))
        Tensor.__init__(out, 0.0)
        out._value = jsparse.BCOO((out_t._value, m.indices), shape=m.shape)
        out._values_tensor = out_t
        out.stop_gradient = out_t.stop_gradient
        return out


class _SparseConvNd(Layer):
    """Shared base of the sparse conv layers (round-5 VERDICT item 5).
    Parity: python/paddle/sparse/nn/layer/conv.py::_Conv3D/_Conv2D —
    weight layout (*kernel, in_channels/groups, out_channels), channels
    last. Compute lives in sparse/conv.py (host rulebook + MXU matmuls)."""

    def __init__(self, in_channels, out_channels, kernel_size, nd, stride,
                 padding, dilation, groups, subm, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        from paddle_tpu.nn import initializer as init
        if groups != 1:
            raise NotImplementedError("sparse conv: groups=1 only")
        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.groups, self._subm, self._nd = groups, subm, nd
        fan_in = in_channels * int(np.prod(ks))
        bound = float(np.sqrt(1.0 / max(1, fan_in)))
        self.weight = self.create_parameter(
            ks + (in_channels, out_channels), attr=weight_attr,
            default_initializer=init.Uniform(-bound, bound))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((out_channels,),
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        fns = {(3, False): functional.conv3d,
               (3, True): functional.subm_conv3d,
               (2, False): functional.conv2d,
               (2, True): functional.subm_conv2d}
        return fns[(self._nd, self._subm)](
            x, self.weight, self.bias, stride=self.stride,
            padding=self.padding, dilation=self.dilation)


class Conv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, subm=False,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, subm=True,
                         weight_attr=weight_attr, bias_attr=bias_attr)


class MaxPool3D(Layer):
    """Sparse max pooling (python/paddle/sparse/nn/layer/pooling.py)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        if return_mask:
            raise NotImplementedError(
                "sparse MaxPool3D: return_mask is not implemented")
        if ceil_mode:
            raise NotImplementedError(
                "sparse MaxPool3D: ceil_mode is not implemented "
                "(floor output sizes only)")
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding

    def forward(self, x):
        return functional.max_pool3d(x, self.kernel_size, self.stride,
                                     self.padding)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding

    def forward(self, x):
        return functional.avg_pool3d(x, self.kernel_size, self.stride,
                                     self.padding)


class SparseLinear(Layer):
    """Fixed-sparsity-pattern linear layer: a trainable value vector over a
    static COO pattern (the sparse TRAINING story — grads land on values
    through sparse.matmul_values)."""

    def __init__(self, in_features, out_features, density=0.1, seed=0):
        super().__init__()
        rng = np.random.default_rng(seed)
        nnz = max(1, int(in_features * out_features * density))
        flat = rng.choice(in_features * out_features, size=nnz, replace=False)
        idx = np.stack([flat // out_features, flat % out_features])
        self.indices = Tensor(jnp.asarray(idx))
        self.shape = (in_features, out_features)
        scale = float(np.sqrt(1.0 / max(1, in_features * density)))
        self.values = self.create_parameter(
            [nnz], default_initializer=lambda shape, dtype: jnp.asarray(
                rng.normal(0, scale, shape[0]).astype(np.float32)))

    def forward(self, x):
        # (B, in) @ sparse(in, out): transpose trick keeps the sparse
        # operand on the left of the sparse kernel
        out_t = sparse.matmul_values(
            self.values, Tensor(self.indices.value[::-1]),
            (self.shape[1], self.shape[0]), x.transpose([1, 0]))
        return out_t.transpose([1, 0])
