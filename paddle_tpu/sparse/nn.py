"""paddle_tpu.sparse.nn — layers over sparse tensors
(python/paddle/sparse/nn/ analog).

Activation layers apply sparsity-preserving value-wise ops; BatchNorm
normalizes the stored values per channel (last dim), matching the
reference's sparse BatchNorm semantics (statistics over non-zero entries,
paddle/phi/kernels/sparse/batch_norm_kernel.cc); Linear is a trainable
fixed-pattern sparse weight trained via sparse.matmul_values.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import paddle_tpu.sparse as sparse
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SparseLinear"]


class ReLU(Layer):
    def forward(self, x):
        return sparse.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return sparse.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return sparse.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return sparse.softmax(x, axis=self.axis)


class BatchNorm(Layer):
    """Normalize stored values per channel (the trailing dense dim of an
    (N, ..., C)-shaped sparse tensor)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter([num_features])
        self.bias = self.create_parameter([num_features], is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        from jax.experimental import sparse as jsparse

        m = x._value
        ch = m.indices[:, -1]
        vals = m.data
        if self.training:
            mean = jnp.zeros((self.num_features,)).at[ch].add(vals)
            cnt = jnp.zeros((self.num_features,)).at[ch].add(1.0)
            mean = mean / jnp.maximum(cnt, 1.0)
            var = jnp.zeros((self.num_features,)).at[ch].add(
                (vals - mean[ch]) ** 2) / jnp.maximum(cnt, 1.0)
            self._mean._set_value(self.momentum * self._mean.value
                                  + (1 - self.momentum) * mean)
            self._variance._set_value(self.momentum * self._variance.value
                                      + (1 - self.momentum) * var)
        else:
            mean, var = self._mean.value, self._variance.value
        normed = (vals - mean[ch]) / jnp.sqrt(var[ch] + self.epsilon)
        out_vals = normed * self.weight.value[ch] + self.bias.value[ch]
        out = Tensor.__new__(type(x))
        Tensor.__init__(out, 0.0)
        out._value = jsparse.BCOO((out_vals, m.indices), shape=m.shape)
        return out


class SparseLinear(Layer):
    """Fixed-sparsity-pattern linear layer: a trainable value vector over a
    static COO pattern (the sparse TRAINING story — grads land on values
    through sparse.matmul_values)."""

    def __init__(self, in_features, out_features, density=0.1, seed=0):
        super().__init__()
        rng = np.random.default_rng(seed)
        nnz = max(1, int(in_features * out_features * density))
        flat = rng.choice(in_features * out_features, size=nnz, replace=False)
        idx = np.stack([flat // out_features, flat % out_features])
        self.indices = Tensor(jnp.asarray(idx))
        self.shape = (in_features, out_features)
        scale = float(np.sqrt(1.0 / max(1, in_features * density)))
        self.values = self.create_parameter(
            [nnz], default_initializer=lambda shape, dtype: jnp.asarray(
                rng.normal(0, scale, shape[0]).astype(np.float32)))

    def forward(self, x):
        # (B, in) @ sparse(in, out): transpose trick keeps the sparse
        # operand on the left of the sparse kernel
        out_t = sparse.matmul_values(
            self.values, Tensor(self.indices.value[::-1]),
            (self.shape[1], self.shape[0]), x.transpose([1, 0]))
        return out_t.transpose([1, 0])
