"""paddle_tpu.runtime — process-level runtime services.

First resident: the resilience layer (fault injection, typed
transient-error retry, decode degradation ladder support) — the
robustness spine under bench, decode serving, distributed checkpointing
and the elastic manager. Reference capability: the elastic/fault-
tolerant subsystem (PAPER §5.3: elastic manager, watchdog, fault-
tolerant fleet).
"""

from paddle_tpu.runtime.resilience import (  # noqa: F401
    CorruptBundleError,
    CorruptCheckpointError,
    DecodeFailedError,
    DegradationEvent,
    FaultEvent,
    FaultInjector,
    InjectedFault,
    RetryEvent,
    classify_error,
    drain_events,
    fault_injector,
    recent_events,
    resilient_call,
)

__all__ = [
    "CorruptBundleError", "CorruptCheckpointError", "DecodeFailedError",
    "DegradationEvent", "FaultEvent", "FaultInjector", "InjectedFault",
    "RetryEvent", "classify_error", "drain_events", "fault_injector",
    "recent_events", "resilient_call",
]
