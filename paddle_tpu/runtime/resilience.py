"""Resilience layer: fault injection, typed retry, degradation support.

Reference capability: the elastic/fault-tolerant training subsystem
(PAPER §5.3 — elastic manager, watchdog, fault-tolerant fleet) and the
serving stack's tolerance of TPU preemptions. A runtime meant for
sustained traffic cannot treat transient ``UNAVAILABLE`` backend errors,
preempted chips, or torn checkpoint writes as test-only events, so the
whole repo shares ONE vocabulary for them here:

- **FaultInjector** — a deterministic, flag-controlled injector usable
  from tests, ``bench.py`` and ``tools/fault_matrix.py``. A *plan* (a
  JSON list, programmatic or via the ``PADDLE_TPU_FAULT_PLAN`` env var)
  names fault sites and schedules: a transient dispatch error on call N,
  an OOM above batch B, a torn/corrupt byte on a checkpoint or bundle
  write, a dead/delayed heartbeat. Injection points are explicit hooks
  (``on_call`` / ``on_write`` via :func:`atomic_write_bytes` /
  ``heartbeat_action``) placed in the decode, checkpoint, bundle and
  elastic paths; with no plan configured every hook is a cheap no-op.

- **resilient_call** — the one retry loop: classifies jax/XLA
  exceptions into transient (``UNAVAILABLE``, ``DEADLINE_EXCEEDED``,
  ``ABORTED``, connection drops; ``RESOURCE_EXHAUSTED`` only during
  *setup*, where a neighbor's compile spike can steal HBM) vs fatal,
  retries transients with exponential backoff under an optional
  deadline, and emits structured :class:`RetryEvent` records. Replaces
  the ad-hoc copy ``bench.py`` grew in round 5.

- **Typed failures** — :class:`CorruptCheckpointError`,
  :class:`CorruptBundleError`, :class:`DecodeFailedError`: the
  documented terminal errors the fault matrix accepts. Anything else
  escaping a fault drill is a bug.

Degradation ladder (wired in ``inference/generate.py`` /
``inference/bundle.py``): fused speculative decode → fused plain decode
→ per-token fallback, stepping down automatically on dispatch failure
and recording each step as a :class:`DegradationEvent`.
"""

from __future__ import annotations

import collections
import dataclasses
import fnmatch
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "RetryEvent", "DegradationEvent", "FaultEvent", "ReplicaEvent",
    "InjectedFault", "CorruptCheckpointError", "CorruptBundleError",
    "DecodeFailedError", "DeadlineExceededError", "ReplicaDeadError",
    "SlabTransferError", "WeightVersionError", "StaleEpochError",
    "classify_error", "resilient_call",
    "FaultInjector", "fault_injector", "atomic_write_bytes",
    "record_event", "drain_events", "recent_events",
    "GenerateResult",
]


# ---------------------------------------------------------------------------
# Typed events (the structured records retries/degradations/injections emit)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryEvent:
    """One transient failure absorbed by ``resilient_call``."""
    site: str
    attempt: int            # 1-based attempt that failed
    max_attempts: int
    error_class: str
    error: str              # truncated message
    delay_s: float          # backoff slept before the next attempt
    kind: str = "retry"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class DegradationEvent:
    """One automatic step down the decode ladder."""
    site: str
    from_level: str
    to_level: str
    error_class: str
    error: str
    kind: str = "degradation"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class ReplicaEvent:
    """One replica health transition in the serving router (chunk
    failure strike, circuit-breaker open, heartbeat suspect/recover,
    fence/unfence, requeue) — the typed record replicated serving emits
    into the same spine as retries/degradations, so a fault drill can
    assert WHICH replica failed and what the router did about it."""
    site: str               # e.g. "serving.router"
    replica: str            # replica name ("replica1")
    action: str             # strike|breaker_open|suspect|recovered|
    #                         unfenced|requeue|shed
    detail: str
    kind: str = "replica"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One injected fault firing (the injector's own audit record)."""
    site: str
    fault: str              # plan rule kind
    detail: str
    kind: str = "fault"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


_EVENTS: "collections.deque" = collections.deque(maxlen=512)
_EVENTS_LOCK = threading.Lock()


_EVENT_COUNTERS = {"retry": "resilience.retries",
                   "degradation": "resilience.degradations",
                   "fault": "resilience.faults_injected",
                   "replica": "resilience.replica_events"}


def record_event(ev) -> None:
    """Append a typed event to the bounded process-wide resilience log.

    With obs enabled (paddle_tpu/obs) the event also mirrors into the
    global metrics registry (``resilience.retries`` /
    ``resilience.degradations`` / ``resilience.faults_injected``) and
    lands as an instant event on the trace timeline — ONE wiring point
    covering every emitter (decode ladder, serving chunk degradation,
    bundle retries, elastic heartbeats). Telemetry must never break the
    resilience spine: any obs failure is swallowed here."""
    with _EVENTS_LOCK:
        _EVENTS.append(ev)
    try:
        import paddle_tpu.obs as obs
        if obs.enabled():
            kind = getattr(ev, "kind", "event")
            obs.metrics.counter(
                _EVENT_COUNTERS.get(kind, f"resilience.{kind}"),
                "typed resilience events by kind").inc()
            obs.tracer.event(f"resilience.{kind}",
                             site=getattr(ev, "site", ""),
                             **{k: v for k, v in ev.as_dict().items()
                                if k in ("from_level", "to_level",
                                         "attempt", "error_class",
                                         "fault", "replica", "action")})
    except Exception:
        pass


def drain_events() -> List[Any]:
    """Pop and return all logged events (tests/tools consume them)."""
    with _EVENTS_LOCK:
        out = list(_EVENTS)
        _EVENTS.clear()
    return out


def recent_events() -> List[Any]:
    """Non-destructive view of the logged events."""
    with _EVENTS_LOCK:
        return list(_EVENTS)


# ---------------------------------------------------------------------------
# Typed failures (the documented terminal errors of the fault taxonomy)
# ---------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """Raised by FaultInjector hooks. The message STARTS with the status
    code (``UNAVAILABLE: ...``) so the same marker classification handles
    injected and real backend errors identically."""

    def __init__(self, message: str, code: str = "UNAVAILABLE"):
        super().__init__(message)
        self.code = code


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed integrity verification (torn shard, sha256
    mismatch, missing manifest) and the needed slices could not be
    recovered from intact shards. Never raised for corruption in shards
    this process does not need — that is the per-shard recovery path."""


class CorruptBundleError(RuntimeError):
    """An AOT bundle entry's bytes do not match the bundle manifest's
    sha256 (bit-flipped weight constants, truncated module) — the entry
    is refused rather than served."""


class DecodeFailedError(RuntimeError):
    """Every rung of the decode degradation ladder failed. Carries the
    resilience events of the attempt and the last underlying error."""

    def __init__(self, message: str, events: Optional[List[Any]] = None,
                 last_error: Optional[BaseException] = None):
        super().__init__(message)
        self.events = list(events or [])
        self.last_error = last_error


class DeadlineExceededError(RuntimeError):
    """A serving request was shed because its deadline cannot be (or was
    not) met: expired at ``submit()``, rejected by queue-depth
    backpressure (the estimated queue delay already blows the budget),
    expired while queued, or expired at requeue after a replica death
    (no zombie retries). The message deliberately does NOT contain the
    ``DEADLINE_EXCEEDED`` backend marker — this is an admission-control
    refusal, never a transient worth retrying."""

    def __init__(self, message: str, request_id: Optional[int] = None):
        super().__init__(message)
        self.request_id = request_id


class ReplicaDeadError(RuntimeError):
    """A serving replica's circuit breaker is open (K consecutive
    classified-fatal chunks / an exhausted ladder), or a request ran out
    of replicas to run on (every candidate dead or excluded). Carries
    the replica name(s) and the last underlying error."""

    def __init__(self, message: str, replica: Optional[str] = None,
                 last_error: Optional[BaseException] = None):
        super().__init__(message)
        self.replica = replica
        self.last_error = last_error


class SlabTransferError(RuntimeError):
    """A bulk slab/migration transfer failed integrity verification:
    a chunked RPC part's sha256 did not match its header digest after
    the one retry, or a shipped row-migration payload's end-to-end
    digest did not match. The transfer is refused rather than absorbed
    — corrupt KV rows scattered into a live carry would decode garbage
    silently."""

    def __init__(self, message: str, key: Optional[str] = None,
                 part: Optional[int] = None):
        super().__init__(message)
        self.key = key
        self.part = part


class WeightVersionError(RuntimeError):
    """A fleet operation would mix weight versions: migrating live
    decode rows between workers built from DIFFERENT ``weights.npz``
    versions (mid hot-reload) is refused typed — a KV cache computed
    under v1 continued under v2 weights is neither v1 nor v2 output.
    Carries both versions so the operator can tell which side lags."""

    def __init__(self, message: str, src_version: Optional[str] = None,
                 dst_version: Optional[str] = None):
        super().__init__(message)
        self.src_version = src_version
        self.dst_version = dst_version


class StaleEpochError(RuntimeError):
    """An RPC op carried a frontend epoch OLDER than the one this worker
    has already stamped: the caller is a zombie incarnation of the
    control plane — a frontend that was declared dead (and replaced)
    but whose process is still issuing ops. The op is refused so a
    zombie can never double-serve a request the new incarnation already
    owns. Carries the op name and both epochs (note: only the message
    survives an RPC pickle round-trip; the TYPE is the contract)."""

    def __init__(self, message: str, op: Optional[str] = None,
                 stale_epoch: Optional[int] = None,
                 current_epoch: Optional[int] = None):
        super().__init__(message)
        self.op = op
        self.stale_epoch = stale_epoch
        self.current_epoch = current_epoch


# ---------------------------------------------------------------------------
# Transient / fatal classification
# ---------------------------------------------------------------------------

# markers that indicate transient backend trouble in ANY phase — a retry
# with backoff is worth it (the round-5 evidence loss: one UNAVAILABLE
# compile error cost a whole BENCH artifact)
TRANSIENT_MARKERS: Tuple[str, ...] = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "socket closed",
    "Socket closed",
    "Connection reset",
    "connection reset",
    "Failed to connect",
    "failed to connect",
    "context deadline exceeded",
)

# transient ONLY while setting up (compile/warmup/first dispatch): a
# neighbor's compile spike or a not-yet-freed prior program can steal
# HBM; in steady state the same error means the workload truly does not
# fit and retrying is futile
SETUP_TRANSIENT_MARKERS: Tuple[str, ...] = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
)


def classify_error(exc: BaseException, phase: str = "steady") -> str:
    """Classify a jax/XLA (or injected) exception: ``"transient"`` —
    worth an exponential-backoff retry — or ``"fatal"``. ``phase`` is
    ``"setup"`` (compiling/warming, where RESOURCE_EXHAUSTED is usually
    a passing HBM spike) or ``"steady"``."""
    msg = str(exc)
    if any(m in msg for m in TRANSIENT_MARKERS):
        return "transient"
    if phase == "setup" and any(m in msg for m in SETUP_TRANSIENT_MARKERS):
        return "transient"
    return "fatal"


def _flag(name: str, default):
    try:
        from paddle_tpu.flags import flags
        return flags.get(name)
    except Exception:
        return default


def resilient_call(fn: Callable, *args,
                   retries: Optional[int] = None,
                   backoff: Optional[float] = None,
                   deadline_s: Optional[float] = None,
                   jitter: float = 0.0,
                   phase: str = "steady",
                   site: str = "call",
                   classify: Optional[Callable] = None,
                   on_event: Optional[Callable] = None,
                   sleep: Callable[[float], None] = time.sleep,
                   **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying transient backend errors.

    Transient exceptions (see :func:`classify_error`; ``phase`` tunes the
    RESOURCE_EXHAUSTED rule) are retried up to ``retries`` times with
    exponential backoff ``backoff * 2**(i-1)`` seconds, bounded by
    ``deadline_s`` of total elapsed time when given. ``jitter > 0``
    stretches each delay by a uniform factor in ``[1, 1+jitter)`` —
    decorrelating the retry storms of many callers hitting the same
    contended resource; the default 0 keeps schedules deterministic.
    Fatal exceptions — and the last transient one once the budget is
    spent — propagate unchanged, so callers keep the real error class.
    Each absorbed failure emits a :class:`RetryEvent` to the process
    event log and to ``on_event``. Defaults come from
    ``FLAGS_resilience_retries`` / ``FLAGS_resilience_backoff_s`` /
    ``FLAGS_resilience_deadline_s`` (0 = no deadline)."""
    if retries is None:
        retries = int(_flag("resilience_retries", 3))
    if backoff is None:
        backoff = float(_flag("resilience_backoff_s", 0.5))
    if deadline_s is None:
        d = float(_flag("resilience_deadline_s", 0.0))
        deadline_s = d if d > 0 else None
    classify = classify or classify_error
    attempts = max(1, retries + 1)
    t0 = time.monotonic()
    for i in range(1, attempts + 1):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            if i >= attempts or classify(e, phase) != "transient":
                raise
            delay = backoff * (2 ** (i - 1))
            if jitter > 0:
                import random
                delay *= 1.0 + random.random() * float(jitter)
            if deadline_s is not None and \
                    (time.monotonic() - t0) + delay > deadline_s:
                raise
            ev = RetryEvent(site=site, attempt=i, max_attempts=attempts,
                            error_class=type(e).__name__,
                            error=str(e)[:300], delay_s=delay)
            record_event(ev)
            if on_event is not None:
                on_event(ev)
            sleep(delay)


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

class FaultInjector:
    """Deterministic, plan-driven fault injection.

    A plan is a list of rules (dicts). Sites/paths match with fnmatch
    patterns; call/beat schedules are exact counters, so a given plan
    fires at the same instant on every run. Rule kinds:

    - ``{"kind": "dispatch_error", "site": "decode.fused", "call": 2,
       "times": 1, "code": "UNAVAILABLE"}`` — raise an
      :class:`InjectedFault` on the Nth matching ``on_call`` (1-based;
      default the first), for ``times`` consecutive calls (default 1).
    - ``{"kind": "oom", "site": "decode.*", "above_batch": 8}`` — raise
      ``RESOURCE_EXHAUSTED`` whenever ``on_call`` sees ``batch`` above
      the bound (default: every time; bound with ``times``).
    - ``{"kind": "torn_write", "path": "*data_r0.npz", "at_byte": 100}``
      — :func:`atomic_write_bytes` writes only the first ``at_byte``
      bytes (default half) STRAIGHT to the destination — no atomic
      rename — then raises, simulating a crash mid-write.
    - ``{"kind": "bit_flip", "path": "*.aot", "at_byte": 7}`` — flip one
      bit in the written bytes (default middle byte): silent media
      corruption the sha256 manifests must catch on load.
    - ``{"kind": "dead_heartbeat", "node": "node1", "after_beats": 3}``
      — ``heartbeat_action`` reports the node dead (beats suppressed
      forever) after N successful beats (default: immediately).
    - ``{"kind": "delay_heartbeat", "node": "*", "after_beats": 2,
       "skip_beats": 4}`` — suppress a window of beats, then resume
      (the stalled-but-alive member).
    - ``{"kind": "rpc_partition", "src": "0", "dst": "1"}`` — DROP every
      RPC message sent from rank ``src`` to rank ``dst`` (fnmatch
      patterns on the rank strings). Directional: partitioning
      ``0 -> 1`` says nothing about ``1 -> 0`` — give both rules for a
      symmetric cut, one for the asymmetric half-partition. Default
      unbounded (a SUSTAINED partition); bound with ``times``.
    - ``{"kind": "rpc_delay", "src": "*", "dst": "2", "delay_s": 0.5}``
      — deliver matching messages late (background timer) instead of
      dropping them: the slow-link half of the partition taxonomy.
    - ``{"kind": "rpc_duplicate", "src": "0", "dst": "*"}`` — deliver
      matching messages TWICE (the duplicate rides a fresh sequence
      number, so the receiver genuinely executes it again): the
      at-least-once-transport drill that exactly-once submission
      dedupe must absorb.

    Configure programmatically (``configure(plan)`` / ``clear()``) or
    via the ``PADDLE_TPU_FAULT_PLAN`` env var (a JSON list, read once at
    first use). Every firing appends a :class:`FaultEvent` to
    ``self.fired`` and the process event log.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[dict] = []
        self._counts: Dict[int, int] = {}   # rule idx -> matched count
        self._beats: Dict[str, int] = {}    # node -> beats attempted
        self._env_loaded = False
        self.fired: List[FaultEvent] = []

    # -- configuration ------------------------------------------------------
    def configure(self, plan) -> "FaultInjector":
        """Install a plan (list of rule dicts, a single dict, or a JSON
        string) and reset all schedule counters."""
        if isinstance(plan, str):
            plan = json.loads(plan)
        if isinstance(plan, dict):
            plan = [plan]
        with self._lock:
            self._rules = [dict(r) for r in (plan or [])]
            self._counts = {}
            self._beats = {}
            self.fired = []
            self._env_loaded = True   # explicit plan wins over the env
        return self

    def clear(self) -> None:
        self.configure([])

    def active(self) -> bool:
        self._maybe_load_env()
        return bool(self._rules)

    def _maybe_load_env(self) -> None:
        if self._env_loaded:
            return
        self._env_loaded = True
        plan = os.environ.get("PADDLE_TPU_FAULT_PLAN", "").strip()
        if plan:
            parsed = json.loads(plan)
            self._rules = [dict(r)
                           for r in (parsed if isinstance(parsed, list)
                                     else [parsed])]

    def _fire(self, site: str, rule: dict, detail: str) -> None:
        ev = FaultEvent(site=site, fault=rule["kind"], detail=detail)
        self.fired.append(ev)
        record_event(ev)

    # -- hooks --------------------------------------------------------------
    def on_call(self, site: str, batch: Optional[int] = None) -> None:
        """Dispatch-shaped injection point. Placed where a device program
        is about to execute; raises :class:`InjectedFault` when a
        ``dispatch_error`` rule schedules a failure here. ``batch``
        (passed by ADMISSION hooks like ``decode.generate``, not by raw
        dispatch sites) additionally arms ``oom`` rules — a plan
        targeting ``decode.*`` dispatch errors therefore never trips an
        admission check, and vice versa."""
        self._maybe_load_env()
        if not self._rules:
            return
        with self._lock:
            for idx, rule in enumerate(self._rules):
                kind = rule.get("kind")
                if not fnmatch.fnmatchcase(site, rule.get("site", "*")):
                    continue
                if kind == "oom":
                    if batch is None or batch <= int(rule["above_batch"]):
                        continue
                    times = rule.get("times")   # default: structural
                    n = self._counts.get(idx, 0)
                    if times is not None and n >= int(times):
                        continue
                    self._counts[idx] = n + 1
                    code = rule.get("code", "RESOURCE_EXHAUSTED")
                    detail = (f"batch {batch} > {rule['above_batch']} "
                              f"at {site}")
                    self._fire(site, rule, detail)
                    raise InjectedFault(
                        f"{code}: injected OOM ({detail})", code=code)
                if kind != "dispatch_error" or batch is not None:
                    continue
                # dispatch_error: exact call-count schedule
                n = self._counts.get(idx, 0) + 1
                self._counts[idx] = n
                first = int(rule.get("call", 1))
                times = int(rule.get("times", 1))
                if first <= n < first + times:
                    code = rule.get("code", "UNAVAILABLE")
                    detail = f"call {n} at {site}"
                    self._fire(site, rule, detail)
                    raise InjectedFault(
                        f"{code}: injected transient dispatch error "
                        f"({detail})", code=code)

    def on_write(self, path: str, data: bytes) -> Tuple[bytes, bool]:
        """Write-shaped injection point. Returns ``(bytes_to_write,
        crash)``: ``bit_flip`` corrupts the payload silently; a
        ``torn_write`` truncates it AND sets ``crash`` — the caller must
        write the torn bytes to the real destination (no rename) and
        raise, simulating the process dying mid-write."""
        self._maybe_load_env()
        if not self._rules:
            return data, False
        name = os.path.basename(path)
        with self._lock:
            for idx, rule in enumerate(self._rules):
                kind = rule.get("kind")
                if kind not in ("torn_write", "bit_flip"):
                    continue
                pat = rule.get("path", "*")
                if not (fnmatch.fnmatchcase(name, pat)
                        or fnmatch.fnmatchcase(path, pat)):
                    continue
                n = self._counts.get(idx, 0)
                if n >= int(rule.get("times", 1)):
                    continue
                self._counts[idx] = n + 1
                if kind == "torn_write":
                    cut = int(rule.get("at_byte", max(1, len(data) // 2)))
                    cut = max(0, min(cut, len(data)))
                    self._fire(path, rule,
                               f"torn at byte {cut}/{len(data)}")
                    return data[:cut], True
                at = int(rule.get("at_byte", len(data) // 2))
                at = max(0, min(at, max(0, len(data) - 1)))
                corrupted = bytearray(data)
                if corrupted:
                    corrupted[at] ^= 0x01
                self._fire(path, rule, f"bit flipped at byte {at}")
                return bytes(corrupted), False
        return data, False

    def rpc_action(self, src: str, dst: str) -> Tuple[str, float]:
        """Message-send-shaped injection point (``distributed/rpc.py``
        routes every request/reply write through it). Returns
        ``(action, delay_s)`` where action is ``"ok"`` (deliver),
        ``"drop"`` (the partition eats the message), ``"delay"``
        (deliver after ``delay_s``) or ``"dup"`` (deliver twice). The
        first matching rule wins; rules match DIRECTIONALLY on the
        (src, dst) rank strings, so asymmetric partitions are just
        one-sided plans."""
        self._maybe_load_env()
        if not self._rules:
            return "ok", 0.0
        with self._lock:
            for idx, rule in enumerate(self._rules):
                kind = rule.get("kind")
                if kind not in ("rpc_partition", "rpc_delay",
                                "rpc_duplicate"):
                    continue
                if not fnmatch.fnmatchcase(str(src),
                                           str(rule.get("src", "*"))):
                    continue
                if not fnmatch.fnmatchcase(str(dst),
                                           str(rule.get("dst", "*"))):
                    continue
                times = rule.get("times")   # default: sustained
                n = self._counts.get(idx, 0)
                if times is not None and n >= int(times):
                    continue
                self._counts[idx] = n + 1
                site = f"rpc:{src}->{dst}"
                if kind == "rpc_partition":
                    self._fire(site, rule, f"message {n + 1} dropped")
                    return "drop", 0.0
                if kind == "rpc_delay":
                    d = float(rule.get("delay_s", 0.2))
                    self._fire(site, rule,
                               f"message {n + 1} delayed {d:.3f}s")
                    return "delay", d
                self._fire(site, rule, f"message {n + 1} duplicated")
                return "dup", 0.0
        return "ok", 0.0

    def heartbeat_action(self, node: str) -> str:
        """Heartbeat-shaped injection point: ``"ok"`` (beat normally),
        ``"dead"`` (suppress forever) or ``"skip"`` (suppress this
        beat)."""
        self._maybe_load_env()
        if not self._rules:
            return "ok"
        with self._lock:
            beats = self._beats.get(node, 0)
            self._beats[node] = beats + 1
            for rule in self._rules:
                kind = rule.get("kind")
                if kind not in ("dead_heartbeat", "delay_heartbeat"):
                    continue
                if not fnmatch.fnmatchcase(node, rule.get("node", "*")):
                    continue
                after = int(rule.get("after_beats", 0))
                if beats < after:
                    continue
                if kind == "dead_heartbeat":
                    if beats == after:
                        self._fire(node, rule,
                                   f"heartbeat dead after {after} beats")
                    return "dead"
                skip = int(rule.get("skip_beats", 1))
                if beats < after + skip:
                    if beats == after:
                        self._fire(node, rule,
                                   f"heartbeat delayed {skip} beats "
                                   f"after {after}")
                    return "skip"
        return "ok"


fault_injector = FaultInjector()


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Crash-safe file write: bytes go to ``path + '.tmp.<pid>'`` and are
    fsynced before an atomic ``os.replace`` — a reader never observes a
    half-written file. The one place torn/corrupt write faults inject:
    a ``bit_flip`` plan corrupts the payload (still atomically renamed —
    silent media corruption); a ``torn_write`` plan writes the truncated
    prefix STRAIGHT to ``path`` and raises (the mid-write crash)."""
    data, crash = fault_injector.on_write(path, bytes(data))
    if crash:
        with open(path, "wb") as f:
            f.write(data)
        raise InjectedFault(
            f"DATA_LOSS: injected crash mid-write of {path} "
            f"({len(data)} bytes written)", code="DATA_LOSS")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Decode result carrier
# ---------------------------------------------------------------------------

class GenerateResult(np.ndarray):
    """An ``np.ndarray`` of tokens that additionally carries the
    resilience record of the generate/serve call that produced it
    (``.resilience``: dict with the final ladder level, retry count and
    typed events) — drop-in for every existing caller, and the fault
    matrix asserts on the attached record."""

    resilience: Optional[dict] = None

    @classmethod
    def wrap(cls, arr: np.ndarray, resilience: Optional[dict]):
        obj = np.asarray(arr).view(cls)
        obj.resilience = resilience
        return obj

    def __array_finalize__(self, obj):
        if obj is not None:
            self.resilience = getattr(obj, "resilience", None)
