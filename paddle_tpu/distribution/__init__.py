"""paddle_tpu.distribution — probability distributions.

Analog of python/paddle/distribution/ (SURVEY P17): Distribution base with
sample/rsample/log_prob/entropy, the standard families, and a
kl_divergence registry.

Differentiability: every formula is written in framework Tensor ops, so
log_prob/entropy/kl are recorded on the autograd tape and gradients flow
to learnable parameters (VAE/policy-gradient use). ``rsample`` draws the
base noise with the functional PRNG and applies the reparameterization in
Tensor math, so pathwise gradients work. ``sample`` detaches.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework import random as rnd
from paddle_tpu.framework.tensor import Tensor, to_tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli", "Beta",
    "Dirichlet", "Exponential", "Gamma", "Laplace", "LogNormal",
    "Multinomial", "Geometric", "Cauchy", "Gumbel", "StudentT", "Poisson",
    "Binomial", "ContinuousBernoulli", "Independent", "MultivariateNormal",
    "ExponentialFamily", "kl_divergence", "register_kl",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "TransformedDistribution",
]

from paddle_tpu.distribution.transform import (  # noqa: E402,F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform, Transform,
)
from paddle_tpu.distribution.transformed_distribution import (  # noqa: E402,F401
    TransformedDistribution,
)
from paddle_tpu.distribution import constraint  # noqa: E402,F401

_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


def _t(x) -> Tensor:
    if isinstance(x, Tensor):
        return x
    return to_tensor(x, dtype="float32")


def _shape(sample_shape) -> tuple:
    if sample_shape is None:
        return ()
    if isinstance(sample_shape, int):
        return (sample_shape,)
    return tuple(int(s) for s in sample_shape)


def _noise(fn, shape):
    """Draw base noise with the functional PRNG (detached by design)."""
    return Tensor(fn(rnd.split_key(), shape))


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value) -> Tensor:
        raise NotImplementedError

    def prob(self, value) -> Tensor:
        return paddle.exp(self.log_prob(value))

    def entropy(self) -> Tensor:
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return paddle.broadcast_to(self.loc, list(self.batch_shape)) \
            if self.batch_shape else self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    @property
    def stddev(self):
        return self.scale

    def rsample(self, shape=()):
        eps = _noise(lambda k, s: jax.random.normal(k, s),
                     _shape(shape) + self.batch_shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        v = _t(value)
        d = v - self.loc
        return -(d * d) / (2.0 * self.scale * self.scale) \
            - paddle.log(self.scale) - _HALF_LOG_2PI

    def entropy(self):
        return 0.5 + _HALF_LOG_2PI + paddle.log(self.scale)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.base = Normal(loc, scale)
        super().__init__(self.base.batch_shape)

    @property
    def mean(self):
        return paddle.exp(self.base.loc + self.base.variance * 0.5)

    @property
    def variance(self):
        s2 = self.base.variance
        return (paddle.exp(s2) - 1.0) * paddle.exp(2.0 * self.base.loc + s2)

    def rsample(self, shape=()):
        return paddle.exp(self.base.rsample(shape))

    def log_prob(self, value):
        v = _t(value)
        return self.base.log_prob(paddle.log(v)) - paddle.log(v)

    def entropy(self):
        return self.base.entropy() + self.base.loc


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return (self.low + self.high) * 0.5

    @property
    def variance(self):
        d = self.high - self.low
        return d * d / 12.0

    def rsample(self, shape=()):
        u = _noise(lambda k, s: jax.random.uniform(k, s),
                   _shape(shape) + self.batch_shape)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        v = _t(value)
        lp = -paddle.log(self.high - self.low)
        inside = paddle.logical_and(v >= self.low, v <= self.high)
        return paddle.where(inside, lp + paddle.zeros_like(v),
                            paddle.full_like(v, -float("inf")))

    def entropy(self):
        return paddle.log(self.high - self.low)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _t(probs)
            self.logits = paddle.log(self.probs) - paddle.log1p(-self.probs)
        else:
            self.logits = _t(logits)
            self.probs = paddle.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.bernoulli(
            key, self.probs.value,
            _shape(shape) + self.batch_shape).astype(jnp.float32))

    rsample = sample  # discrete: no pathwise gradient

    def log_prob(self, value):
        v = _t(value)
        return v * F.log_sigmoid(self.logits) \
            + (1.0 - v) * F.log_sigmoid(-self.logits)

    def entropy(self):
        p = self.probs
        eps = 1e-12
        return -(p * paddle.log(p + eps) + (1.0 - p) * paddle.log(1.0 - p + eps))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None and probs is None:
            self.logits = _t(logits)
            self.probs = F.softmax(self.logits, axis=-1)
        elif probs is not None:
            p = _t(probs)
            self.probs = p / paddle.sum(p, axis=-1, keepdim=True)
            self.logits = paddle.log(self.probs + 1e-30)
        else:
            raise ValueError("pass logits or probs")
        super().__init__(self.probs.shape[:-1])

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.categorical(
            key, self.logits.value, shape=_shape(shape) + self.batch_shape))

    def log_prob(self, value):
        idx = value if isinstance(value, Tensor) else Tensor(jnp.asarray(value))
        n = self.probs.shape[-1]
        onehot = F.one_hot(idx.astype("int64"), n).astype("float32")
        logp = F.log_softmax(self.logits, axis=-1)
        return paddle.sum(onehot * logp, axis=-1)

    def probs_of(self, value):
        return paddle.exp(self.log_prob(value))

    def entropy(self):
        logp = F.log_softmax(self.logits, axis=-1)
        return -paddle.sum(self.probs * logp, axis=-1)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return self.probs * float(self.total_count)

    def sample(self, shape=()):
        key = rnd.split_key()
        cat = jax.random.categorical(
            key, jnp.log(self.probs.value + 1e-30),
            shape=_shape(shape) + (self.total_count,) + self.batch_shape)
        onehot = jax.nn.one_hot(cat, self.probs.shape[-1])
        axis = len(_shape(shape))
        return Tensor(jnp.sum(onehot, axis=axis))

    def log_prob(self, value):
        v = _t(value)
        return paddle.lgamma(paddle.full_like(
            paddle.sum(v, axis=-1), self.total_count + 1.0)) \
            - paddle.sum(paddle.lgamma(v + 1.0), axis=-1) \
            + paddle.sum(v * paddle.log(self.probs + 1e-30), axis=-1)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / (self.rate * self.rate)

    def rsample(self, shape=()):
        e = _noise(lambda k, s: jax.random.exponential(k, s),
                   _shape(shape) + self.batch_shape)
        return e / self.rate

    def log_prob(self, value):
        v = _t(value)
        return paddle.log(self.rate) - self.rate * v

    def entropy(self):
        return 1.0 - paddle.log(self.rate)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / (self.rate * self.rate)

    def sample(self, shape=()):
        key = rnd.split_key()
        g = jax.random.gamma(key, self.concentration.value,
                             _shape(shape) + self.batch_shape)
        return Tensor(g) / self.rate.detach()

    def log_prob(self, value):
        v = _t(value)
        a, b = self.concentration, self.rate
        return a * paddle.log(b) + (a - 1.0) * paddle.log(v) - b * v \
            - paddle.lgamma(a)

    def entropy(self):
        a, b = self.concentration, self.rate
        return a - paddle.log(b) + paddle.lgamma(a) \
            + (1.0 - a) * paddle.digamma(a)


def _betaln(a, b):
    return paddle.lgamma(a) + paddle.lgamma(b) - paddle.lgamma(a + b)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1.0))

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.beta(key, self.alpha.value, self.beta.value,
                                      _shape(shape) + self.batch_shape))

    def log_prob(self, value):
        v = _t(value)
        return (self.alpha - 1.0) * paddle.log(v) \
            + (self.beta - 1.0) * paddle.log1p(-v) \
            - _betaln(self.alpha, self.beta)

    def entropy(self):
        a, b = self.alpha, self.beta
        return _betaln(a, b) - (a - 1.0) * paddle.digamma(a) \
            - (b - 1.0) * paddle.digamma(b) \
            + (a + b - 2.0) * paddle.digamma(a + b)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return self.concentration / paddle.sum(self.concentration, axis=-1,
                                               keepdim=True)

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.dirichlet(key, self.concentration.value,
                                           _shape(shape) + self.batch_shape))

    def log_prob(self, value):
        v = _t(value)
        a = self.concentration
        return paddle.sum((a - 1.0) * paddle.log(v), axis=-1) \
            + paddle.lgamma(paddle.sum(a, axis=-1)) \
            - paddle.sum(paddle.lgamma(a), axis=-1)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale * self.scale

    def rsample(self, shape=()):
        u = _noise(lambda k, s: jax.random.uniform(k, s, minval=-0.5,
                                                   maxval=0.5),
                   _shape(shape) + self.batch_shape)
        return self.loc - self.scale * paddle.sign(u) \
            * paddle.log1p(-2.0 * paddle.abs(u))

    def log_prob(self, value):
        v = _t(value)
        return -paddle.abs(v - self.loc) / self.scale \
            - paddle.log(2.0 * self.scale)

    def entropy(self):
        return 1.0 + paddle.log(2.0 * self.scale)


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return 1.0 / self.probs

    def sample(self, shape=()):
        u = _noise(lambda k, s: jax.random.uniform(k, s, minval=1e-7,
                                                   maxval=1.0),
                   _shape(shape) + self.batch_shape)
        return paddle.ceil(paddle.log(u) / paddle.log1p(-self.probs.detach()))

    def log_prob(self, value):
        v = _t(value)
        return (v - 1.0) * paddle.log1p(-self.probs) + paddle.log(self.probs)


# -- KL registry -------------------------------------------------------------

_KL_TABLE = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_TABLE[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_TABLE.get((type(p), type(q)))
    if fn is None:
        # subclass dispatch: most-specific registered pair wins, ties
        # resolved by LEFT specificity first (the reference dispatch()'s
        # lexicographic total order on (cls_p, cls_q))
        matches = [(cp, cq) for cp, cq in _KL_TABLE
                   if isinstance(p, cp) and isinstance(q, cq)]
        if matches:
            best = min(matches, key=lambda m: (
                type(p).__mro__.index(m[0]), type(q).__mro__.index(m[1])))
            fn = _KL_TABLE[best]
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) * (p.scale / q.scale)
    d = (p.loc - q.loc) / q.scale
    return 0.5 * (var_ratio + d * d - 1.0 - paddle.log(var_ratio))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = F.log_softmax(p.logits, axis=-1)
    logq = F.log_softmax(q.logits, axis=-1)
    return paddle.sum(p.probs * (logp - logq), axis=-1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    eps = 1e-12
    a, b = p.probs, q.probs
    return a * (paddle.log(a + eps) - paddle.log(b + eps)) \
        + (1.0 - a) * (paddle.log(1.0 - a + eps) - paddle.log(1.0 - b + eps))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return paddle.log((q.high - q.low) / (p.high - p.low))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return paddle.log(1.0 / r) + r - 1.0


from paddle_tpu.distribution.extras import (  # noqa: E402,F401
    Binomial, Cauchy, ContinuousBernoulli, ExponentialFamily, Gumbel,
    Independent, MultivariateNormal, Poisson, StudentT,
)
