"""paddle_tpu.distribution — probability distributions.

Analog of python/paddle/distribution/ (SURVEY P17): Distribution base with
sample/log_prob/entropy, the standard families, and a kl_divergence
registry. Sampling uses the framework's functional PRNG (framework.random
split keys), so results are reproducible under paddle.seed and traceable
under jit.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.framework import random as rnd
from paddle_tpu.framework.tensor import Tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli", "Beta",
    "Dirichlet", "Exponential", "Gamma", "Laplace", "LogNormal",
    "Multinomial", "Geometric", "kl_divergence", "register_kl",
]


def _v(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x, jnp.float32)


def _shape(sample_shape) -> tuple:
    if sample_shape is None:
        return ()
    if isinstance(sample_shape, int):
        return (sample_shape,)
    return tuple(int(s) for s in sample_shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value) -> Tensor:
        raise NotImplementedError

    def prob(self, value) -> Tensor:
        return Tensor(jnp.exp(self.log_prob(value).value))

    def entropy(self) -> Tensor:
        raise NotImplementedError

    def kl_divergence(self, other) -> Tensor:
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        key = rnd.split_key()
        eps = jax.random.normal(key, _shape(shape) + self.batch_shape)
        return Tensor(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(e, self.batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.base = Normal(loc, scale)
        super().__init__(self.base.batch_shape)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.base.loc + self.base.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.base.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.base.loc + s2))

    def sample(self, shape=()):
        return Tensor(jnp.exp(self.base.sample(shape).value))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(self.base.log_prob(jnp.log(v)).value - jnp.log(v))

    def entropy(self):
        return Tensor(self.base.entropy().value + self.base.loc)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor((self.high - self.low) ** 2 / 12)

    def sample(self, shape=()):
        key = rnd.split_key()
        u = jax.random.uniform(key, _shape(shape) + self.batch_shape)
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = _v(value)
        inside = (v >= self.low) & (v <= self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _v(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _v(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.bernoulli(
            key, self.probs, _shape(shape) + self.batch_shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(v * jax.nn.log_sigmoid(self.logits)
                      + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        eps = 1e-12
        return Tensor(-(p * jnp.log(p + eps) + (1 - p) * jnp.log(1 - p + eps)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None and probs is None:
            self.logits = _v(logits)
            self.probs = jax.nn.softmax(self.logits, -1)
        elif probs is not None:
            self.probs = _v(probs)
            self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)
            self.logits = jnp.log(self.probs + 1e-30)
        else:
            raise ValueError("pass logits or probs")
        super().__init__(self.probs.shape[:-1])

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.categorical(
            key, self.logits, shape=_shape(shape) + self.batch_shape))

    def log_prob(self, value):
        idx = _v(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(jnp.take_along_axis(logp, idx[..., None], -1)[..., 0])

    def probs_of(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(self.probs * logp, -1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _v(probs)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    def sample(self, shape=()):
        key = rnd.split_key()
        cat = jax.random.categorical(
            key, jnp.log(self.probs + 1e-30),
            shape=_shape(shape) + (self.total_count,) + self.batch_shape)
        onehot = jax.nn.one_hot(cat, self.probs.shape[-1])
        axis = len(_shape(shape))
        return Tensor(jnp.sum(onehot, axis=axis))

    def log_prob(self, value):
        v = _v(value)
        from jax.scipy.special import gammaln
        return Tensor(gammaln(self.total_count + 1.0)
                      - jnp.sum(gammaln(v + 1.0), -1)
                      + jnp.sum(v * jnp.log(self.probs + 1e-30), -1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(self.rate ** -2)

    def sample(self, shape=()):
        key = rnd.split_key()
        e = jax.random.exponential(key, _shape(shape) + self.batch_shape)
        return Tensor(e / self.rate)

    def log_prob(self, value):
        v = _v(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _v(concentration)
        self.rate = _v(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def sample(self, shape=()):
        key = rnd.split_key()
        g = jax.random.gamma(key, self.concentration,
                             _shape(shape) + self.batch_shape)
        return Tensor(g / self.rate)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _v(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - gammaln(a))

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        a, b = self.concentration, self.rate
        return Tensor(a - jnp.log(b) + gammaln(a) + (1 - a) * digamma(a))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s ** 2 * (s + 1)))

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.beta(key, self.alpha, self.beta,
                                      _shape(shape) + self.batch_shape))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = _v(value)
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v)
                      - betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return Tensor(betaln(a, b) - (a - 1) * digamma(a)
                      - (b - 1) * digamma(b)
                      + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    def sample(self, shape=()):
        key = rnd.split_key()
        return Tensor(jax.random.dirichlet(key, self.concentration,
                                           _shape(shape) + self.batch_shape))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _v(value)
        a = self.concentration
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1)
                      + gammaln(jnp.sum(a, -1)) - jnp.sum(gammaln(a), -1))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        key = rnd.split_key()
        u = jax.random.uniform(key, _shape(shape) + self.batch_shape,
                               minval=-0.5, maxval=0.5)
        return Tensor(self.loc - self.scale * jnp.sign(u)
                      * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _v(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _v(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.probs)

    def sample(self, shape=()):
        key = rnd.split_key()
        u = jax.random.uniform(key, _shape(shape) + self.batch_shape,
                               minval=1e-7, maxval=1.0)
        return Tensor(jnp.ceil(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _v(value)
        return Tensor((v - 1) * jnp.log1p(-self.probs) + jnp.log(self.probs))


# -- KL registry -------------------------------------------------------------

_KL_TABLE = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_TABLE[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_TABLE.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(p.probs * (logp - logq), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    eps = 1e-12
    a, b = p.probs, q.probs
    return Tensor(a * (jnp.log(a + eps) - jnp.log(b + eps))
                  + (1 - a) * (jnp.log(1 - a + eps) - jnp.log(1 - b + eps)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(1 / r) + r - 1)
