"""Bijective variable transforms (python/paddle/distribution/transform.py
analog): forward / inverse / log-det-Jacobian triples, composable with
ChainTransform and liftable over event dims with IndependentTransform;
TransformedDistribution (transformed_distribution.py) pushes a base
distribution through them.

TPU-native: every op is jnp-composed (traces under jit); the
log_det_jacobian of a transform without a closed form falls back to
autodiff of the forward (jax.vmap(jax.grad)) — the reference's
`_call_forward_log_det_jacobian` has no such fallback.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


class Transform:
    """Base class (reference transform.py:59). Subclasses implement
    ``_forward``/``_inverse``/``_forward_log_det_jacobian`` over raw jnp
    arrays; the public API accepts and returns Tensors."""

    #: event dims consumed by one application (0 = elementwise)
    _domain_event_dim = 0
    _codomain_event_dim = 0

    def forward(self, x):
        return Tensor(self._forward(_v(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_v(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._forward_log_det_jacobian(_v(x)))

    def inverse_log_det_jacobian(self, y):
        """-fldj(f^{-1}(y)) unless a subclass has a closed form."""
        yv = _v(y)
        return Tensor(-self._forward_log_det_jacobian(self._inverse(yv)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # -- hooks ------------------------------------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        # autodiff fallback for elementwise transforms
        if self._domain_event_dim != 0:
            raise NotImplementedError
        g = jax.grad(lambda s: self._forward(s))
        flat = x.reshape(-1)
        d = jax.vmap(g)(flat).reshape(x.shape)
        return jnp.log(jnp.abs(d))


class AbsTransform(Transform):
    """y = |x| (non-injective; inverse returns the positive branch,
    matching the reference's set-valued convention collapsed to +)."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        super().__init__()
        self.loc = _t(loc)
        self.scale = _t(scale)

    def _forward(self, x):
        return _v(self.loc) + _v(self.scale) * x

    def _inverse(self, y):
        return (y - _v(self.loc)) / _v(self.scale)

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(_v(self.scale))), x.shape)


class ChainTransform(Transform):
    """Composition f_n(...f_1(x)); log-det-Jacobians accumulate through
    the intermediate values (reference transform.py:504)."""

    def __init__(self, transforms: Sequence[Transform]):
        super().__init__()
        self.transforms = list(transforms)
        self._domain_event_dim = max(
            [t._domain_event_dim for t in self.transforms], default=0)
        self._codomain_event_dim = self._domain_event_dim

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        # every term reduces to the chain's BATCH rank (input rank minus
        # the chain's domain event dim) — intermediate values may change
        # rank (Reshape), so reducing by per-transform deltas misaligns
        batch_rank = x.ndim - self._domain_event_dim
        total = None
        for t in self.transforms:
            ld = t._forward_log_det_jacobian(x)
            if ld.ndim > batch_rank:
                ld = ld.sum(axis=tuple(range(batch_rank, ld.ndim)))
            total = ld if total is None else total + ld
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class IndependentTransform(Transform):
    """Reinterprets the rightmost ``reinterpreted_batch_rank`` dims as
    event dims: the log-det-Jacobian sums over them."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        super().__init__()
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._domain_event_dim = base._domain_event_dim + self.rank
        self._codomain_event_dim = base._codomain_event_dim + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self.base._forward_log_det_jacobian(x)
        return ld.sum(axis=tuple(range(ld.ndim - self.rank, ld.ndim)))


class PowerTransform(Transform):
    """y = x ** power (x > 0)."""

    def __init__(self, power):
        super().__init__()
        self.power = _t(power)

    def _forward(self, x):
        return jnp.power(x, _v(self.power))

    def _inverse(self, y):
        return jnp.power(y, 1.0 / _v(self.power))

    def _forward_log_det_jacobian(self, x):
        p = _v(self.power)
        return jnp.log(jnp.abs(p * jnp.power(x, p - 1.0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        super().__init__()
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(jnp.prod(jnp.array(self.in_event_shape or (1,)))) != \
                int(jnp.prod(jnp.array(self.out_event_shape or (1,)))):
            raise ValueError("reshape must preserve the event size")
        self._domain_event_dim = len(self.in_event_shape)
        self._codomain_event_dim = len(self.out_event_shape)

    def _forward(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        # log sigmoid'(x) = -softplus(-x) - softplus(x)
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class SoftmaxTransform(Transform):
    """Normalizes the last axis (not bijective; inverse is log, matching
    the reference's convention)."""

    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("softmax is not injective")


class StackTransform(Transform):
    """Applies transforms[i] to slice i along ``axis``."""

    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        super().__init__()
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, fn_name, x):
        parts = jnp.moveaxis(x, self.axis, 0)
        outs = [getattr(t, fn_name)(parts[i])
                for i, t in enumerate(self.transforms)]
        return jnp.moveaxis(jnp.stack(outs), 0, self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)


class StickBreakingTransform(Transform):
    """R^K -> K+1 simplex via stick breaking (reference :1179)."""

    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        K = x.shape[-1]
        offset = jnp.arange(K, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones(x.shape[:-1] + (1,), x.dtype)],
                               axis=-1)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype),
             jnp.cumprod(1.0 - z, axis=-1)], axis=-1)
        return zpad * one_minus

    def _inverse(self, y):
        K1 = y.shape[-1]
        cum = jnp.cumsum(y[..., :-1], axis=-1)
        rest = 1.0 - jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,), y.dtype), cum[..., :-1]], axis=-1)
        z = y[..., :-1] / rest
        offset = jnp.arange(K1 - 1, 0, -1, dtype=y.dtype)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        K = x.shape[-1]
        offset = jnp.arange(K, 0, -1, dtype=x.dtype)
        t = x - jnp.log(offset)
        z = jax.nn.sigmoid(t)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype),
             jnp.cumprod(1.0 - z, axis=-1)[..., :-1]], axis=-1)
        # d y_k / d x_k = sigmoid'(t_k) * prod_{j<k}(1 - z_j)
        return jnp.sum(-jax.nn.softplus(-t) - jax.nn.softplus(t)
                       + jnp.log(one_minus), axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))
