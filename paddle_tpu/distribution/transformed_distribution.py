"""TransformedDistribution
(python/paddle/distribution/transformed_distribution.py analog): a base
distribution pushed through a chain of bijectors; log_prob applies the
change-of-variables formula through the inverse chain."""

from __future__ import annotations

from typing import Sequence

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor

__all__ = ["TransformedDistribution"]


class TransformedDistribution:
    def __init__(self, base, transforms: Sequence):
        from paddle_tpu.distribution.transform import ChainTransform, Transform
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)

    @property
    def batch_shape(self):
        return self.base.batch_shape

    @property
    def event_shape(self):
        shape = tuple(self.base.batch_shape) + tuple(self.base.event_shape)
        out = self._chain.forward_shape(shape)
        n = len(out) - len(self.base.batch_shape)
        return tuple(out[len(out) - n:]) if n > 0 else ()

    def sample(self, shape=()):
        # base.sample, not rsample: non-reparameterized bases (Gamma,
        # Beta, Categorical, ...) only implement sample
        x = self.base.sample(shape)
        return self._chain.forward(x).detach()

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self._chain.forward(x)

    def log_prob(self, value) -> Tensor:
        """log p_Y(y) = log p_X(f^{-1}(y)) - log|det J_f(f^{-1}(y))|."""
        x = self._chain.inverse(value)
        ld = self._chain.forward_log_det_jacobian(x)
        base_lp = self.base.log_prob(x)
        # align ranks: the chain's ldj may have consumed event dims
        bl = base_lp._value if isinstance(base_lp, Tensor) else base_lp
        lv = ld._value if isinstance(ld, Tensor) else ld
        while bl.ndim > lv.ndim:
            bl = bl.sum(axis=-1)
        return Tensor(bl - lv)

    def prob(self, value) -> Tensor:
        return paddle.exp(self.log_prob(value))
