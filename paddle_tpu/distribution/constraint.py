"""Support constraints (python/paddle/distribution/constraint.py analog):
predicates over parameter/sample supports, used by variable transforms and
distribution validation."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.framework.tensor import Tensor

__all__ = ["Constraint", "Real", "Range", "Positive", "Simplex",
           "real", "positive", "simplex"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class Constraint:
    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        v = _v(value)
        return Tensor(v == v)


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper

    def __call__(self, value):
        v = _v(value)
        return Tensor((_v(self._lower) <= v) & (v <= _v(self._upper)))


class Positive(Constraint):
    def __call__(self, value):
        return Tensor(_v(value) >= 0.0)


class Simplex(Constraint):
    def __call__(self, value):
        v = _v(value)
        return Tensor(jnp.all(v >= 0, axis=-1)
                      & (jnp.abs(v.sum(-1) - 1.0) < 1e-6))


real = Real()
positive = Positive()
simplex = Simplex()
