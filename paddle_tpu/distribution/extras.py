"""Distribution families closing the round-3 tail (VERDICT item 10).

Reference parity: python/paddle/distribution/{cauchy,gumbel,poisson,
binomial,continuous_bernoulli,multivariate_normal,independent,
exponential_family}.py (+ student_t capability). Same conventions as
paddle_tpu/distribution/__init__.py: Tensor math everywhere so log_prob/
entropy/kl ride the autograd tape, rsample reparameterizes through
functional-PRNG base noise, sample detaches.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor

from paddle_tpu.distribution import (  # circular-safe: loaded after core
    Distribution, _noise, _shape, _t, register_kl,
)

__all__ = ["Cauchy", "Gumbel", "StudentT", "Poisson", "Binomial",
           "ContinuousBernoulli", "Independent", "MultivariateNormal",
           "ExponentialFamily"]

_EULER = 0.5772156649015329
_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


class Cauchy(Distribution):
    """Reference: python/paddle/distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        eps = _noise(lambda k, s: jax.random.cauchy(k, s),
                     _shape(shape) + self.batch_shape)
        return self.loc + self.scale * eps

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return -paddle.log(self.scale) - math.log(math.pi) \
            - paddle.log1p(z * z)

    def cdf(self, value):
        z = (_t(value) - self.loc) / self.scale
        return paddle.atan(z) / math.pi + 0.5

    def entropy(self):
        return paddle.log(self.scale) + math.log(4 * math.pi) \
            + paddle.zeros(list(self.batch_shape))


@register_kl(Cauchy, Cauchy)
def _kl_cauchy(p, q):
    # Chyzak & Nielsen (2019) closed form
    sq = (p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2
    return paddle.log(sq / (4.0 * p.scale * q.scale))


class Gumbel(Distribution):
    """Reference: python/paddle/distribution/gumbel.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return self.loc + self.scale * _EULER

    @property
    def variance(self):
        return (math.pi ** 2 / 6.0) * self.scale * self.scale

    @property
    def stddev(self):
        return paddle.sqrt(self.variance)

    def rsample(self, shape=()):
        g = _noise(lambda k, s: jax.random.gumbel(k, s),
                   _shape(shape) + self.batch_shape)
        return self.loc + self.scale * g

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        return -(z + paddle.exp(-z)) - paddle.log(self.scale)

    def cdf(self, value):
        z = (_t(value) - self.loc) / self.scale
        return paddle.exp(-paddle.exp(-z))

    def entropy(self):
        return paddle.log(self.scale) + 1.0 + _EULER \
            + paddle.zeros(list(self.batch_shape))


class StudentT(Distribution):
    """Student's t (df, loc, scale). Reference capability:
    python/paddle/distribution/student_t.py (newer snapshots)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return paddle.where(self.df > 1.0,
                            paddle.broadcast_to(
                                self.loc, list(self.batch_shape))
                            if self.batch_shape else self.loc,
                            paddle.full_like(self.df, float("nan")))

    @property
    def variance(self):
        v = self.scale ** 2 * self.df / (self.df - 2.0)
        inf = paddle.full_like(self.df, float("inf"))
        nan = paddle.full_like(self.df, float("nan"))
        return paddle.where(self.df > 2.0, v,
                            paddle.where(self.df > 1.0, inf, nan))

    def rsample(self, shape=()):
        """t = normal / sqrt(chi2/df). Pathwise gradients are exact for
        loc/scale; for df they flow only through the explicit
        ``/sqrt(chi2/df)`` factor — the gamma draw itself is detached
        (no implicit-reparameterization term), so fitting df by rsample
        gradients is approximate."""
        sh = _shape(shape) + self.batch_shape
        z = _noise(lambda k, s: jax.random.normal(k, s), sh)
        g = _noise(lambda k, s: jax.random.gamma(
            k, jnp.broadcast_to(0.5 * self.df.value, s)), sh)
        chi2 = 2.0 * g
        return self.loc + self.scale * z / paddle.sqrt(chi2 / self.df)

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def log_prob(self, value):
        z = (_t(value) - self.loc) / self.scale
        half = 0.5 * (self.df + 1.0)
        return paddle.lgamma(half) - paddle.lgamma(0.5 * self.df) \
            - 0.5 * paddle.log(self.df * math.pi) - paddle.log(self.scale) \
            - half * paddle.log1p(z * z / self.df)

    def entropy(self):
        half = 0.5 * (self.df + 1.0)
        return half * (paddle.digamma(half) - paddle.digamma(0.5 * self.df)) \
            + 0.5 * paddle.log(self.df) + _betaln_(0.5 * self.df,
                                                   _t(0.5)) \
            + paddle.log(self.scale) + paddle.zeros(list(self.batch_shape))


def _betaln_(a, b):
    return paddle.lgamma(a) + paddle.lgamma(b) - paddle.lgamma(a + b)


class Poisson(Distribution):
    """Reference: python/paddle/distribution/poisson.py."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        sh = _shape(shape) + self.batch_shape
        out = _noise(lambda k, s: jax.random.poisson(
            k, jnp.broadcast_to(self.rate.value, s), s), sh)
        return out.astype("float32")

    def log_prob(self, value):
        v = _t(value)
        return v * paddle.log(self.rate) - self.rate - paddle.lgamma(v + 1.0)

    def entropy(self):
        # series approximation matching the reference implementation's
        # moment expansion for large rate; exact summation is used below
        # a small-rate threshold
        r = self.rate
        large = 0.5 * paddle.log(2 * math.pi * math.e * r) \
            - 1.0 / (12.0 * r) - 1.0 / (24.0 * r * r)
        ks = jnp.arange(0.0, 30.0)
        rv = jnp.asarray(r.value)[..., None]
        logpmf = (ks * jnp.log(jnp.maximum(rv, 1e-30)) - rv
                  - jax.scipy.special.gammaln(ks + 1.0))
        pmf = jnp.exp(logpmf)
        small = Tensor((-pmf * logpmf).sum(-1))
        return paddle.where(r > 10.0, large, small)


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return p.rate * (paddle.log(p.rate) - paddle.log(q.rate)) \
        + q.rate - p.rate


class Binomial(Distribution):
    """Reference: python/paddle/distribution/binomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        sh = _shape(shape) + self.batch_shape
        out = _noise(lambda k, s: jax.random.binomial(
            k, jnp.broadcast_to(self.total_count.value, s),
            jnp.broadcast_to(self.probs.value, s), shape=s), sh)
        return out.astype("float32")

    def log_prob(self, value):
        v = _t(value)
        n, p = self.total_count, self.probs
        eps = 1e-12
        comb = paddle.lgamma(n + 1.0) - paddle.lgamma(v + 1.0) \
            - paddle.lgamma(n - v + 1.0)
        return comb + v * paddle.log(p + eps) \
            + (n - v) * paddle.log(1.0 - p + eps)

    def entropy(self, max_count: int | None = None):
        """Exact support sum. Under jit ``total_count`` is traced and cannot
        size the support, so the sum is truncated at ``max_count`` (default
        127); terms with k > n contribute exactly 0 via the mask, so the
        truncation only loses accuracy if a traced n exceeds ``max_count`` —
        pass a larger ``max_count`` in that case (passing it explicitly also
        acknowledges the truncation and silences the warning).
        """
        try:
            nmax = int(jnp.max(self.total_count.value))
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            if max_count is None:
                import warnings
                warnings.warn(
                    "Binomial.entropy under jit truncates the support sum at "
                    "127; if total_count can exceed that the result is "
                    "silently wrong — pass entropy(max_count=...) to size "
                    "the truncation (and silence this warning).",
                    stacklevel=2)
            nmax = 127 if max_count is None else max_count
        ks = jnp.arange(0.0, nmax + 1.0)
        n = self.total_count.value[..., None]
        p = jnp.clip(self.probs.value[..., None], 1e-12, 1 - 1e-12)
        logpmf = (jax.scipy.special.gammaln(n + 1.0)
                  - jax.scipy.special.gammaln(ks + 1.0)
                  - jax.scipy.special.gammaln(n - ks + 1.0)
                  + ks * jnp.log(p) + (n - ks) * jnp.log1p(-p))
        logpmf = jnp.where(ks <= n, logpmf, -jnp.inf)
        pmf = jnp.exp(logpmf)
        return Tensor(-(pmf * jnp.where(pmf > 0, logpmf, 0.0)).sum(-1))


@register_kl(Binomial, Binomial)
def _kl_binomial(p, q):
    eps = 1e-12
    return p.total_count * (
        p.probs * (paddle.log(p.probs + eps) - paddle.log(q.probs + eps))
        + (1.0 - p.probs) * (paddle.log(1.0 - p.probs + eps)
                             - paddle.log(1.0 - q.probs + eps)))


class ContinuousBernoulli(Distribution):
    """Reference: python/paddle/distribution/continuous_bernoulli.py."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _outside(self):
        lo, hi = self._lims
        return paddle.logical_or(self.probs < lo, self.probs > hi)

    def _log_norm(self):
        """log C(p); C = 2*atanh(1-2p)/(1-2p) away from 1/2, -> log 2 at
        1/2 (Taylor-stable blend, reference's cut_probs trick)."""
        x = 1.0 - 2.0 * self._cut()
        exact = paddle.log(2.0 * paddle.atanh(x) / x)
        mid = self.probs - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0) * mid * mid
        return paddle.where(self._outside(), exact, taylor)

    def _cut(self):
        """probs with the near-1/2 region replaced by a safe constant —
        the reference's cut_probs trick. jnp.where propagates NaN grads
        from the UNSELECTED branch, so the singular exact formulas must
        never see probs ~ 0.5 even when the Taylor branch is selected."""
        lo, _ = self._lims
        safe = paddle.clip(self.probs, 1e-6, 1 - 1e-6)
        return paddle.where(self._outside(), safe,
                            paddle.full_like(safe, lo))

    @property
    def mean(self):
        cut = self._cut()
        exact = cut / (2.0 * cut - 1.0) \
            + 1.0 / (2.0 * paddle.atanh(1.0 - 2.0 * cut))
        mid = self.probs - 0.5
        taylor = 0.5 + mid / 3.0
        return paddle.where(self._outside(), exact, taylor)

    def rsample(self, shape=()):
        u = _noise(lambda k, s: jax.random.uniform(k, s, minval=1e-6,
                                                   maxval=1 - 1e-6),
                   _shape(shape) + self.batch_shape)
        return self.icdf(u)

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def icdf(self, value):
        u = _t(value)
        p = self._cut()
        q = 1.0 - p
        exact = (paddle.log1p(u * (p / q - 1.0))
                 / (paddle.log(p) - paddle.log(q)))
        return paddle.where(self._outside(), exact, u)

    def log_prob(self, value):
        v = _t(value)
        p = paddle.clip(self.probs, 1e-6, 1 - 1e-6)
        return v * paddle.log(p) + (1.0 - v) * paddle.log(1.0 - p) \
            + self._log_norm()

    def entropy(self):
        # E[-log p(X)] via the closed-form mean
        p = paddle.clip(self.probs, 1e-6, 1 - 1e-6)
        m = self.mean
        return -(m * paddle.log(p) + (1.0 - m) * paddle.log(1.0 - p)) \
            - self._log_norm()


@register_kl(ContinuousBernoulli, ContinuousBernoulli)
def _kl_continuous_bernoulli(p, q):
    pp = paddle.clip(p.probs, 1e-6, 1 - 1e-6)
    qp = paddle.clip(q.probs, 1e-6, 1 - 1e-6)
    m = p.mean
    return m * (paddle.log(pp) - paddle.log(qp)) \
        + (1.0 - m) * (paddle.log(1.0 - pp) - paddle.log(1.0 - qp)) \
        + p._log_norm() - q._log_norm()


class Independent(Distribution):
    """Reinterpret batch dims of ``base`` as event dims.
    Reference: python/paddle/distribution/independent.py."""

    def __init__(self, base: Distribution, reinterpreted_batch_rank: int):
        if reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError(
                f"reinterpreted_batch_rank {reinterpreted_batch_rank} > "
                f"base batch rank {len(base.batch_shape)}")
        self.base = base
        self.reinterpreted_batch_rank = reinterpreted_batch_rank
        cut = len(base.batch_shape) - reinterpreted_batch_rank
        super().__init__(base.batch_shape[:cut],
                         base.batch_shape[cut:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _sum_rightmost(self, x):
        for _ in range(self.reinterpreted_batch_rank):
            x = paddle.sum(x, axis=-1)
        return x

    def log_prob(self, value):
        return self._sum_rightmost(self.base.log_prob(value))

    def entropy(self):
        return self._sum_rightmost(self.base.entropy())


class MultivariateNormal(Distribution):
    """Reference: python/paddle/distribution/multivariate_normal.py."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _t(loc)
        given = [a is not None for a in
                 (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("exactly one of covariance_matrix, "
                             "precision_matrix, scale_tril must be given")
        if scale_tril is not None:
            self._L = _t(scale_tril)
        elif covariance_matrix is not None:
            self._L = Tensor(jnp.linalg.cholesky(
                _t(covariance_matrix).value))
        else:
            prec = _t(precision_matrix).value
            # cov = inv(prec); cholesky via inverse of prec's factor
            self._L = Tensor(jnp.linalg.cholesky(jnp.linalg.inv(prec)))
        d = self.loc.shape[-1]
        super().__init__(jnp.broadcast_shapes(
            self.loc.shape[:-1], self._L.shape[:-2]), (d,))

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        Lv = self._L.value
        return Tensor(Lv @ jnp.swapaxes(Lv, -1, -2))

    @property
    def variance(self):
        Lv = self._L.value
        return Tensor(jnp.sum(Lv * Lv, axis=-1))

    def rsample(self, shape=()):
        sh = _shape(shape) + self.batch_shape + self.event_shape
        eps = _noise(lambda k, s: jax.random.normal(k, s), sh)
        return self.loc + paddle.matmul(
            self._L, eps.unsqueeze(-1)).squeeze(-1)

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def log_prob(self, value):
        d = _t(value) - self.loc
        # solve L y = d  ->  maha = |y|^2
        y = Tensor(jax.scipy.linalg.solve_triangular(
            jnp.broadcast_to(self._L.value,
                             d.shape[:-1] + tuple(self._L.shape[-2:])),
            d.value[..., None], lower=True))
        maha = paddle.sum(y.squeeze(-1) ** 2, axis=-1)
        half_logdet = paddle.sum(paddle.log(Tensor(jnp.abs(
            jnp.diagonal(self._L.value, axis1=-2, axis2=-1)))), axis=-1)
        k = self.event_shape[0]
        return -0.5 * maha - half_logdet - k * _HALF_LOG_2PI

    def entropy(self):
        half_logdet = paddle.sum(paddle.log(Tensor(jnp.abs(
            jnp.diagonal(self._L.value, axis1=-2, axis2=-1)))), axis=-1)
        k = self.event_shape[0]
        return half_logdet + 0.5 * k * (1.0 + math.log(2 * math.pi))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    Lp, Lq = p._L.value, q._L.value
    k = p.event_shape[0]
    # broadcast BOTH factors to the joint batch (q may carry more batch
    # dims than p)
    bshape = jnp.broadcast_shapes(Lp.shape[:-2], Lq.shape[:-2])
    Lp = jnp.broadcast_to(Lp, bshape + Lp.shape[-2:])
    Lq = jnp.broadcast_to(Lq, bshape + Lq.shape[-2:])
    # tr(Σq⁻¹ Σp) = |Lq⁻¹ Lp|_F² ; maha through Lq solve
    M = jax.scipy.linalg.solve_triangular(Lq, Lp, lower=True)
    tr = jnp.sum(M * M, axis=(-2, -1))
    d = (q.loc - p.loc).value[..., None]
    y = jax.scipy.linalg.solve_triangular(
        jnp.broadcast_to(Lq, d.shape[:-2] + Lq.shape[-2:]), d, lower=True)
    maha = jnp.sum(y[..., 0] ** 2, axis=-1)
    logdet = (jnp.sum(jnp.log(jnp.abs(jnp.diagonal(
        Lq, axis1=-2, axis2=-1))), -1)
        - jnp.sum(jnp.log(jnp.abs(jnp.diagonal(
            Lp, axis1=-2, axis2=-1))), -1))
    return Tensor(logdet + 0.5 * (tr + maha - k))


class ExponentialFamily(Distribution):
    """Base class carrying the natural-parameter / log-normalizer
    interface (reference: python/paddle/distribution/exponential_family.py,
    Bregman-divergence KL via autodiff of the log normalizer)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily(p, q):
    """Bregman divergence of the log normalizers, as ONE tape-recorded op
    whose body differentiates the log normalizer with jax AD — gradients
    w.r.t. every natural parameter (and through them the distributions'
    learnable parameters) are exact, including the ∇²A term that a
    naive 'treat ∇A as a constant' formulation drops. Reference:
    exponential_family.py + kl.py _kl_expfamily_expfamily (which
    differentiates its static graph the same way)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    from paddle_tpu.ops.registry import OpDef, apply_op
    p_nat = [n if isinstance(n, Tensor) else _t(n)
             for n in p._natural_parameters]
    q_nat = [n if isinstance(n, Tensor) else _t(n)
             for n in q._natural_parameters]
    k = len(p_nat)

    def impl(*nats):
        pn, qn = nats[:k], nats[k:]

        def lognorm(ns):
            out = p._log_normalizer(*[Tensor(n) for n in ns])
            return out.value if isinstance(out, Tensor) else jnp.asarray(out)

        grads = jax.grad(lambda ns: jnp.sum(lognorm(ns)))(tuple(pn))
        acc = lognorm(qn) - lognorm(pn)
        for g, a, b in zip(grads, pn, qn):
            acc = acc - g * (b - a)
        return acc

    opdef = OpDef("expfamily_bregman_kl", impl, n_outputs=1)
    return apply_op(opdef, tuple(p_nat + q_nat), {})
