"""Compiled-SPMD zero-bubble (ZB-H1) pipeline training step.

Redesign of the reference's ZB-H1 scheduler
(python/paddle/distributed/passes/pipeline_scheduler_pass/
pipeline_zero_bubble.py): backward is SPLIT into

- ``dx`` — the input cotangent, which the upstream rank needs on the very
  next tick (it sits on the critical path), computed at the same tick
  1F1B runs its backward, and
- ``dW`` — the parameter gradient, which nothing downstream waits for,
  DEFERRED by ``r`` ticks on rank ``r``: micro-batch ``j``'s dW runs at
  global tick ``j + 2S - 1`` on every rank, which lands the final dWs of
  late stages exactly in the drain ticks where 1F1B leaves them idle
  (the H1 picture: the last stage defers most, stage 0 none).

Schedule (ticks t = 0 .. M + 2S - 2, same grid as 1F1B):

  fwd  f = t - r              (unchanged)
  dx   b = t + r - 2S + 1     (1F1B's backward tick, input-grad only)
  dW   j = t - 2S + 1         (r ticks after j's dx on rank r)

Deferral legality: j's dx runs at tick ``j + 2S - 1 - r``; its dW runs
``r`` ticks later, still within the T = M + 2S - 1 grid (the last dW,
j = M - 1, lands on the final tick for every rank). The saved stage input
(written at tick ``j + r``) is re-read ``2S - 1 - r`` ticks later and the
cotangent ``r`` ticks later — both inside the 2S-slot rings.

Bubble math, stated honestly: in the reference's ASYNC runtime the split
removes (S-1)·t_dW of per-rank idle time from the drain bubble — the
1F1B bubble (S-1)(t_F + t_dx + t_dW) shrinks to (S-1)(t_F + t_dx), the
H1 claim. In this compiled-SPMD form every tick is closed by the
``ppermute`` rendezvous, so wall time is Σ_t max_r cost(r, t) and the
deferral moves dW work between ticks without shortening the synchronous
tick grid — the capability (split backward + H1 placement) is what this
module provides, plus the schedule hook a future async executor would
need. The split pays one extra stage-forward recompute per micro-batch
(dx and dW each re-linearize from the saved input; the reference caches
the linearization instead — with jax.vjp the cache would pin every
micro-batch's intermediates and break the 1F1B memory bound).

``zb_schedule(S, M)`` exposes the static per-rank tick table so the
schedule itself is testable (and documents the accounting above).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from paddle_tpu.framework.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel.mesh import ProcessMesh

__all__ = ["spmd_pipeline_zb", "zb_schedule"]


def zb_schedule(S: int, M: int) -> List[Dict[str, List[Tuple[int, int]]]]:
    """Static ZB-H1 tick table: per rank, the list of (tick, micro) for
    each duty. Asserts the schedule invariants the compiled loop relies
    on (dW deferral = r ticks; everything inside the T-tick grid)."""
    T = M + 2 * S - 1
    table = []
    for r in range(S):
        fwd = [(j + r, j) for j in range(M)]
        dx = [(j + 2 * S - 1 - r, j) for j in range(M)]
        dw = [(j + 2 * S - 1, j) for j in range(M)]
        assert all(0 <= t < T for t, _ in fwd + dx + dw), (S, M, r)
        # dW of micro j runs exactly r ticks after its dx on rank r
        assert all(tw - td == r for (td, _), (tw, _) in zip(dx, dw))
        table.append({"fwd": fwd, "dx": dx, "dw": dw})
    return table


def spmd_pipeline_zb(stage_fn: Callable, loss_fn: Callable,
                     stacked_params: dict, x, targets,
                     mesh: ProcessMesh, n_micro: int, axis: str = "pp",
                     loss_params: Optional[dict] = None,
                     return_x_grad: bool = False):
    """One ZB-H1 forward+backward pass. Same contract as
    ``pipeline_1f1b.spmd_pipeline_1f1b`` (losses and grads averaged over
    micro-batches; grads in the stacked (S, ...) layout)."""
    S = mesh.dim_size(axis)
    lead = next(iter(stacked_params.values())).shape[0] if stacked_params else S
    if lead != S:
        raise ValueError(f"stacked stage dim {lead} != pp axis size {S}")
    M = x.shape[0]
    if M != n_micro:
        raise ValueError(f"x leading dim {M} != n_micro {n_micro}")
    W = 2 * S
    T = M + 2 * S - 1
    has_lp = loss_params is not None
    lp = loss_params if has_lp else {}

    param_specs = {k: P(axis) for k in stacked_params}

    def local(params_loc, lp_rep, x_all, tgt_all):
        r = jax.lax.axis_index(axis)
        p_here = {k: v[0] for k, v in params_loc.items()}
        state0 = jnp.zeros_like(x_all[0])

        fs = state0
        bs = state0
        resid = jnp.zeros((W,) + state0.shape, state0.dtype)   # stage inputs
        cts = jnp.zeros((W,) + state0.shape, state0.dtype)     # dx cotangents
        gacc = {k: jnp.zeros_like(v) for k, v in p_here.items()}
        lp_acc = {k: jnp.zeros_like(v) for k, v in lp_rep.items()}
        xg = (jnp.zeros_like(x_all) if return_x_grad else None)
        loss_acc = jnp.zeros((), jnp.float32)
        inv_m = jnp.float32(1.0 / M)

        def seed_loss(y2, tgt, lp_rep):
            if has_lp:
                l, (dlp, dly) = jax.value_and_grad(
                    lambda p, yy: loss_fn(p, yy, tgt).astype(jnp.float32),
                    argnums=(0, 1))(lp_rep, y2)
                return l, dly, dlp
            l, dly = jax.value_and_grad(
                lambda yy: loss_fn(yy, tgt).astype(jnp.float32))(y2)
            return l, dly, {}

        for t in range(T):
            # ---- forward ------------------------------------------------
            f = t - r
            has_f = (f >= 0) & (f < M)
            state_in = jnp.where(r == 0, x_all[jnp.clip(f, 0, M - 1)], fs)
            y = jax.lax.cond(has_f,
                             lambda s=state_in: stage_fn(p_here, s),
                             lambda: state0)

            # ---- dx: input cotangent only (critical path) ---------------
            b = t + r - 2 * S + 1
            has_b = (b >= 0) & (b < M)
            slot_in = jnp.mod(t - (2 * S - 1 - 2 * r), W)
            saved = jax.lax.dynamic_index_in_dim(resid, slot_in,
                                                 keepdims=False)
            tgt = tgt_all[jnp.clip(b, 0, M - 1)]

            def do_dx(saved=saved, tgt=tgt, bs=bs):
                # params are closure constants: the vjp yields ONLY dx
                y2, vjp_fn = jax.vjp(lambda s: stage_fn(p_here, s), saved)
                l, dly, dlp = seed_loss(y2, tgt, lp_rep)
                last = r == S - 1
                ct = jnp.where(last, dly.astype(y2.dtype) * inv_m, bs)
                (dx,) = vjp_fn(ct)
                lc = jnp.where(last, l * inv_m, 0.0)
                dlp = {k: jnp.where(last, v * inv_m, 0.0)
                       for k, v in dlp.items()}
                return dx, ct, lc, dlp

            def skip_dx():
                return (state0, state0, jnp.zeros((), jnp.float32),
                        {k: jnp.zeros_like(v) for k, v in lp_rep.items()})

            dx, ct, lc, dlp = jax.lax.cond(has_b, do_dx, skip_dx)
            lp_acc = {k: lp_acc[k] + dlp[k] for k in lp_acc}
            loss_acc = loss_acc + lc
            # bank the cotangent for the deferred dW (slot by dx tick)
            cts = jnp.where(has_b, cts.at[jnp.mod(t, W)].set(ct), cts)
            if return_x_grad:
                xg = jnp.where(has_b & (r == 0),
                               xg.at[jnp.clip(b, 0, M - 1)].set(dx), xg)

            # ---- dW: deferred r ticks (the ZB split) --------------------
            j = t - 2 * S + 1
            has_w = (j >= 0) & (j < M)
            # j's stage input was saved at tick j + r -> slot (j + r) % W
            slot_w_in = jnp.mod(jnp.clip(j, 0, M - 1) + r, W)
            saved_w = jax.lax.dynamic_index_in_dim(resid, slot_w_in,
                                                   keepdims=False)
            # j's cotangent was banked at its dx tick t - r
            slot_w_ct = jnp.mod(t - r, W)
            ct_w = jax.lax.dynamic_index_in_dim(cts, slot_w_ct,
                                                keepdims=False)

            def do_dw(saved_w=saved_w, ct_w=ct_w):
                _, vjp_fn = jax.vjp(lambda p: stage_fn(p, saved_w), p_here)
                (dp,) = vjp_fn(ct_w)
                return dp

            def skip_dw():
                return {k: jnp.zeros_like(v) for k, v in p_here.items()}

            dp = jax.lax.cond(has_w, do_dw, skip_dw)
            gacc = {k: gacc[k] + dp[k] for k in gacc}

            # ---- rings + residual save ----------------------------------
            resid = jnp.where(has_f,
                              resid.at[jnp.mod(t, W)].set(state_in), resid)
            fs = jax.lax.ppermute(y, axis,
                                  [(i, (i + 1) % S) for i in range(S)])
            bs = jax.lax.ppermute(dx, axis,
                                  [(i, (i - 1) % S) for i in range(S)])

        loss = jax.lax.psum(loss_acc, axis)
        grads = {k: v[None] for k, v in gacc.items()}
        outs = [loss, grads]
        if has_lp:
            outs.append({k: jax.lax.psum(v, axis) for k, v in lp_acc.items()})
        if return_x_grad:
            outs.append(jax.lax.psum(xg, axis))
        return tuple(outs)

    out_specs = [P(), {k: P(axis) for k in stacked_params}]
    if has_lp:
        out_specs.append({k: P() for k in lp})
    if return_x_grad:
        out_specs.append(P())

    fn = shard_map(local, mesh=mesh.jax_mesh,
                   in_specs=(param_specs, {k: P() for k in lp}, P(), P()),
                   out_specs=tuple(out_specs), check_vma=False)
    res = fn(stacked_params, lp, x, targets)
    if len(res) == 2:
        return res[0], res[1]
    return res
