"""paddle_tpu.parallel — device mesh, placements, and the GSPMD tensor API.

The TPU-native core that replaces the reference's DistTensor + SPMD-rule +
reshard machinery (paddle/phi/core/distributed/auto_parallel/) with
jax.sharding meshes and XLA sharding propagation. Higher-level surfaces
(paddle_tpu.distributed.*) build on this.
"""

from paddle_tpu.parallel.mesh import (  # noqa: F401
    ProcessMesh, auto_mesh, decode_mesh, get_mesh, init_mesh, set_mesh,
)
from paddle_tpu.parallel.placements import (  # noqa: F401
    Partial, Placement, ReduceType, Replicate, Shard,
    guarded_spec, match_partition_rules, shard_by_rules,
)
from paddle_tpu.parallel.api import (  # noqa: F401
    dtensor_from_fn, local_shape, named_sharding, placements_to_spec,
    reshard, shard_layer, shard_tensor, spec_to_placements, unshard,
)
