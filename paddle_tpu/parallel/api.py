"""Semi-auto parallel user API: shard_tensor / reshard / shard_layer.

Redesign of the reference's dygraph semi-auto API
(python/paddle/distributed/auto_parallel/api.py: shard_tensor:130,
reshard:346, shard_layer:445, dtensor_from_fn:312) on the GSPMD model:
the *global-view* tensor is a ``jax.Array`` with a ``NamedSharding``; the
per-op SPMD rules + reshard machinery of the reference
(paddle/phi/infermeta/spmd_rules/, .../reshard/) are played by XLA's
sharding propagation — eager ops on sharded arrays follow
computation-follows-data, and ``reshard`` compiles to the minimal
collective (allgather / all-to-all / slice / psum) instead of hand-written
R↔S/P↔R functions.

``Partial`` placements are the one case XLA does not expose publicly, so
they are tracked on the Tensor and materialized with a ``shard_map`` psum
when resharded to Replicate/Shard.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.framework.tensor import Tensor, Parameter
from paddle_tpu.parallel.mesh import ProcessMesh, get_mesh
from paddle_tpu.parallel.placements import Partial, Placement, Replicate, Shard

__all__ = [
    "shard_tensor", "reshard", "dtensor_from_fn", "shard_layer",
    "placements_to_spec", "spec_to_placements", "named_sharding",
    "local_shape", "unshard",
]


def placements_to_spec(placements: Sequence[Placement], mesh: ProcessMesh,
                       ndim: Optional[int] = None) -> P:
    """placements (one per mesh dim) -> PartitionSpec (one entry per tensor dim).

    Multiple mesh axes sharding the same tensor dim become a tuple entry, in
    mesh-dim order (matches the reference's multi-axis Shard semantics).
    """
    dim_axes = {}
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            dim_axes.setdefault(pl.dim, []).append(mesh.dim_names[mesh_dim])
    if not dim_axes:
        return P()
    max_dim = max(dim_axes) if ndim is None else ndim - 1
    entries = []
    for d in range(max_dim + 1):
        axes = dim_axes.get(d)
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return P(*entries)


def spec_to_placements(spec: P, mesh: ProcessMesh) -> List[Placement]:
    placements: List[Placement] = [Replicate() for _ in range(mesh.ndim)]
    for tdim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[mesh.dim_names.index(ax)] = Shard(tdim)
    return placements


def named_sharding(mesh: ProcessMesh, placements: Sequence[Placement],
                   ndim: Optional[int] = None) -> NamedSharding:
    return NamedSharding(mesh.jax_mesh, placements_to_spec(placements, mesh, ndim))


def _normalize_placements(placements, mesh: ProcessMesh):
    if placements is None:
        return [Replicate() for _ in range(mesh.ndim)]
    pls = list(placements)
    if len(pls) < mesh.ndim:
        pls += [Replicate()] * (mesh.ndim - len(pls))
    return pls


def shard_tensor(data, mesh: Optional[ProcessMesh] = None,
                 placements: Optional[Sequence[Placement]] = None,
                 dtype=None, stop_gradient: Optional[bool] = None) -> Tensor:
    """Create a distributed (global-view) tensor from `data`.

    Reference: python/paddle/distributed/auto_parallel/api.py:130. The data
    is the *global* value; each device materializes only its shard
    (jax.device_put moves per-device slices, the single-process analog of
    every rank holding its local shard in DistTensor).
    """
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("shard_tensor: no mesh given and no default mesh set")
    placements = _normalize_placements(placements, mesh)
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError("shard_tensor cannot create Partial placements; "
                         "Partial arises from ops (e.g. row-parallel matmul)")
    was_param = isinstance(data, Parameter)
    if isinstance(data, Tensor):
        sg = data.stop_gradient if stop_gradient is None else stop_gradient
        value = data._logical_value()  # never treat a source pad as data
        name = data.name
    else:
        sg = True if stop_gradient is None else stop_gradient
        value = jnp.asarray(data, dtype=dtype)
        name = None
    sharding = named_sharding(mesh, placements, ndim=jnp.ndim(value))
    value, logical = _pad_for_uneven(value, mesh, placements)
    value = jax.device_put(value, sharding)
    if was_param:
        out = Parameter(value, name=name, trainable=not sg)
    else:
        out = Tensor(value, stop_gradient=sg, name=name)
    out._placements = list(placements)
    out._process_mesh = mesh
    out._dist_pad = logical
    return out


def _uneven_logical(shape, mesh: ProcessMesh, placements):
    """The logical shape when `placements` shard `shape` unevenly, else None."""
    counts = {}
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            counts[p.dim] = counts.get(p.dim, 1) * mesh.shape[mesh_dim]
    if any(shape[d] % n for d, n in counts.items()):
        return tuple(shape)
    return None


def _pad_for_uneven(value, mesh: ProcessMesh, placements):
    """Pad-and-mask uneven shards (reference reshard/ uneven handling):
    jax.Array storage requires tile-divisible dims, so non-divisible Shard
    dims are zero-padded up to ``ceil(size/n)*n``. Returns (padded value,
    logical shape or None). The logical view is restored by
    Tensor._logical_value / unshard."""
    shape = list(jnp.shape(value))
    counts = {}
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            counts[p.dim] = counts.get(p.dim, 1) * mesh.shape[mesh_dim]
    pads = [(0, 0)] * len(shape)
    uneven = False
    for dim, n in counts.items():  # dims sharded by several axes need
        rem = shape[dim] % n       # divisibility by the PRODUCT
        if rem:
            pads[dim] = (0, n - rem)
            uneven = True
    if not uneven:
        return value, None
    logical = tuple(shape)
    return jnp.pad(value, pads), logical


def _materialize_partial(t: Tensor, mesh: ProcessMesh):
    """psum pending-partial axes (PToR: reshard/p_to_r_reshard_function.cc)."""
    from paddle_tpu.framework.jax_compat import shard_map

    partial_axes = tuple(
        mesh.dim_names[i] for i, p in enumerate(t._placements or [])
        if isinstance(p, Partial))
    if not partial_axes:
        return t._value
    cur_spec = placements_to_spec(
        [p if isinstance(p, Shard) else Replicate() for p in t._placements],
        mesh, ndim=t.ndim)

    def local_sum(x):
        return jax.lax.psum(x, partial_axes)

    fn = shard_map(local_sum, mesh=mesh.jax_mesh, in_specs=(cur_spec,),
                   out_specs=cur_spec, check_vma=False)
    return jax.jit(fn)(t._value)


def reshard(x: Tensor, mesh: Optional[ProcessMesh] = None,
            placements: Optional[Sequence[Placement]] = None) -> Tensor:
    """Redistribute `x` to new placements (api.py:346 analog).

    S->R, R->S, S->S' all compile to one XLA collective via device_put with
    the target NamedSharding; P->* first materializes the pending sum.
    """
    mesh = mesh or x._process_mesh or get_mesh()
    if mesh is None:
        raise ValueError("reshard: no mesh available")
    placements = _normalize_placements(placements, mesh)
    value = x._value
    if x._placements and any(isinstance(p, Partial) for p in x._placements):
        value = _materialize_partial(x, x._process_mesh or mesh)
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError("reshard target may not be Partial")
    sharding = named_sharding(mesh, placements, ndim=x.ndim)
    logical = _uneven_logical(x.shape, mesh, placements)
    # run as a taped op so backward reaches x (device_put is differentiable;
    # its transpose moves the cotangent back, i.e. the reverse collective).
    # apply_op feeds the LOGICAL value, and padding happens inside the op,
    # so uneven leaves keep their gradients (the pad's transpose is a slice)
    from paddle_tpu.ops.registry import OpDef, apply_op
    src = x
    if value is not x._value:  # partial was materialized outside the tape
        if x._dist_pad is not None:
            value = value[tuple(slice(0, s) for s in x._dist_pad)]
        src = Tensor(value, stop_gradient=x.stop_gradient, name=x.name)
        src._grad_node = x._grad_node
        src._out_index = x._out_index

    def impl(v):
        pv, _ = _pad_for_uneven(v, mesh, placements)
        return jax.device_put(pv, sharding)

    out = apply_op(OpDef("reshard", impl), (src,), {})
    out._placements = list(placements)
    out._process_mesh = mesh
    out._dist_pad = logical
    return out


def unshard(x: Tensor) -> Tensor:
    """Gather to a fully replicated tensor (get the global value everywhere)."""
    mesh = x._process_mesh or get_mesh()
    if mesh is None or x._placements is None:
        return x
    return reshard(x, mesh, [Replicate()] * mesh.ndim)


def local_shape(global_shape: Sequence[int], mesh: ProcessMesh,
                placements: Sequence[Placement],
                coord: Optional[Sequence[int]] = None) -> tuple:
    """Per-device shard shape, uneven dims included.

    Uneven semantics match the reference's balanced split
    (phi/core/distributed/auto_parallel/reshard/ uneven handling): each
    rank holds ``ceil(size / n)`` rows except the tail, which holds the
    remainder (possibly 0). Without ``coord`` (mesh coordinates, one per
    mesh dim) the maximal (rank-0 / padded-tile) shape is returned — the
    shape XLA actually tiles; with ``coord`` the exact shape at those
    coordinates.
    """
    shape = list(global_shape)
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            n = mesh.shape[mesh_dim]
            tile = -(-shape[p.dim] // n)  # ceil
            if coord is None:
                shape[p.dim] = tile
            else:
                c = coord[mesh_dim]
                shape[p.dim] = max(0, min(tile, shape[p.dim] - c * tile))
    return tuple(shape)


def dtensor_from_fn(fn: Callable, mesh: Optional[ProcessMesh] = None,
                    placements: Optional[Sequence[Placement]] = None,
                    *args, **kwargs) -> Tensor:
    """Build a dist tensor by calling fn then sharding (api.py:312). On TPU
    the interesting optimization is creating big params *already sharded*;
    jit-with-out-sharding makes XLA initialize each shard on-device."""
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def shard_layer(layer, process_mesh: Optional[ProcessMesh] = None,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None):
    """Shard every parameter of `layer` in place (api.py:445 analog).

    shard_fn(name, layer, mesh) mutates a sublayer's params; the default
    replicates everything (dp-style).
    """
    mesh = process_mesh or get_mesh()
    if mesh is None:
        raise ValueError("shard_layer: no mesh")

    def default_shard_fn(name, sublayer, mesh):
        for pname, param in list(sublayer._parameters.items()):
            if param is None:
                continue
            sublayer._parameters[pname] = shard_tensor(
                param, mesh, [Replicate()] * mesh.ndim)

    fn = shard_fn or default_shard_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, mesh))
    return layer
