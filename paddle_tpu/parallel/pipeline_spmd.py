"""Compiled SPMD pipeline parallelism.

Redesign of the reference's pipeline runtime (fleet/meta_parallel/
pipeline_parallel.py 1F1B :459, pp_utils/p2p_communication.py, and the
FleetExecutor interceptor dataflow N21): instead of per-micro-batch NCCL
p2p orchestrated from Python, the whole schedule compiles into ONE SPMD
program over the mesh 'pp' axis:

- stage params live sharded over 'pp' (stage i's weights on ring rank i),
- micro-batches stream through a rotating state buffer moved by
  ``lax.ppermute`` (collective-permute rides ICI),
- the schedule loop is a static Python loop of T = M + S - 1 ticks
  (GPipe-style fill/drain; every device computes every tick, with bubble
  ticks masked), and
- backward is ``jax.grad`` through the loop — XLA reverses the permutes,
  which reproduces the 1F1B-reversed communication pattern without any
  hand-written schedule; per-tick ``jax.checkpoint`` bounds activation
  memory the way recompute_interval does in the reference.

This is the deadlock-free-by-construction answer to SURVEY §7.3 hard
part #1.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from paddle_tpu.framework.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel.mesh import ProcessMesh

__all__ = ["spmd_pipeline", "stack_stage_params"]


def stack_stage_params(stage_states: Sequence[dict]) -> dict:
    """Stack per-stage param dicts (same structure) along a leading stage
    axis: the 'pp'-shardable layout (stage i's slice lands on ring rank i)."""
    keys = list(stage_states[0].keys())
    for st in stage_states[1:]:
        if list(st.keys()) != keys:
            raise ValueError("pipeline stages must have identical param structure")
    return {k: jnp.stack([st[k] for st in stage_states]) for k in keys}


def spmd_pipeline(stage_fn: Callable, stacked_params: dict, x,
                  mesh: ProcessMesh, n_micro: int, axis: str = "pp",
                  checkpoint_ticks: bool = True, partial_manual: bool = False,
                  virtual_chunks: int = 1):
    """Run `x` through S pipeline stages as one compiled SPMD program.

    stage_fn(params_slice, microbatch) -> microbatch (same shape/dtype);
    stacked_params[k] has leading dim S (stage axis, sharded over `axis`);
    x has leading dim M = n_micro (micro-batch axis, replicated).

    With ``virtual_chunks = v > 1`` (interleaved VPP,
    pipeline_parallel.py:987 analog) stacked_params[k] has leading dims
    ``(v, S)`` — ``[j, r]`` holds global stage ``j*S + r`` — and the ring
    is traversed v times, cutting the warmup bubble per chunk from
    ``(S-1) * v``-deep to ``(S-1)``-deep stage computes.

    Returns the pipeline output with leading dim M.
    """
    if virtual_chunks > 1:
        return _spmd_pipeline_interleaved(
            stage_fn, stacked_params, x, mesh, n_micro, axis,
            checkpoint_ticks, partial_manual, virtual_chunks)
    S = mesh.dim_size(axis)
    lead = next(iter(stacked_params.values())).shape[0] if stacked_params else S
    if lead != S:
        raise ValueError(f"stacked stage dim {lead} != pp axis size {S}")
    M = x.shape[0]
    if M != n_micro:
        raise ValueError(f"x leading dim {M} != n_micro {n_micro}")

    param_specs = {k: P(axis) for k in stacked_params}
    # per-stage micro-batch IO (the scalability fix): when M divides by S,
    # inputs/outputs are sharded over the pp axis (each rank holds M/S
    # micro-batches) and single micro-batches ride ppermutes to/from the
    # ring ends — per-rank IO memory is M/S x activation, not M x. With
    # M % S != 0 the replicated fallback keeps correctness.
    shard_io = S > 1 and M % S == 0
    per = M // S if shard_io else M
    x_spec = P(axis) if shard_io else P()
    out_spec = P(axis) if shard_io else P()

    def local(params_loc, x_all):
        # params_loc[k]: (1, ...) this rank's stage slice;
        # x_all: (per, ...) local micro-batches (sharded) or (M, ...) (repl)
        r = jax.lax.axis_index(axis)
        p_here = {k: v[0] for k, v in params_loc.items()}
        state = jnp.zeros_like(x_all[0])
        outputs = jnp.zeros((per,) + x_all.shape[1:], x_all.dtype)

        # checkpoint ONLY the stage compute: the accumulator ops (.at.set,
        # where, ppermute) are linear and need no residuals — wrapping the
        # whole tick would keep T copies of the output buffer live
        compute = jax.checkpoint(stage_fn) if checkpoint_ticks else stage_fn

        def tick(t, state, outputs):
            # stage 0 ingests micro-batch t (while t < M); others take the
            # state handed over the ring last tick
            if t < M:
                if shard_io:
                    # owner rank t//per ships micro-batch t to the ring head
                    send = x_all[t % per]
                    inject = jax.lax.ppermute(send, axis, [(t // per, 0)])
                else:
                    inject = x_all[t]
                state = jnp.where(r == 0, inject, state)
            y = compute(p_here, state)
            # last stage emits micro-batch t-(S-1) once the pipe is full
            mb = t - (S - 1)
            if 0 <= mb < M:
                if shard_io:
                    dst = mb // per
                    moved = jax.lax.ppermute(y, axis, [(S - 1, dst)])
                    outputs = outputs.at[mb % per].set(
                        jnp.where(r == dst, moved, outputs[mb % per]))
                else:
                    emit = jnp.where(r == S - 1, y, jnp.zeros_like(y))
                    outputs = outputs.at[mb].set(emit)
            state = jax.lax.ppermute(
                y, axis, [(j, (j + 1) % S) for j in range(S)])
            return state, outputs

        for t in range(M + S - 1):
            state, outputs = tick(t, state, outputs)
        if not shard_io:
            # outputs live on the last ring rank only; share them ringwide
            outputs = jax.lax.psum(outputs, axis)
        return outputs

    kwargs = dict(mesh=mesh.jax_mesh,
                  in_specs=({k: param_specs[k] for k in stacked_params},
                            x_spec),
                  out_specs=out_spec, check_vma=False)
    if partial_manual:
        # manual only over the pp ring; dp/mp/sep stay GSPMD-automatic so
        # hybrid tp/dp sharding inside a stage keeps working
        kwargs["axis_names"] = {axis}
    fn = shard_map(local, **kwargs)
    return fn(stacked_params, x)


def _spmd_pipeline_interleaved(stage_fn, stacked_params, x, mesh, n_micro,
                               axis, checkpoint_ticks, partial_manual, v):
    """Interleaved virtual-pipeline forward (Megatron VPP; reference
    pipeline_parallel.py:987 ``interleave``): global stage ``l = j*S + r``
    runs on rank ``l % S`` with local chunk ``j = l // S``, so each rank
    touches every v-th layer block and micro-batches re-enter the ring v
    times. One compiled SPMD loop of ``M + v*S - 1`` ticks; each tick a
    rank runs (up to) v chunk computes, each cond-skipped when idle."""
    S = mesh.dim_size(axis)
    shapes = {k: p.shape for k, p in stacked_params.items()}
    for k, shp in shapes.items():
        if shp[0] != v or shp[1] != S:
            raise ValueError(
                f"virtual_chunks={v}: stacked param {k} must have leading "
                f"dims (v, S) = ({v}, {S}), got {shp[:2]}")
    M = x.shape[0]
    if M != n_micro:
        raise ValueError(f"x leading dim {M} != n_micro {n_micro}")
    L = v * S
    T = M + L - 1

    param_specs = {k: P(None, axis) for k in stacked_params}
    # same per-stage micro-batch IO as the base pipeline: shard M over the
    # pp axis when divisible (owner rank ships mb t to the ring head at its
    # injection tick; the last global stage ships results back to owners)
    shard_io = S > 1 and M % S == 0
    per = M // S if shard_io else M
    io_spec = P(axis) if shard_io else P()

    def local(params_loc, x_all):
        r = jax.lax.axis_index(axis)
        # params_loc[k]: (v, 1, ...) — this rank's v chunk slices
        p_chunks = [{k: p[j, 0] for k, p in params_loc.items()}
                    for j in range(v)]
        zero = jnp.zeros_like(x_all[0])
        outputs = jnp.zeros((per,) + x_all.shape[1:], x_all.dtype)
        fs = [zero] * v  # per-chunk ring payload

        compute = jax.checkpoint(stage_fn) if checkpoint_ticks else stage_fn

        for t in range(T):
            # global stage 0 (j=0, r=0) consumes micro-batch t this tick
            if shard_io:
                if t < M:
                    send = x_all[t % per]
                    inject_t = jax.lax.ppermute(send, axis, [(t // per, 0)])
                else:
                    inject_t = zero
            ys = []
            for j in range(v):
                # micro-batch at global stage j*S + r this tick
                m = t - j * S - r
                active = (m >= 0) & (m < M)
                # chunk input: ring payload; rank 0 takes the wrapped
                # payload of chunk j-1 (stage (j-1)*S + S-1 -> j*S); the
                # j==0 wrap value is dead — global stage 0 injects x below
                state_in = jnp.where(r == 0, fs[j - 1], fs[j])
                inject = inject_t if shard_io else x_all[jnp.clip(m, 0, M - 1)]
                state_in = jnp.where((r == 0) & (j == 0), inject, state_in)
                if partial_manual:
                    # masked, not cond: GSPMD inserts mp/dp collectives
                    # inside branches and pp-divergent predicates deadlock
                    # the mesh (see pipeline_1f1b.skip_idle)
                    y = jnp.where(active, compute(p_chunks[j], state_in), zero)
                else:
                    y = jax.lax.cond(
                        active,
                        lambda s=state_in, pj=p_chunks[j]: compute(pj, s),
                        lambda: zero)
                ys.append(y)
                # last global stage emits micro-batch m
                if j == v - 1:
                    mb = t - (L - 1)
                    if 0 <= mb < M:
                        if shard_io:
                            dst = mb // per
                            moved = jax.lax.ppermute(y, axis, [(S - 1, dst)])
                            outputs = outputs.at[mb % per].set(
                                jnp.where(r == dst, moved,
                                          outputs[mb % per]))
                        else:
                            emit = jnp.where(r == S - 1, y,
                                             jnp.zeros_like(y))
                            outputs = outputs.at[mb].set(emit)
            # one permute per chunk ring, all ranks, outside the conds
            fs = [jax.lax.ppermute(
                ys[j], axis, [(i, (i + 1) % S) for i in range(S)])
                for j in range(v)]
        if not shard_io:
            outputs = jax.lax.psum(outputs, axis)
        return outputs

    kwargs = dict(mesh=mesh.jax_mesh,
                  in_specs=({k: param_specs[k] for k in stacked_params},
                            io_spec),
                  out_specs=io_spec, check_vma=False)
    if partial_manual:
        kwargs["axis_names"] = {axis}
    fn = shard_map(local, **kwargs)
    return fn(stacked_params, x)
