"""ProcessMesh — the device mesh.

Analog of the reference's ``ProcessMesh``
(paddle/phi/core/distributed/auto_parallel/process_mesh.h and
python/paddle/distributed/auto_parallel/process_mesh.py) redesigned around
``jax.sharding.Mesh``: an N-D arrangement of devices with named axes. On
TPU the mesh layout determines which collectives ride ICI vs DCN; XLA's
GSPMD partitioner inserts the collectives, so the mesh (not a ProcessGroup
object per ring) is the unit of communication topology.

A global "current mesh" supports the auto-parallel API
(``shard_tensor`` etc. default to it), mirroring the reference's implicit
default process group.
"""

from __future__ import annotations

import contextlib
from typing import List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["ProcessMesh", "init_mesh", "get_mesh", "set_mesh", "auto_mesh",
           "decode_mesh"]

_GLOBAL_MESH: Optional["ProcessMesh"] = None


class ProcessMesh:
    """N-D named device mesh. ``dim_names`` follow the reference's hybrid
    axis conventions: dp / pp / sharding / sep / mp (fleet/base/topology.py:65),
    but any names are accepted."""

    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None,
                 shape: Optional[Sequence[int]] = None, process_ids=None):
        if isinstance(mesh, Mesh):
            self._jax_mesh = mesh
            self._shape = tuple(mesh.devices.shape)
            self._dim_names = tuple(mesh.axis_names)
            return
        devices = np.asarray(jax.devices())
        if mesh is not None:
            arr = np.asarray(mesh)
            shape = arr.shape
            process_ids = arr.reshape(-1)
        if shape is None:
            shape = (len(np.asarray(process_ids).reshape(-1))
                     if process_ids is not None else devices.size,)
        shape = tuple(int(s) for s in shape)
        if dim_names is None:
            dim_names = tuple(f"d{i}" for i in range(len(shape)))
        dim_names = tuple(dim_names)
        if process_ids is not None:
            ids = np.asarray(process_ids).reshape(-1)
            devs = devices[ids]
        else:
            n = int(np.prod(shape))
            if n > devices.size:
                raise ValueError(
                    f"mesh shape {shape} needs {n} devices, have {devices.size}")
            devs = devices[:n]
        self._jax_mesh = Mesh(devs.reshape(shape), dim_names)
        self._shape = shape
        self._dim_names = dim_names

    # -- reference-parity surface -------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return [d.id for d in self._jax_mesh.devices.reshape(-1)]

    @property
    def mesh(self):
        return np.array([d.id for d in self._jax_mesh.devices.reshape(-1)]).reshape(self._shape)

    @property
    def size(self) -> int:
        return int(np.prod(self._shape))

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def dim_size(self, name) -> int:
        if isinstance(name, str):
            return self._shape[self._dim_names.index(name)]
        return self._shape[name]

    def get_dim_size(self, name) -> int:
        return self.dim_size(name)

    def get_rank_by_dim_and_process_id(self, dim, process_id: int) -> int:
        axis = self._dim_names.index(dim) if isinstance(dim, str) else dim
        flat = [d.id for d in self._jax_mesh.devices.reshape(-1)]
        coord = np.unravel_index(flat.index(process_id), self._shape)
        return int(coord[axis])

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self._shape == other._shape
                and self._dim_names == other._dim_names
                and self.process_ids == other.process_ids)

    def __hash__(self):
        return hash((self._shape, self._dim_names, tuple(self.process_ids)))

    def __enter__(self):
        self._prev = _GLOBAL_MESH
        set_mesh(self)
        self._ctx = self._jax_mesh.__enter__()
        return self

    def __exit__(self, *exc):
        self._jax_mesh.__exit__(*exc)
        set_mesh(self._prev)
        return False

    def __repr__(self):
        return f"ProcessMesh(shape={list(self._shape)}, dim_names={list(self._dim_names)})"


def init_mesh(shape: Sequence[int], dim_names: Sequence[str]) -> ProcessMesh:
    """Create a mesh over the local devices and install it as the default."""
    m = ProcessMesh(shape=shape, dim_names=dim_names)
    set_mesh(m)
    return m


def set_mesh(mesh: Optional[ProcessMesh]) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _GLOBAL_MESH


def decode_mesh(spec) -> ProcessMesh:
    """Build the serving/decode mesh from a ``"dp:D,tp:T"`` flag string,
    an ``{"dp": D, "tp": T}`` dict (ordered — axis order is the device
    reshape order), or pass a ProcessMesh through unchanged. The ``dp``
    axis carries batch rows (data-parallel engine replicas of the slot
    table); ``tp`` carries attention heads / MLP hidden / vocab (the
    Megatron-style tensor-parallel split, Pope et al.). Axis names are
    free-form — any axes the decode partition rules don't name simply
    replicate."""
    if isinstance(spec, ProcessMesh):
        return spec
    if isinstance(spec, Mesh):
        return ProcessMesh(spec)
    if isinstance(spec, str):
        axes = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise ValueError(
                    f"mesh spec {spec!r} must be 'name:size,...' "
                    f"(e.g. 'dp:2,tp:4'); bad segment {part!r}")
            name, _, size = part.partition(":")
            axes[name.strip()] = int(size)
        spec = axes
    if not isinstance(spec, dict) or not spec:
        raise ValueError(f"cannot build a mesh from {spec!r}")
    return ProcessMesh(shape=tuple(int(v) for v in spec.values()),
                       dim_names=tuple(spec.keys()))


def auto_mesh(dp: int = 1, mp: int = 1, pp: int = 1, sep: int = 1) -> ProcessMesh:
    """Build a hybrid mesh [dp, pp, sep, mp] like HybridCommunicateGroup's
    rank topology (fleet/base/topology.py:178); axes of size 1 are kept so
    sharding specs can always name them."""
    return ProcessMesh(shape=(dp, pp, sep, mp), dim_names=("dp", "pp", "sep", "mp"))
