"""Compiled-SPMD 1F1B pipeline training step.

Redesign of the reference's 1F1B scheduler
(fleet/meta_parallel/pipeline_parallel.py:459 ``forward_backward_pipeline``)
for the XLA/SPMD world: instead of a host loop issuing per-micro-batch NCCL
p2p sends, the WHOLE 1F1B timeline — warmup forwards, steady-state
one-forward-one-backward, drain backwards — compiles into one SPMD program
over the mesh's ``pp`` axis:

- tick ``t``: rank ``r`` forwards micro-batch ``f = t - r`` (when
  ``0 <= f < M``) and backwards micro-batch ``b = t + r - 2S + 1`` (when
  ``0 <= b < M``); both sides are ``lax.cond``-skipped on idle ticks so
  warmup/drain ranks do no wasted compute,
- activations ring forward via ``lax.ppermute`` (r -> r+1) and cotangents
  ring backward (r -> r-1); the loss gradient seeds the cotangent ring at
  the last stage,
- each rank keeps a circular residual buffer of ``2S`` saved stage INPUTS
  (the 1F1B memory bound: ≤ 2S in-flight micro-batches per rank instead of
  GPipe's M + S - 1), and the backward tick recomputes the stage forward
  from the saved input (recompute-style, ``jax.vjp`` at the saved point),
- per-stage parameter gradients accumulate locally and come back stacked
  ``(S, ...)``; the loss comes back psum-reduced.

Total ticks: ``M + 2S - 1`` (vs the compiled GPipe path's ``2(M + S - 1)``
fwd+reversed ticks). No ``(M, ...)`` output buffer is materialized unless
the caller asks for the input cotangents (``return_x_grad`` — needed to
chain an embedding lookup in front of the pipe).

The interleaved virtual-pipeline (VPP) variant of the forward loop lives in
``pipeline_spmd.spmd_pipeline`` via ``virtual_chunks`` (see
pipeline_parallel.py:987 ``interleave`` and
passes/pipeline_scheduler_pass/pipeline_zero_bubble.py for the reference
schedule family).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from paddle_tpu.framework.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel.mesh import ProcessMesh

__all__ = ["spmd_pipeline_1f1b"]


def spmd_pipeline_1f1b(stage_fn: Callable, loss_fn: Callable,
                       stacked_params: dict, x, targets,
                       mesh: ProcessMesh, n_micro: int, axis: str = "pp",
                       loss_params: Optional[dict] = None,
                       return_x_grad: bool = False,
                       partial_manual: bool = False,
                       skip_idle: Optional[bool] = None):
    """One 1F1B forward+backward pass.

    stage_fn(params_slice, state) -> state (same shape/dtype);
    loss_fn(final_state, target) -> scalar — or, when ``loss_params`` is
    given, loss_fn(loss_params, final_state, target) -> scalar (the final
    norm / lm-head weights live here; their gradients are returned).
    stacked_params[k]: leading dim S (stage axis, sharded over `axis`);
    x, targets: leading dim M = n_micro.

    Returns ``(loss, grads)`` plus, in order when requested,
    ``loss_param_grads`` and ``x_grad`` (cotangent w.r.t. x, shape like x).
    The loss and all gradients are averaged over the M micro-batches;
    grads[k] has the same stacked (S, ...) layout as stacked_params[k].
    """
    S = mesh.dim_size(axis)
    lead = next(iter(stacked_params.values())).shape[0] if stacked_params else S
    if lead != S:
        raise ValueError(f"stacked stage dim {lead} != pp axis size {S}")
    M = x.shape[0]
    if M != n_micro:
        raise ValueError(f"x leading dim {M} != n_micro {n_micro}")
    W = 2 * S  # residual ring: covers the max fwd->bwd delay 2S-1 (rank 0)
    T = M + 2 * S - 1
    has_lp = loss_params is not None
    lp = loss_params if has_lp else {}
    if skip_idle is None:
        # cond-skipping idle ticks is only safe when the pp axis is the
        # ONLY partitioned axis in the body: under partial-manual hybrid
        # tp/dp, GSPMD inserts mp/dp collectives INSIDE the branches, the
        # pp ranks diverge on the predicate, and the mesh deadlocks
        # (observed: mp all-reduce vs ring collective-permute rendezvous).
        # Masked always-execute keeps collectives uniform across ranks.
        skip_idle = not partial_manual

    param_specs = {k: P(axis) for k in stacked_params}

    def local(params_loc, lp_rep, x_all, tgt_all):
        r = jax.lax.axis_index(axis)
        p_here = {k: v[0] for k, v in params_loc.items()}
        state0 = jnp.zeros_like(x_all[0])

        fs = state0                                   # forward ring payload
        bs = state0                                   # cotangent ring payload
        resid = jnp.zeros((W,) + state0.shape, state0.dtype)
        gacc = {k: jnp.zeros_like(v) for k, v in p_here.items()}
        lp_acc = {k: jnp.zeros_like(v) for k, v in lp_rep.items()}
        xg = (jnp.zeros_like(x_all) if return_x_grad else None)
        loss_acc = jnp.zeros((), jnp.float32)
        inv_m = jnp.float32(1.0 / M)

        def seed_loss(y2, tgt, lp_rep):
            """Loss value + cotangent seed + loss-param grads at rank S-1."""
            if has_lp:
                l, (dlp, dly) = jax.value_and_grad(
                    lambda p, yy: loss_fn(p, yy, tgt).astype(jnp.float32),
                    argnums=(0, 1))(lp_rep, y2)
                return l, dly, dlp
            l, dly = jax.value_and_grad(
                lambda yy: loss_fn(yy, tgt).astype(jnp.float32))(y2)
            return l, dly, {}

        for t in range(T):
            # ---- forward: micro-batch f = t - r (traced, r-dependent) ----
            f = t - r
            has_f = (f >= 0) & (f < M)
            state_in = jnp.where(r == 0, x_all[jnp.clip(f, 0, M - 1)], fs)

            if skip_idle:
                y = jax.lax.cond(
                    has_f,
                    lambda s=state_in: stage_fn(p_here, s),
                    lambda: state0)
            else:
                y = jnp.where(has_f, stage_fn(p_here, state_in), state0)

            # ---- backward: micro-batch b = t + r - 2S + 1 ----------------
            b = t + r - 2 * S + 1
            has_b = (b >= 0) & (b < M)
            # input saved at tick t_w = b + r, delay t - t_w = 2S - 1 - 2r
            slot = jnp.mod(t - (2 * S - 1 - 2 * r), W)
            saved = jax.lax.dynamic_index_in_dim(resid, slot, keepdims=False)
            tgt = tgt_all[jnp.clip(b, 0, M - 1)]

            def do_b(saved=saved, tgt=tgt, bs=bs):
                y2, vjp_fn = jax.vjp(lambda p, s: stage_fn(p, s),
                                     p_here, saved)
                l, dly, dlp = seed_loss(y2, tgt, lp_rep)
                last = r == S - 1
                ct = jnp.where(last, dly.astype(y2.dtype) * inv_m, bs)
                dp, dx = vjp_fn(ct)
                lc = jnp.where(last, l * inv_m, 0.0)
                dlp = {k: jnp.where(last, v * inv_m, 0.0) for k, v in dlp.items()}
                return dp, dx, lc, dlp

            def skip_b():
                return ({k: jnp.zeros_like(v) for k, v in p_here.items()},
                        state0, jnp.zeros((), jnp.float32),
                        {k: jnp.zeros_like(v) for k, v in lp_rep.items()})

            if skip_idle:
                dp, dx, lc, dlp = jax.lax.cond(has_b, do_b, skip_b)
            else:
                live, dead = do_b(), skip_b()
                dp, dx, lc, dlp = jax.tree_util.tree_map(
                    lambda a, z: jnp.where(has_b, a, z), live, dead)
            gacc = {k: gacc[k] + dp[k] for k in gacc}
            lp_acc = {k: lp_acc[k] + dlp[k] for k in lp_acc}
            loss_acc = loss_acc + lc
            if return_x_grad:
                # the cotangent leaving rank 0 is dL/d x[b]
                xg = jnp.where(has_b & (r == 0),
                               xg.at[jnp.clip(b, 0, M - 1)].set(dx), xg)

            # ---- rings + residual save (uniform across ranks) ------------
            resid = jnp.where(has_f,
                              resid.at[jnp.mod(t, W)].set(state_in), resid)
            fs = jax.lax.ppermute(y, axis, [(j, (j + 1) % S) for j in range(S)])
            bs = jax.lax.ppermute(dx, axis,
                                  [(j, (j - 1) % S) for j in range(S)])

        loss = jax.lax.psum(loss_acc, axis)
        grads = {k: v[None] for k, v in gacc.items()}   # (1, ...) per rank
        outs = [loss, grads]
        if has_lp:
            outs.append({k: jax.lax.psum(v, axis) for k, v in lp_acc.items()})
        if return_x_grad:
            outs.append(jax.lax.psum(xg, axis))
        return tuple(outs)

    out_specs = [P(), {k: P(axis) for k in stacked_params}]
    if has_lp:
        out_specs.append({k: P() for k in lp})
    if return_x_grad:
        out_specs.append(P())

    kwargs = dict(mesh=mesh.jax_mesh,
                  in_specs=(param_specs, {k: P() for k in lp}, P(), P()),
                  out_specs=tuple(out_specs), check_vma=False)
    if partial_manual:
        # manual only over the pp ring; dp/mp/sep stay GSPMD-automatic so
        # hybrid tp/dp sharding inside a stage keeps working
        kwargs["axis_names"] = {axis}
    fn = shard_map(local, **kwargs)
    res = fn(stacked_params, lp, x, targets)
    if len(res) == 2:
        return res[0], res[1]
    return res
