"""Sharded training step: nn.Layer + Optimizer -> one compiled SPMD program.

TPU-native replacement for the reference's whole distributed runtime around
a train step — EagerReducer bucketed allreduce (collective/reducer.h:88),
HybridParallelOptimizer grad sync (hybrid_parallel_optimizer.py:255), and
the semi-auto Engine/Parallelizer pipeline (auto_parallel/static/engine.py:62):
the model is lifted to a pure fn(params, batch), differentiated with
jax.grad, the optimizer's functional update is applied, and the whole step
is jit-compiled over a mesh with NamedShardings on every param. XLA's SPMD
partitioner inserts the reduce-scatter/allreduce that the reference issues
by hand; donated buffers give in-place param/optimizer-state updates.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from paddle_tpu.parallel.api import named_sharding, placements_to_spec
from paddle_tpu.parallel.mesh import ProcessMesh
from paddle_tpu.parallel.placements import Replicate, Shard

__all__ = ["ShardedTrainer", "sharded_data_spec"]


def _apply_grad_clip(clip, grads: dict) -> dict:
    """Functional (jit-safe) form of the nn.clip classes; global-norm clip
    matches HybridParallelClipGrad semantics (hybrid_parallel_optimizer.py:41)
    — with GSPMD the cross-group norm allreduce is implicit in the sharded sum."""
    from paddle_tpu.nn.clip import (
        ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
    )
    if clip is None:
        return grads
    if isinstance(clip, ClipGradByGlobalNorm):
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in grads.values()))
        scale = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
        return {n: (g * scale).astype(g.dtype) for n, g in grads.items()}
    if isinstance(clip, ClipGradByNorm):
        out = {}
        for n, g in grads.items():
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            s = jnp.minimum(clip.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out[n] = (g * s).astype(g.dtype)
        return out
    if isinstance(clip, ClipGradByValue):
        return {n: jnp.clip(g, clip.min, clip.max) for n, g in grads.items()}
    raise NotImplementedError(f"grad clip {type(clip).__name__} in compiled step")


def sharded_data_spec(mesh: ProcessMesh, batch_axes=("dp",)) -> P:
    """Batch dim sharded over the data-parallel mesh axes."""
    axes = tuple(a for a in batch_axes if a in mesh.dim_names)
    return P(axes if len(axes) > 1 else (axes[0] if axes else None))


class ShardedTrainer:
    """Compile-once distributed trainer.

    ``plan`` maps param name -> placements (one per mesh dim); unknown names
    replicate. ``loss_fn(model, *batch) -> scalar Tensor`` drives the forward
    pass (the model's params are transparently swapped for traced values).
    Optimizer state inherits each param's sharding (ZeRO-free default;
    sharding-stage variants remap these in distributed.sharding).
    """

    def __init__(self, model: Layer, optimizer, loss_fn: Callable,
                 mesh: ProcessMesh, plan: Optional[Dict[str, Sequence]] = None,
                 data_spec: Optional[P] = None, donate: bool = True,
                 amp_dtype: Optional[str] = None, pass_rules=None,
                 offload: str = ""):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.plan = plan or {}
        # optional jaxpr rewrite rules (passes/) applied to the whole
        # compiled train step — the auto-parallel pass pipeline hook
        self.pass_rules = list(pass_rules) if pass_rules else []
        # bf16-native AMP: params stay f32 (master weights), MXU ops run in
        # amp_dtype via the auto_cast dispatch hook (no loss scaling needed
        # for bf16 on TPU — SURVEY §7.1 AMP row)
        self.amp_dtype = amp_dtype
        self.data_spec = data_spec if data_spec is not None else sharded_data_spec(mesh)
        self._step = None
        self._multi_step = None
        self._lr_cache = None
        self._seed_dev = None
        # optimizer-state offload to host memory (group_sharded offload= /
        # pinned-memory capability, group_sharded_utils.py analog): the
        # TPU-native form is a pinned_host memory-kind sharding — XLA
        # streams the states HBM<->host around the update. TPU-only (the
        # CPU SPMD partitioner cannot compute from host memory).
        if offload not in ("", "opt"):
            raise ValueError(f"offload must be '' or 'opt', got {offload!r}")
        self._offload_opt = False
        if offload == "opt":
            if jax.default_backend() != "tpu":
                import warnings
                warnings.warn("ShardedTrainer(offload='opt') needs a TPU "
                              "backend; ignoring", stacklevel=2)
            else:
                self._offload_opt = True

        state = dict(model.state_dict())
        for name, b in model.named_buffers():
            state.setdefault(name, b)
        self.state_names = tuple(state.keys())
        self.trainable = tuple(
            n for n, p in model.named_parameters() if not p.stop_gradient)
        self._tensors = state

        # place every param/buffer per plan (replicate by default)
        self.shardings: Dict[str, NamedSharding] = {}
        for name, t in state.items():
            pls = list(self.plan.get(name, [Replicate()] * mesh.ndim))
            sh = named_sharding(mesh, pls, ndim=t.ndim)
            t._set_value(jax.device_put(t._value, sh))
            t._placements = pls
            t._process_mesh = mesh
            self.shardings[name] = sh

        # functional optimizer state, sharded like its param — or, with a
        # ZeRO stage set (distributed.sharding), additionally sharded over
        # the sharding/dp axis (stage-1/2 optimizer-state partitioning:
        # dygraph_sharding_optimizer.py:44 analog, done as placements)
        zero_stage = getattr(optimizer, "_zero_stage", 0)
        zero_axis = None
        if zero_stage >= 1:
            from paddle_tpu.distributed.sharding import shard_axis_for
            zero_axis = shard_axis_for(mesh)
        self.opt_state = {}
        self.opt_shardings = {}
        for name in self.trainable:
            p = state[name]
            st = optimizer.init_state(p.value)
            pst, psh = {}, {}
            for k, v in st.items():
                if getattr(v, "shape", ()) == tuple(p.shape):
                    sh = self.shardings[name]
                    if zero_axis is not None:
                        sh = self._zero_sharding(p, name, zero_axis) or sh
                else:
                    sh = NamedSharding(mesh.jax_mesh, P())
                if self._offload_opt:
                    sh = sh.with_memory_kind("pinned_host")
                pst[k] = jax.device_put(v, sh)
                psh[k] = sh
            self.opt_state[name] = pst
            self.opt_shardings[name] = psh

    def _zero_sharding(self, p, name: str, axis: str):
        """Optimizer-state sharding over `axis`, layered on the param's own
        plan (dygraph_sharding_optimizer.py:44 stage-1 semantics)."""
        from paddle_tpu.distributed.sharding import zero_shard_placements
        pls = self.plan.get(name, [Replicate()] * self.mesh.ndim)
        new = zero_shard_placements(p.shape, pls, self.mesh, axis)
        return named_sharding(self.mesh, new, ndim=p.ndim) if new else None

    # -- compiled step ------------------------------------------------------
    def _single_step_fn(self, n_batch: int):
        """The pure (params, buffers, opt_state, lr, seed, *batch) ->
        (params', opt_state', loss, seed') step body, shared by the
        one-step and K-step executables."""
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        state_names, trainable = self.state_names, self.trainable
        wd = getattr(opt, "_weight_decay", 0.0) or 0.0
        offload = self._offload_opt
        if offload:
            dev_shardings = {
                n: {k: sh.with_memory_kind("device")
                    for k, sh in per.items()}
                for n, per in self.opt_shardings.items()}

        def step(params, buffers, opt_state, lr, seed, *batch):
            # seed is a DEVICE-resident counter (donated, bumped in-graph):
            # no per-step host->device scalar transfer, which costs a
            # blocking RPC round-trip on tunneled/remote runtimes
            if offload:
                # stream the host-resident optimizer states into HBM for
                # the update; out_shardings put the new states back on host
                opt_state = {
                    n: {k: jax.device_put(v, dev_shardings[n][k])
                        for k, v in per.items()}
                    for n, per in opt_state.items()}
            def compute_loss(train_params):
                full = dict(buffers)
                full.update(train_params)
                from paddle_tpu.autograd import tape
                from paddle_tpu.framework import random as rnd
                with tape.no_grad():
                    # swap param values for traced ones; loss_fn drives forward
                    state = dict(model.state_dict())
                    for n, b in model.named_buffers():
                        state.setdefault(n, b)
                    originals = []
                    # per-step traced RNG key: dropout & co. draw fresh
                    # randomness every executed step instead of baking the
                    # trace-time key in as a constant (mpu/random.py
                    # RNGStatesTracker analog)
                    from paddle_tpu.flags import flags as _flags
                    rnd.push_trace_key(
                        jax.random.key(seed, impl=_flags.train_rng_impl))
                    try:
                        for n, t in state.items():
                            if n in full:
                                originals.append((t, t._value))
                                t._value = full[n]
                        if self.amp_dtype:
                            from paddle_tpu.amp import auto_cast
                            with auto_cast(dtype=self.amp_dtype):
                                loss = loss_fn(model,
                                               *[Tensor(b) for b in batch])
                        else:
                            loss = loss_fn(model, *[Tensor(b) for b in batch])
                    finally:
                        rnd.pop_trace_key()
                        for t, v in originals:
                            t._value = v
                return loss._value if isinstance(loss, Tensor) else loss

            loss, grads = jax.value_and_grad(compute_loss)(params)
            grads = _apply_grad_clip(getattr(opt, "_grad_clip", None), grads)
            new_params, new_opt = {}, {}
            for name in trainable:
                g = grads[name]
                p, st = params[name], opt_state[name]
                new_p, new_st = opt.update(g, st, p, lr, wd)
                new_params[name] = new_p
                new_opt[name] = new_st
            return new_params, new_opt, loss, seed + 1

        return step

    def _build(self, n_batch: int):
        step = self._single_step_fn(n_batch)
        trainable, state_names = self.trainable, self.state_names
        in_shardings = (
            {n: self.shardings[n] for n in trainable},
            {n: self.shardings[n] for n in state_names if n not in trainable},
            self.opt_shardings,
            NamedSharding(self.mesh.jax_mesh, P()),
            NamedSharding(self.mesh.jax_mesh, P()),
        ) + tuple(NamedSharding(self.mesh.jax_mesh, self.data_spec)
                  for _ in range(n_batch))
        out_shardings = (
            {n: self.shardings[n] for n in trainable},
            self.opt_shardings,
            NamedSharding(self.mesh.jax_mesh, P()),
            NamedSharding(self.mesh.jax_mesh, P()),
        )
        if self.pass_rules:
            from paddle_tpu.passes.rewrite import rewrite as _rewrite
            step = _rewrite(step, self.pass_rules)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 2, 4))

    def _build_multi(self, n_batch: int):
        """K steps per dispatch: a lax.scan over the single-step body with
        per-step batch slices. One executable run amortizes the host
        dispatch / runtime-RPC cost over K steps (on remote/tunneled
        runtimes each execute costs a round-trip; sustained training
        should not pay it per step)."""
        import jax.lax as lax

        single = self._single_step_fn(n_batch)

        def multi(params, buffers, opt_state, lr, seed, *batches):
            def body(carry, xs):
                p, o, s = carry
                new_p, new_o, loss, s2 = single(p, buffers, o, lr, s, *xs)
                return (new_p, new_o, s2), loss

            (p, o, s), losses = lax.scan(
                body, (params, opt_state, seed), tuple(batches))
            return p, o, losses, s

        rep = NamedSharding(self.mesh.jax_mesh, P())
        data = NamedSharding(self.mesh.jax_mesh,
                             P(None, *self.data_spec))
        in_shardings = (
            {n: self.shardings[n] for n in self.trainable},
            {n: self.shardings[n] for n in self.state_names
             if n not in self.trainable},
            self.opt_shardings, rep, rep,
        ) + (data,) * n_batch
        out_shardings = (
            {n: self.shardings[n] for n in self.trainable},
            self.opt_shardings, rep, rep,
        )
        if self.pass_rules:
            from paddle_tpu.passes.rewrite import rewrite as _rewrite
            multi = _rewrite(multi, self.pass_rules)
        return jax.jit(multi, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=(0, 2, 4))

    def train_steps(self, *stacked_batch) -> Tensor:
        """Run K steps in ONE compiled dispatch. Each input is stacked
        (K, ...): slice k feeds step k. Returns the (K,) per-step losses.
        Model params / optimizer state advance K steps in place."""
        vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                for b in stacked_batch]
        data = NamedSharding(self.mesh.jax_mesh, P(None, *self.data_spec))

        def put(v):
            # same per-host contract as _put_batch: multi-process callers
            # pass their LOCAL (K, local_batch, ...) slice
            if isinstance(v, jax.Array) and v.sharding == data:
                return v
            if jax.process_count() > 1:
                import numpy as np
                return jax.make_array_from_process_local_data(
                    data, np.asarray(v))
            return jax.device_put(v, data)

        vals = [put(v) for v in vals]
        K = vals[0].shape[0]
        if self._multi_step is None:
            self._multi_step = self._build_multi(len(vals))
        params = {n: self._tensors[n]._value for n in self.trainable}
        buffers = {n: self._tensors[n]._value for n in self.state_names
                   if n not in self.trainable}
        lr, seed = self._scalars()
        new_params, new_opt, losses, self._seed_dev = self._multi_step(
            params, buffers, self.opt_state, lr, seed, *vals)
        for n in self.trainable:
            self._tensors[n]._set_value(new_params[n])
        self.opt_state = new_opt
        self.optimizer._step_count += K
        return Tensor(losses)

    def _put_batch(self, v):
        """Host batch -> global sharded array. Multi-process: `v` is this
        process's LOCAL batch shard (per-host data feeding, the reference's
        per-rank DataLoader semantics); the global array is assembled from
        every process's local slice. Single-process: `v` is the global batch.
        Arrays already carrying the target sharding pass through untouched
        (no per-step device_put RPC)."""
        sh = NamedSharding(self.mesh.jax_mesh, self.data_spec)
        if isinstance(v, jax.Array) and v.sharding == sh:
            return v
        if jax.process_count() > 1:
            import numpy as np
            return jax.make_array_from_process_local_data(sh, np.asarray(v))
        return jax.device_put(v, sh)

    def _scalars(self):
        """Device-resident lr + RNG-seed counter. lr is re-transferred only
        when its host value changes; the seed lives on device for good
        (bumped inside the compiled step, donated back in)."""
        lr_host = float(self.optimizer.get_lr())
        if self._lr_cache is None or self._lr_cache[0] != lr_host:
            rep = NamedSharding(self.mesh.jax_mesh, P())
            self._lr_cache = (lr_host,
                              jax.device_put(jnp.float32(lr_host), rep))
        if self._seed_dev is None:
            rep = NamedSharding(self.mesh.jax_mesh, P())
            self._seed_dev = jax.device_put(
                jnp.uint32(self.optimizer._step_count), rep)
        return self._lr_cache[1], self._seed_dev

    def train_step(self, *batch) -> Tensor:
        """Run one step; updates model params + optimizer state in place."""
        vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        vals = [self._put_batch(v) for v in vals]
        if self._step is None:
            self._step = self._build(len(vals))
        params = {n: self._tensors[n]._value for n in self.trainable}
        buffers = {n: self._tensors[n]._value for n in self.state_names
                   if n not in self.trainable}
        lr, seed = self._scalars()
        new_params, new_opt, loss, self._seed_dev = self._step(
            params, buffers, self.opt_state, lr, seed, *vals)
        for n in self.trainable:
            self._tensors[n]._set_value(new_params[n])
        self.opt_state = new_opt
        self.optimizer._step_count += 1
        return Tensor(loss)

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self) -> dict:
        """Model params + optimizer state as Tensors (dist-checkpoint
        ready: each carries its mesh/placements)."""
        out = {}
        for n in self.state_names:
            out[f"model.{n}"] = self._tensors[n]
        for n in self.trainable:
            for k, v in self.opt_state[n].items():
                t = Tensor(v)
                t._process_mesh = self.mesh
                out[f"opt.{n}.{k}"] = t
        return out

    def save(self, path: str) -> None:
        from paddle_tpu.distributed import checkpoint as ckpt
        ckpt.save_state_dict(self.state_dict(), path)

    def load(self, path: str) -> None:
        from paddle_tpu.distributed import checkpoint as ckpt
        sd = self.state_dict()
        ckpt.load_state_dict(sd, path)
        for n in self.trainable:
            for k in self.opt_state[n]:
                new_v = sd[f"opt.{n}.{k}"].value
                self.opt_state[n][k] = jax.device_put(
                    new_v, self.opt_shardings[n][k])

    def compile_lowered(self, *batch_shapes_dtypes):
        """AOT-lower the step (for dryrun/compile checks without execution)."""
        import numpy as np
        vals = [jnp.zeros(s, d) for s, d in batch_shapes_dtypes]
        if self._step is None:
            self._step = self._build(len(vals))
        params = {n: self._tensors[n]._value for n in self.trainable}
        buffers = {n: self._tensors[n]._value for n in self.state_names
                   if n not in self.trainable}
        lr = jnp.asarray(0.0, dtype=jnp.float32)
        seed = jnp.asarray(0, dtype=jnp.uint32)
        return self._step.lower(params, buffers, self.opt_state, lr, seed,
                                *vals)
