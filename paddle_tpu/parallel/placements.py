"""Placement types for distributed (global-view) tensors.

TPU-native analog of the reference's placement model
(paddle/phi/core/distributed/auto_parallel/placement_types.h): a tensor's
distribution over an N-D ProcessMesh is one placement per mesh dimension —
``Shard(dim)`` (tensor dim split over that mesh axis), ``Replicate()``
(full copy per device along that axis), or ``Partial(op)`` (each device
holds an unreduced partial term; reduction pending).

On TPU the Shard/Replicate cases lower directly to a
``jax.sharding.NamedSharding`` PartitionSpec; ``Partial`` is metadata the
XLA sharding system has internally but does not expose, so we carry it on
the Tensor and materialize it with a compiled ``psum`` at reshard time —
mirroring how the reference's PToRReshardFunction issues an allreduce
(paddle/phi/core/distributed/auto_parallel/reshard/p_to_r_reshard_function.cc).
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

__all__ = ["Placement", "Shard", "Replicate", "Partial", "ReduceType",
           "match_partition_rules", "guarded_spec", "shard_by_rules"]


class ReduceType:
    kRedSum = "sum"
    kRedMax = "max"
    kRedMin = "min"
    kRedProd = "prod"
    kRedAvg = "avg"
    kRedAny = "any"
    kRedAll = "all"


class Placement:
    def is_shard(self, dim=None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False


class Shard(Placement):
    """Tensor dimension `dim` is split across this mesh axis."""

    __slots__ = ("dim",)

    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None) -> bool:
        return dim is None or dim == self.dim

    def get_dim(self) -> int:
        return self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    __slots__ = ()

    def is_replicated(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Each device along this mesh axis holds an unreduced partial value."""

    __slots__ = ("reduce_type",)

    def __init__(self, reduce_type: str = ReduceType.kRedSum):
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"


# -- regex partition rules (GSPMD param sharding) ---------------------------
#
# The EasyLM/fmengine ``match_partition_rules`` idiom (SNIPPETS.md): a
# param tree is sharded by the FIRST regex that matches each leaf's name,
# each rule carrying one PartitionSpec-style entry per tensor dim. Scalars
# and single-element leaves always replicate. The decode/serving stack
# (inference/sharding.py) builds its tensor-parallel plan on these.

def match_partition_rules(rules: Sequence[Tuple[str, Sequence]],
                          params: Dict[str, object]) -> Dict[str, tuple]:
    """``{name: spec_entries}`` for a flat ``{name: array}`` dict, by the
    first rule whose regex ``re.search``-matches the name. ``rules`` is
    ``[(regex, entries), ...]`` where ``entries`` is a tuple with one
    mesh-axis name (or None) per tensor dim — shorter/longer than the
    leaf's rank is fine, :func:`guarded_spec` trims and pads. A name no
    rule matches raises (end rule lists with ``(r".*", ())``)."""
    import numpy as np
    specs: Dict[str, tuple] = {}
    for name, v in params.items():
        if np.ndim(v) == 0 or int(np.prod(np.shape(v))) == 1:
            specs[name] = ()
            continue
        for rx, entries in rules:
            if re.search(rx, name) is not None:
                specs[name] = tuple(entries)
                break
        else:
            raise ValueError(f"no partition rule matches param {name!r}")
    return specs


def _axis_size(mesh, entry) -> int:
    axes = entry if isinstance(entry, (tuple, list)) else (entry,)
    n = 1
    for a in axes:
        n *= mesh.dim_size(a)
    return n


def guarded_spec(shape: Sequence[int], entries: Sequence, mesh):
    """Entries -> a ``PartitionSpec`` that is always legal for ``shape``
    on ``mesh``: entries are trimmed/padded to the rank, axis names the
    mesh doesn't carry are dropped, and an axis whose size does not
    divide the tensor dim is dropped (replicated) — jax NamedShardings
    refuse uneven shards, and a replicated dim is always CORRECT under
    GSPMD (the guard trades efficiency, never numerics). Returns the
    PartitionSpec (import-light: callers wrap in NamedSharding)."""
    from jax.sharding import PartitionSpec as P
    names = set(mesh.dim_names)
    out = []
    for d in range(len(shape)):
        e = entries[d] if d < len(entries) else None
        if e is None:
            out.append(None)
            continue
        axes = tuple(a for a in (e if isinstance(e, (tuple, list))
                                 else (e,)) if a in names)
        if not axes or int(shape[d]) % _axis_size(mesh, axes) != 0:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    return P(*out)


def shard_by_rules(params: Dict[str, object], mesh,
                   rules: Sequence[Tuple[str, Sequence]],
                   specs: Optional[Dict[str, tuple]] = None
                   ) -> Dict[str, object]:
    """``device_put`` every leaf of a flat param dict to its rule-matched
    ``NamedSharding`` over ``mesh`` (a ProcessMesh). The returned dict is
    fully committed to the mesh's devices — the make_shard_fns pattern of
    SNIPPETS.md, minus the pjit ceremony jax no longer needs."""
    import jax
    from jax.sharding import NamedSharding
    specs = match_partition_rules(rules, params) if specs is None else specs
    out = {}
    for name, v in params.items():
        ns = NamedSharding(mesh.jax_mesh,
                           guarded_spec(getattr(v, "shape", ()),
                                        specs[name], mesh))
        out[name] = jax.device_put(v, ns)
    return out
