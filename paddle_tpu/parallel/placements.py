"""Placement types for distributed (global-view) tensors.

TPU-native analog of the reference's placement model
(paddle/phi/core/distributed/auto_parallel/placement_types.h): a tensor's
distribution over an N-D ProcessMesh is one placement per mesh dimension —
``Shard(dim)`` (tensor dim split over that mesh axis), ``Replicate()``
(full copy per device along that axis), or ``Partial(op)`` (each device
holds an unreduced partial term; reduction pending).

On TPU the Shard/Replicate cases lower directly to a
``jax.sharding.NamedSharding`` PartitionSpec; ``Partial`` is metadata the
XLA sharding system has internally but does not expose, so we carry it on
the Tensor and materialize it with a compiled ``psum`` at reshard time —
mirroring how the reference's PToRReshardFunction issues an allreduce
(paddle/phi/core/distributed/auto_parallel/reshard/p_to_r_reshard_function.cc).
"""

from __future__ import annotations

__all__ = ["Placement", "Shard", "Replicate", "Partial", "ReduceType"]


class ReduceType:
    kRedSum = "sum"
    kRedMax = "max"
    kRedMin = "min"
    kRedProd = "prod"
    kRedAvg = "avg"
    kRedAny = "any"
    kRedAll = "all"


class Placement:
    def is_shard(self, dim=None) -> bool:
        return False

    def is_replicated(self) -> bool:
        return False

    def is_partial(self) -> bool:
        return False


class Shard(Placement):
    """Tensor dimension `dim` is split across this mesh axis."""

    __slots__ = ("dim",)

    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None) -> bool:
        return dim is None or dim == self.dim

    def get_dim(self) -> int:
        return self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    __slots__ = ()

    def is_replicated(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Each device along this mesh axis holds an unreduced partial value."""

    __slots__ = ("reduce_type",)

    def __init__(self, reduce_type: str = ReduceType.kRedSum):
        self.reduce_type = reduce_type

    def is_partial(self) -> bool:
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"
