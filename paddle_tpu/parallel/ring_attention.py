"""Ring attention + Ulysses — long-context / context parallelism.

NEW capability relative to the reference (SURVEY §5.7: "No ring attention,
no Ulysses, no blockwise CP exists in this snapshot"); the reference tops
out at Megatron-SP + SEP axis + recompute. TPU-native design:

- **Ring attention** (blockwise context parallel): sequence sharded over a
  mesh axis; each device keeps its q shard and rotates k/v shards around
  the ring with ``jax.lax.ppermute`` — the bidirectional ICI torus makes
  neighbor exchange effectively free, and compute on the current block
  overlaps the DMA of the next. Online-softmax merging keeps only a
  (S/n × S/n) score block alive per step, so max context scales linearly
  with ring size.
- **Ulysses**: all-to-all re-shard seq->heads, local full-seq attention on
  H/n heads, all-to-all back. Better for small rings + many heads; the
  all-to-all also rides ICI.

Both are differentiable through the shard_map (ppermute/all_to_all have
transposes), so they drop into the tape/grad machinery like any op.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from paddle_tpu.framework.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import OpDef, apply_op
from paddle_tpu.parallel.mesh import ProcessMesh, get_mesh

__all__ = ["ring_attention", "ulysses_attention", "ring_attention_fn",
           "ulysses_attention_fn"]

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """One (Sq_loc x Sk_loc) attention block -> (out, lse). f32 logits."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # fully-masked rows: keep exp() finite
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    # normalized block output; _merge re-weights blocks by exp(lse)
    p_norm = (p / jnp.maximum(l, 1e-30)).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p_norm, v)
    lse = jnp.where(m <= -1e29, _NEG_INF, m_safe + jnp.log(jnp.maximum(l, 1e-30)))
    return out, lse[..., 0]  # (b,q,h,d), (b,h,q)


def _merge(acc, out, lse_acc, lse):
    """Numerically-stable online-softmax merge of two partial results."""
    m = jnp.maximum(lse_acc, lse)
    m_safe = jnp.maximum(m, -1e29)
    a1 = jnp.exp(lse_acc - m_safe)
    a2 = jnp.exp(lse - m_safe)
    denom = a1 + a2
    w1 = (a1 / jnp.maximum(denom, 1e-30))
    w2 = (a2 / jnp.maximum(denom, 1e-30))
    # (b,h,q) -> (b,q,h,1) weighting
    def wexp(w):
        return jnp.swapaxes(w, 1, 2)[..., None]
    merged = acc * wexp(w1).astype(acc.dtype) + out * wexp(w2).astype(out.dtype)
    lse_new = m_safe + jnp.log(jnp.maximum(denom, 1e-30))
    lse_new = jnp.where(m <= -1e29, _NEG_INF, lse_new)
    return merged, lse_new


def _ring_local(q, k, v, *, axis, n, scale, causal):
    """Local computation inside shard_map: q stays, k/v rotate the ring.

    Inputs are the local seq shards (B, S/n, H, D); rank r owns global
    block r (contiguous chunking over the sequence).
    """
    r = jax.lax.axis_index(axis)
    b, s_loc, h, d = q.shape
    qf = q.astype(jnp.float32)

    acc = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)

    def step(i, carry):
        acc, lse, k_cur, v_cur = carry
        src_block = (r - i) % n  # which global kv block we now hold
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)
            g_rows = r * s_loc + rows
            g_cols = src_block * s_loc + cols
            mask = (g_rows >= g_cols)[None, None]
        else:
            mask = None
        out_i, lse_i = _block_attn(qf, k_cur.astype(jnp.float32),
                                   v_cur.astype(jnp.float32), scale, mask)
        acc, lse = _merge(acc, out_i, lse, lse_i)
        # rotate kv to the next rank (bidirectional ICI ring)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return acc, lse, k_nxt, v_nxt

    # python loop: n is static (mesh size); lets XLA pipeline ppermute/compute
    carry = (acc, lse, k, v)
    for i in range(n):
        carry = jax.checkpoint(functools.partial(step, i))(carry)
    acc, lse, _, _ = carry
    return acc.astype(q.dtype)


def _head_axis(mesh: ProcessMesh, head_axis):
    """Keep the head dim sharded over tp inside the shard_map (otherwise
    every mp slice would recompute all heads)."""
    if head_axis is None and "mp" in mesh.dim_names and mesh.dim_size("mp") > 1:
        head_axis = "mp"
    if head_axis is not None and (head_axis not in mesh.dim_names
                                  or mesh.dim_size(head_axis) == 1):
        head_axis = None
    return head_axis


def ring_attention_fn(q, k, v, mesh: ProcessMesh, axis: str = "sep",
                      causal: bool = True, scale: Optional[float] = None,
                      head_axis: Optional[str] = None):
    """Pure-jax ring attention over `axis`. Layout (B, S, H, D), S is the
    *global* sequence; the shard_map shards it internally. Heads stay
    sharded over `head_axis` (default: 'mp' when present) so hybrid
    TP + CP does not duplicate head compute."""
    n = mesh.dim_size(axis)
    d = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    if q.shape[1] % n:
        raise ValueError(f"ring_attention: seq {q.shape[1]} % ring {n} != 0")
    head_axis = _head_axis(mesh, head_axis)
    if head_axis is not None and q.shape[2] % mesh.dim_size(head_axis):
        head_axis = None  # heads not divisible: replicate rather than fail
    spec = P(None, axis, head_axis, None)
    fn = shard_map(
        functools.partial(_ring_local, axis=axis, n=n, scale=scale,
                          causal=causal),
        mesh=mesh.jax_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def _ulysses_local(q, k, v, *, axis, n, scale, causal):
    """all-to-all heads<->seq: local (B, S/n, H, D) -> (B, S, H/n, D)."""
    def seq_to_heads(x):
        # split heads into n groups, exchange so each rank gets full seq of
        # its head group: (b, s/n, h, d) -> (b, s, h/n, d)
        b, s_loc, h, d = x.shape
        x = x.reshape(b, s_loc, n, h // n, d)
        x = jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                               tiled=True)  # (b, s_loc*n, 1, h//n, d)
        return x.reshape(b, s_loc * n, h // n, d)

    def heads_to_seq(x):
        # inverse: (b, s, h/n, d) -> (b, s/n, h, d)
        b, s, hn, d = x.shape
        x = x.reshape(b, n, s // n, hn, d)
        x = jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=3,
                               tiled=True)  # (b, 1, s//n, hn*n, d)
        return x.reshape(b, s // n, hn * n, d)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    s = qh.shape[1]
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        mask = (rows >= cols)[None, None]
    else:
        mask = None
    out, _ = _block_attn(qh.astype(jnp.float32), kh.astype(jnp.float32),
                         vh.astype(jnp.float32), scale, mask)
    return heads_to_seq(out.astype(q.dtype))


def ulysses_attention_fn(q, k, v, mesh: ProcessMesh, axis: str = "sep",
                         causal: bool = True, scale: Optional[float] = None,
                         head_axis: Optional[str] = None):
    """DeepSpeed-Ulysses-style sequence parallelism (all-to-all head
    exchange). The *local* head count (global / tp shard) must be
    divisible by the axis size."""
    n = mesh.dim_size(axis)
    h = q.shape[2]
    d = q.shape[-1]
    head_axis = _head_axis(mesh, head_axis)
    h_loc = h // mesh.dim_size(head_axis) if head_axis else h
    if head_axis is not None and h % mesh.dim_size(head_axis):
        head_axis = None
        h_loc = h
    if h_loc % n:
        raise ValueError(f"ulysses: local heads {h_loc} % axis {n} != 0")
    if q.shape[1] % n:
        raise ValueError(f"ulysses: seq {q.shape[1]} % axis {n} != 0")
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    spec = P(None, axis, head_axis, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis=axis, n=n, scale=scale,
                          causal=causal),
        mesh=mesh.jax_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


# -- taped eager wrappers ----------------------------------------------------

def ring_attention(q, k, v, mesh: Optional[ProcessMesh] = None,
                   axis: str = "sep", causal: bool = True, scale=None):
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("ring_attention needs a mesh")
    opdef = OpDef("ring_attention",
                  lambda q, k, v: ring_attention_fn(q, k, v, mesh, axis,
                                                    causal, scale))
    return apply_op(opdef, (q if isinstance(q, Tensor) else Tensor(q),
                            k if isinstance(k, Tensor) else Tensor(k),
                            v if isinstance(v, Tensor) else Tensor(v)), {})


def ulysses_attention(q, k, v, mesh: Optional[ProcessMesh] = None,
                      axis: str = "sep", causal: bool = True, scale=None):
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("ulysses_attention needs a mesh")
    opdef = OpDef("ulysses_attention",
                  lambda q, k, v: ulysses_attention_fn(q, k, v, mesh, axis,
                                                       causal, scale))
    return apply_op(opdef, (q if isinstance(q, Tensor) else Tensor(q),
                            k if isinstance(k, Tensor) else Tensor(k),
                            v if isinstance(v, Tensor) else Tensor(v)), {})
