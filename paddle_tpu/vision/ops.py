"""paddle.vision.ops — detection operator family.

Analog of python/paddle/vision/ops.py (nms, roi_align, roi_pool,
box_coder, prior_box, yolo_box, deform_conv2d, distribute_fpn_proposals)
over the phi detection kernels (paddle/phi/kernels/*nms*, roi_align_kernel,
box_coder_kernel, prior_box_kernel, yolo_box_kernel,
deformable_conv_kernel, distribute_fpn_proposals_kernel).

TPU-native shapes: everything except final NMS selection is static-shaped
dense math (MXU/VPU friendly). NMS keeps XLA-compatible control flow by
computing a fixed-iteration suppression matrix; the trailing
data-dependent compaction happens on concrete values (eager), mirroring
where the reference syncs to the host for proposal counts.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import register_op

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder",
           "prior_box", "yolo_box", "deform_conv2d", "DeformConv2D",
           "distribute_fpn_proposals", "decode_jpeg", "read_file", "matrix_nms", "psroi_pool"]


def _box_iou_impl(boxes1, boxes2):
    a1, a2 = boxes1[:, None, :2], boxes1[:, None, 2:]
    b1, b2 = boxes2[None, :, :2], boxes2[None, :, 2:]
    lt = jnp.maximum(a1, b1)
    rb = jnp.minimum(a2, b2)
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.prod(jnp.clip(boxes1[:, 2:] - boxes1[:, :2], 0, None), -1)
    area_b = jnp.prod(jnp.clip(boxes2[:, 2:] - boxes2[:, :2], 0, None), -1)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op("box_iou", ref="paddle/phi/kernels/impl/box_clip_kernel_impl.h "
             "(iou family)")
def box_iou(boxes1, boxes2):
    """Pairwise IoU of (N, 4) and (M, 4) xyxy boxes -> (N, M)."""
    return _box_iou_impl(boxes1, boxes2)


@register_op("nms_mask", differentiable=False,
             ref="paddle/phi/kernels/impl/nms_kernel_impl.h")
def _nms_mask(boxes, scores, iou_threshold):
    """Static-shaped greedy NMS: keep mask over score-sorted boxes.

    The classic O(N^2) suppression computed as a fixed-length fori_loop
    over the sorted order — jit-safe (no dynamic shapes); callers compact
    the mask on concrete values."""
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _box_iou_impl(b, b)
    n = boxes.shape[0]

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~sup

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold: float = 0.3, scores=None, category_idxs=None,
        categories=None, top_k: Optional[int] = None, *,
        score_threshold: Optional[float] = None):
    """Greedy NMS returning kept indices by descending score
    (python/paddle/vision/ops.py:nms parity, incl. categorical batching).
    Positional order matches the reference — nms(boxes, 0.5) binds the
    iou threshold; score_threshold is a keyword-only extension."""
    bx = boxes if isinstance(boxes, Tensor) else Tensor(jnp.asarray(boxes))
    n = bx.shape[0]
    sc = scores if scores is not None else Tensor(jnp.ones((n,)))
    if not isinstance(sc, Tensor):
        sc = Tensor(jnp.asarray(sc))
    if category_idxs is not None:
        # per-category NMS via the coordinate-offset trick: boxes from
        # different categories can never overlap
        cat = jnp.asarray(category_idxs.value if isinstance(
            category_idxs, Tensor) else category_idxs)
        span = jnp.max(bx.value) - jnp.min(bx.value) + 1.0
        bx = Tensor(bx.value + (cat[:, None] * span).astype(bx.value.dtype))
    keep = _nms_mask(bx, sc, iou_threshold)
    mask = np.asarray(keep.value)
    scn = np.asarray(sc.value)
    if score_threshold is not None:
        mask = mask & (scn > score_threshold)
    idx = np.nonzero(mask)[0]
    idx = idx[np.argsort(-scn[idx], kind="stable")]
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(jnp.asarray(idx.astype(np.int64)))


@register_op("roi_align", ref="paddle/phi/kernels/roi_align_kernel.h")
def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign: x (N, C, H, W), boxes (R, 4) xyxy in input coords with
    boxes_num giving rois per image. Bilinear-sampled (R, C, oh, ow)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    if sampling_ratio > 0:
        ratio_h = ratio_w = sampling_ratio
    else:
        # reference: adaptive ceil(roi_size/output) samples per bin. The
        # per-roi count is dynamic; the static-shape form uses the
        # worst-case bound (whole-image roi), which SUPERSETS the
        # reference's sample grid on every roi
        ratio_h = max(1, -(-H // oh))
        ratio_w = max(1, -(-W // ow))
    off = 0.5 if aligned else 0.0
    if boxes_num is None:
        img_of_roi = jnp.zeros((R,), jnp.int32)
    else:
        img_of_roi = jnp.repeat(jnp.arange(len(boxes_num)),
                                jnp.asarray(boxes_num),
                                total_repeat_length=R).astype(jnp.int32)

    b = boxes * spatial_scale
    x1, y1, x2, y2 = b[:, 0] - off, b[:, 1] - off, b[:, 2] - off, b[:, 3] - off
    rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
    bin_w = rw / ow
    bin_h = rh / oh
    # sample grid: (R, oh*ratio_h) x (R, ow*ratio_w)
    sy = (y1[:, None] + (jnp.arange(oh * ratio_h) + 0.5)[None, :]
          * (bin_h / ratio_h)[:, None])                     # (R, oh*ratio_h)
    sx = (x1[:, None] + (jnp.arange(ow * ratio_w) + 0.5)[None, :]
          * (bin_w / ratio_w)[:, None])                     # (R, ow*ratio_w)

    def bilinear(img, ys, xs):
        """img (C, H, W); ys (P,), xs (Q,) -> (C, P, Q). Samples with an
        unclamped coordinate outside [-1, H] / [-1, W] contribute ZERO
        (reference BilinearInterpolate), not border-replicated values;
        coordinates in (-1, 0) snap onto the border like the reference."""
        in_y = (ys >= -1.0) & (ys <= H)
        in_x = (xs >= -1.0) & (xs <= W)
        ys = jnp.clip(ys, 0.0, H - 1)          # (-1,0) -> 0, (H-1,H) -> H-1
        xs = jnp.clip(xs, 0.0, W - 1)
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
        y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
        wy = jnp.clip(ys - y0, 0.0, 1.0)
        wx = jnp.clip(xs - x0, 0.0, 1.0)
        v00 = img[:, y0i][:, :, x0i]
        v01 = img[:, y0i][:, :, x1i]
        v10 = img[:, y1i][:, :, x0i]
        v11 = img[:, y1i][:, :, x1i]
        out = (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
               + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
               + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
               + v11 * wy[None, :, None] * wx[None, None, :])
        return out * in_y[None, :, None] * in_x[None, None, :]

    def per_roi(r):
        img = x[img_of_roi[r]]
        samp = bilinear(img, sy[r], sx[r])    # (C, oh*ratio_h, ow*ratio_w)
        samp = samp.reshape(C, oh, ratio_h, ow, ratio_w)
        return samp.mean(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(R))


@register_op("roi_pool", ref="paddle/phi/kernels/roi_pool_kernel.h")
def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0):
    """RoI max pooling via a dense oversampled grid (static shapes)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    if boxes_num is None:
        img_of_roi = jnp.zeros((R,), jnp.int32)
    else:
        img_of_roi = jnp.repeat(jnp.arange(len(boxes_num)),
                                jnp.asarray(boxes_num),
                                total_repeat_length=R).astype(jnp.int32)
    b = jnp.round(boxes * spatial_scale)
    # dense integer sampling, masked max per bin. PER-AXIS worst-case
    # ratios: H/oh and W/ow independently, so a wide-but-short roi still
    # visits every pixel column of each bin
    ratio_h = max(4, -(-H // oh))
    ratio_w = max(4, -(-W // ow))

    def per_roi(r):
        x1, y1, x2, y2 = b[r]
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        ys = y1 + (jnp.arange(oh * ratio_h)) * (rh / (oh * ratio_h))
        xs = x1 + (jnp.arange(ow * ratio_w)) * (rw / (ow * ratio_w))
        yi = jnp.clip(jnp.floor(ys), 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(jnp.floor(xs), 0, W - 1).astype(jnp.int32)
        img = x[img_of_roi[r]]
        samp = img[:, yi][:, :, xi]         # (C, oh*ratio_h, ow*ratio_w)
        samp = samp.reshape(C, oh, ratio_h, ow, ratio_w)
        return samp.max(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(R))


@register_op("psroi_pool",
             ref="paddle/phi/kernels/psroi_pool_kernel.h (R-FCN "
                 "position-sensitive average pooling)")
def psroi_pool(x, boxes, boxes_num=None, output_size=7,
               spatial_scale=1.0):
    """Position-sensitive RoI AVERAGE pooling: input channels are split
    into oh*ow positional groups; output bin (c, i, j) averages the
    (c*oh*ow + i*ow + j)-th input channel over that bin's region —
    static-shape dense sampling like roi_pool above."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    N, C, H, W = x.shape
    if C % (oh * ow):
        raise ValueError(
            f"psroi_pool: input channels {C} must be divisible by "
            f"output_size product {oh * ow}")
    c_out = C // (oh * ow)
    R = boxes.shape[0]
    if boxes_num is None:
        img_of_roi = jnp.zeros((R,), jnp.int32)
    else:
        img_of_roi = jnp.repeat(jnp.arange(len(boxes_num)),
                                jnp.asarray(boxes_num),
                                total_repeat_length=R).astype(jnp.int32)
    b = boxes * spatial_scale
    ratio_h = max(2, -(-H // oh))
    ratio_w = max(2, -(-W // ow))
    # channel map: bin (i, j) of output channel c reads input channel
    # c*oh*ow + i*ow + j (the R-FCN position-sensitive layout)
    chan = (jnp.arange(c_out)[:, None, None] * (oh * ow)
            + jnp.arange(oh)[None, :, None] * ow
            + jnp.arange(ow)[None, None, :])            # (c_out, oh, ow)

    def per_roi(r):
        x1, y1, x2, y2 = b[r]
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        ys = y1 + (jnp.arange(oh * ratio_h) + 0.5) * (rh / (oh * ratio_h))
        xs = x1 + (jnp.arange(ow * ratio_w) + 0.5) * (rw / (ow * ratio_w))
        yi = jnp.clip(jnp.floor(ys), 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(jnp.floor(xs), 0, W - 1).astype(jnp.int32)
        img = x[img_of_roi[r]]
        samp = img[:, yi][:, :, xi]          # (C, oh*rh, ow*rw)
        samp = samp.reshape(C, oh, ratio_h, ow, ratio_w)
        pooled = samp.mean(axis=(2, 4))      # (C, oh, ow)
        return pooled[chan, jnp.arange(oh)[None, :, None],
                      jnp.arange(ow)[None, None, :]]

    return jax.vmap(per_roi)(jnp.arange(R))


@register_op("box_coder", differentiable=False,
             ref="paddle/phi/kernels/box_coder_kernel.h")
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """Encode/decode boxes against priors (SSD-style)."""
    pb = prior_box
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    var = (prior_box_var if prior_box_var is not None
           else jnp.ones((1, 4), pb.dtype))
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        out = jnp.stack([(tcx[:, None] - pcx[None, :]) / pw[None, :],
                         (tcy[:, None] - pcy[None, :]) / ph[None, :],
                         jnp.log(tw[:, None] / pw[None, :]),
                         jnp.log(th[:, None] / ph[None, :])], axis=-1)
        return out / jnp.reshape(var, (1, -1, 4))
    # decode_center_size: target (A, B, 4) deltas; ``axis`` names the dim
    # matched against the priors (reference DecodeCenterSize: prior index =
    # dim ``axis``), and the per-prior variance broadcasts along that SAME
    # dim
    if axis == 0:
        expand = lambda v: v[:, None]                       # noqa: E731
        var_b = jnp.reshape(var, (-1, 1, 4)) if var.ndim == 2 else var
    else:
        expand = lambda v: v[None, :]                       # noqa: E731
        var_b = jnp.reshape(var, (1, -1, 4)) if var.ndim == 2 else var
    d = target_box * var_b
    pw, ph, pcx, pcy = expand(pw), expand(ph), expand(pcx), expand(pcy)
    cx = d[..., 0] * pw + pcx
    cy = d[..., 1] * ph + pcy
    w = jnp.exp(d[..., 2]) * pw
    h = jnp.exp(d[..., 3]) * ph
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)


@register_op("prior_box", differentiable=False,
             ref="paddle/phi/kernels/prior_box_kernel.h")
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False):
    """SSD prior (anchor) boxes over the feature map grid."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for s_i, ms in enumerate(min_sizes):
        # reference pairing: max_sizes[s] belongs to min_sizes[s]
        mx_box = None
        if max_sizes:
            s = np.sqrt(ms * max_sizes[s_i])
            mx_box = (s, s)
        ar_boxes = [(ms * np.sqrt(ar), ms / np.sqrt(ar)) for ar in ars]
        if min_max_aspect_ratios_order and mx_box is not None:
            # [min (ar=1), max, remaining ars] — the MobileNet-SSD layout
            boxes.append(ar_boxes[0])
            boxes.append(mx_box)
            boxes.extend(ar_boxes[1:])
        else:
            boxes.extend(ar_boxes)
            if mx_box is not None:
                boxes.append(mx_box)
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    gx, gy = jnp.meshgrid(cx, cy)                 # (fh, fw)
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([(gx - bw / 2) / iw, (gy - bh / 2) / ih,
                              (gx + bw / 2) / iw, (gy + bh / 2) / ih], -1))
    pb = jnp.stack(out, axis=2)                   # (fh, fw, n_prior, 4)
    if clip:
        pb = jnp.clip(pb, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, pb.dtype), pb.shape)
    return pb, var


@register_op("yolo_box", differentiable=False,
             ref="paddle/phi/kernels/yolo_box_kernel.h")
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """Decode YOLOv3 head output (N, A*(5+C), H, W) into boxes + scores."""
    N, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    feat = x.reshape(N, A, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (sig(feat[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + gx) / W
    by = (sig(feat[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + gy) / H
    bw = jnp.exp(feat[:, :, 2]) * an[None, :, 0, None, None] / (
        W * downsample_ratio)
    bh = jnp.exp(feat[:, :, 3]) * an[None, :, 1, None, None] / (
        H * downsample_ratio)
    conf = sig(feat[:, :, 4])
    probs = sig(feat[:, :, 5:]) * conf[:, :, None]
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, A * H * W, 4)
    scores = jnp.moveaxis(probs, 2, -1).reshape(N, A * H * W, class_num)
    keep = (conf.reshape(N, A * H * W) > conf_thresh)[..., None]
    return boxes * keep, scores * keep


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 public API (paddle.vision.ops signature).
    ``mask`` is forwarded POSITIONALLY into the registered op — kwarg
    Tensors are non-differentiable attrs in the registry, and the DCNv2
    modulation mask must receive gradients."""
    if mask is None:
        return _deform_conv2d_op(x, offset, weight, bias, stride=stride,
                                 padding=padding, dilation=dilation,
                                 deformable_groups=deformable_groups,
                                 groups=groups)
    return _deform_conv2d_masked_op(
        x, offset, weight, mask, bias, stride=stride, padding=padding,
        dilation=dilation, deformable_groups=deformable_groups,
        groups=groups)


@register_op("deform_conv2d",
             ref="paddle/phi/kernels/deformable_conv_kernel.h")
def _deform_conv2d_op(x, offset, weight, bias=None, stride=1, padding=0,
                      dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2: bilinear-sample x at kernel positions shifted
    by learned offsets, then a dense matmul with the kernel (the im2col
    formulation; v2 when ``mask`` modulation is given)."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("deform_conv2d: groups > 1 TBD")
    N, C, H, W = x.shape
    Co, _, kh, kw = weight.shape
    oh = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) // stride[0] + 1
    ow = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) // stride[1] + 1
    # base sampling grids per kernel tap and output pixel, plus offsets
    off = offset.reshape(N, kh * kw, 2, oh, ow)
    off_y = off[:, :, 0].reshape(N, kh, kw, oh, ow)
    off_x = off[:, :, 1].reshape(N, kh, kw, oh, ow)
    by = (jnp.arange(oh)[None, :] * stride[0] - padding[0]
          + jnp.arange(kh)[:, None] * dilation[0])           # (kh, oh)
    bx = (jnp.arange(ow)[None, :] * stride[1] - padding[1]
          + jnp.arange(kw)[:, None] * dilation[1])           # (kw, ow)
    py = by[None, :, None, :, None] + off_y                  # (N,kh,kw,oh,ow)
    px = bx[None, None, :, None, :] + off_x

    def bilin(img, ys, xs):
        """img (C, H, W); ys/xs (...,) -> (C, ...)."""
        y0 = jnp.floor(ys)
        x0 = jnp.floor(xs)
        wy = ys - y0
        wx = xs - x0
        out = 0.0
        for dy, sy in ((0, 1 - wy), (1, wy)):
            for dx, sx in ((0, 1 - wx), (1, wx)):
                yi = y0 + dy
                xi = x0 + dx
                valid = ((yi >= 0) & (yi <= H - 1)
                         & (xi >= 0) & (xi <= W - 1))
                yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                v = img[:, yc, xc] * valid[None]
                out = out + v * (sy * sx)[None]
        return out

    def per_image(img, pyi, pxi, m):
        samp = bilin(img, pyi, pxi)              # (C, kh, kw, oh, ow)
        if m is not None:
            samp = samp * m[None]
        cols = samp.reshape(C * kh * kw, oh * ow)
        wmat = weight.reshape(Co, C * kh * kw)
        return (wmat @ cols).reshape(Co, oh, ow)

    msk = (mask.reshape(N, kh, kw, oh, ow) if mask is not None
           else None)
    out = jax.vmap(per_image)(x, py, px, msk) if msk is not None else \
        jax.vmap(lambda i, a, b: per_image(i, a, b, None))(x, py, px)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register_op("deform_conv2d_v2",
             ref="paddle/phi/kernels/deformable_conv_kernel.h (modulated)")
def _deform_conv2d_masked_op(x, offset, weight, mask, bias=None, stride=1,
                             padding=0, dilation=1, deformable_groups=1,
                             groups=1):
    """DCNv2 with the modulation mask as a differentiable positional."""
    return _deform_conv2d_op.op.impl(
        x, offset, weight, bias, stride=stride, padding=padding,
        dilation=dilation, deformable_groups=deformable_groups,
        groups=groups, mask=mask)


class DeformConv2D(paddle.nn.Layer):
    """Layer wrapper over deform_conv2d (paddle.vision.ops.DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size, kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]], attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], is_bias=True,
                                           attr=bias_attr))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self.stride, padding=self.padding,
                             dilation=self.dilation,
                             deformable_groups=self.deformable_groups,
                             groups=self.groups, mask=mask)


@register_op("distribute_fpn_proposals", differentiable=False,
             ref="paddle/phi/kernels/distribute_fpn_proposals_kernel.h")
def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False):
    """Assign each RoI to an FPN level by scale: returns per-level index
    masks (static shapes: boolean masks per level + restore order)."""
    off = 1.0 if pixel_offset else 0.0
    w = fpn_rois[:, 2] - fpn_rois[:, 0] + off
    h = fpn_rois[:, 3] - fpn_rois[:, 1] + off
    scale = jnp.sqrt(jnp.clip(w * h, 0, None))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    masks = tuple((lvl == i) for i in range(min_level, max_level + 1))
    # restore index: position of each roi in the level-grouped concat order
    order = jnp.argsort(lvl, stable=True)
    restore = jnp.argsort(order, stable=True)
    return masks + (restore,)



def decode_jpeg(x, mode="unchanged", name=None):
    """Reference: paddle/phi/kernels/gpu/decode_jpeg_kernel.cu (nvjpeg).
    This build has no image codec (no nvjpeg analog on TPU hosts, and the
    environment is egress-limited — no libjpeg binding is shipped);
    decode on the host with PIL/cv2 and feed arrays instead."""
    raise NotImplementedError(
        "decode_jpeg: no JPEG codec in the TPU build — decode on the host "
        "(PIL/cv2) and pass the decoded array")


def read_file(filename, name=None):
    """Reference: paddle/phi/kernels/cpu/read_file_kernel.cc. Host file IO
    belongs to the input pipeline here (paddle_tpu.io readers); kept as a
    named raiser for op-compat parity."""
    raise NotImplementedError(
        "read_file: use paddle_tpu.io datasets / plain Python file IO; "
        "the op-based file reader is a GPU-pipeline construct")


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2) — decay every box's score by its max IoU with
    higher-scored same-class boxes in one IoU matrix instead of
    sequential suppression (paddle/phi/kernels/impl/matrix_nms ref).
    bboxes (B, N, 4), scores (B, C, N); returns the reference's
    [label, score, x1, y1, x2, y2] rows per image. Output sizes are
    data-dependent -> eager-only (host assembly), like the reference's
    CPU kernel."""
    import numpy as _np

    bv = _np.asarray(bboxes.numpy() if isinstance(bboxes, Tensor) else bboxes)
    sv = _np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores)
    B, C, N = sv.shape
    all_rows, all_idx, rois_num = [], [], []
    for b in range(B):
        rows, idxs = [], []
        for c in range(C):
            if c == background_label:
                continue
            sc = sv[b, c]
            keep = _np.nonzero(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[_np.argsort(-sc[keep])]
            if nms_top_k > -1:
                order = order[:nms_top_k]
            boxes = bv[b, order]
            s = sc[order]
            x1, y1, x2, y2 = boxes.T
            off = 0.0 if normalized else 1.0
            area = (x2 - x1 + off) * (y2 - y1 + off)
            ix1 = _np.maximum(x1[:, None], x1[None, :])
            iy1 = _np.maximum(y1[:, None], y1[None, :])
            ix2 = _np.minimum(x2[:, None], x2[None, :])
            iy2 = _np.minimum(y2[:, None], y2[None, :])
            iw = _np.maximum(ix2 - ix1 + off, 0)
            ih = _np.maximum(iy2 - iy1 + off, 0)
            inter = iw * ih
            iou = inter / (area[:, None] + area[None, :] - inter + 1e-10)
            iou = _np.triu(iou, k=1)             # higher-scored rows only
            iou_cmax = iou.max(axis=0)           # per box: max IoU w/ better
            # reference decay_score (matrix_nms_kernel.cc): candidate j is
            # decayed by min over suppressors i<j of f(iou_ij, cmax_i)
            # where cmax_i COMPENSATES suppressor i's own suppression
            cmax = iou_cmax[:, None]
            if use_gaussian:
                decay_m = _np.exp((cmax ** 2 - iou ** 2) * gaussian_sigma)
            else:
                decay_m = (1 - iou) / _np.maximum(1 - cmax, 1e-10)
            decay = _np.minimum(_np.triu(decay_m, k=1)
                                + _np.tril(_np.ones_like(decay_m)),
                                1.0).min(axis=0)
            ds = s * decay
            sel = ds > post_threshold
            for i in _np.nonzero(sel)[0]:
                rows.append([float(c), float(ds[i]), *boxes[i].tolist()])
                idxs.append(int(order[i]) + b * N)
        if rows:
            rows_a = _np.asarray(rows, _np.float32)
            top = _np.argsort(-rows_a[:, 1])
            if keep_top_k > -1:
                top = top[:keep_top_k]
            all_rows.append(rows_a[top])
            all_idx.extend([idxs[t] for t in top])
            rois_num.append(len(top))
        else:
            rois_num.append(0)
    out = _np.concatenate(all_rows, axis=0) if all_rows else \
        _np.zeros((0, 6), _np.float32)
    # reference API contract: ALWAYS (out, rois_num, index) with None
    # placeholders for disabled returns (python/paddle/vision/ops.py)
    out_t = Tensor(jnp.asarray(out))
    rois_t = Tensor(jnp.asarray(_np.asarray(rois_num, _np.int32))) \
        if return_rois_num else None
    idx_t = Tensor(jnp.asarray(_np.asarray(all_idx, _np.int32))) \
        if return_index else None
    return out_t, rois_t, idx_t


# detection training tail (round 5): RPN proposals, multiclass NMS,
# differentiable YOLOv3 loss — see vision/detection.py
from paddle_tpu.vision.detection import (  # noqa: E402,F401
    generate_proposals, multiclass_nms3, yolo_loss,
)
__all__ += ["generate_proposals", "multiclass_nms3", "yolo_loss"]
