"""Detection training tail (round-5 VERDICT item 7).

Capability analogs of the reference's RPN / YOLO training ops:
- generate_proposals: paddle/phi/kernels/gpu/generate_proposals_kernel.cu
- multiclass_nms3:    paddle/phi/kernels/gpu/multiclass_nms3_kernel.cu
- yolo_loss:          paddle/phi/kernels/impl/yolo_loss_kernel_impl.h

TPU-native split: the *differentiable* training math (yolo_loss) is pure
jnp — target assignment is a static-shape scatter, every loss term an
XLA fusion, gradients flow to the prediction map. The *selection* ops
(proposal generation, multiclass NMS) are data-dependent-size by nature;
like the host-side metric code of every ecosystem they run eagerly over
concrete arrays (the rulebook pattern: host selects, device computes) —
their consumers (roi_align, heads) are device ops again.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.ops.registry import register_op

__all__ = ["generate_proposals", "multiclass_nms3", "yolo_loss"]


def _np(x):
    return np.asarray(x.value if isinstance(x, Tensor) else x)


def _nms_np(boxes: np.ndarray, scores: np.ndarray, thresh: float,
            top_k: Optional[int] = None, offset: float = 0.0,
            eta: float = 1.0) -> np.ndarray:
    """Greedy NMS over concrete arrays; returns kept indices (desc score).
    ``eta < 1`` is the reference's adaptive NMS: after each kept box the
    threshold decays (``thresh *= eta`` while thresh > 0.5)."""
    order = np.argsort(-scores, kind="stable")
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = np.maximum(x2 - x1 + offset, 0) * np.maximum(y2 - y1 + offset, 0)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if top_k is not None and len(keep) >= top_k:
            break
        rest = order[1:]
        xx1 = np.maximum(x1[i], x1[rest])
        yy1 = np.maximum(y1[i], y1[rest])
        xx2 = np.minimum(x2[i], x2[rest])
        yy2 = np.minimum(y2[i], y2[rest])
        inter = np.maximum(xx2 - xx1 + offset, 0) * \
            np.maximum(yy2 - yy1 + offset, 0)
        iou = inter / np.maximum(areas[i] + areas[rest] - inter, 1e-10)
        order = rest[iou <= thresh]
        if eta < 1.0 and thresh > 0.5:
            thresh *= eta
    return np.asarray(keep, np.int64)


@register_op("generate_proposals", differentiable=False,
             ref="paddle/phi/kernels/gpu/generate_proposals_kernel.cu",
             n_outputs=3)
def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n: int = 6000,
                       post_nms_top_n: int = 1000,
                       nms_thresh: float = 0.5, min_size: float = 0.1,
                       eta: float = 1.0, pixel_offset: bool = True,
                       return_rois_num: bool = True):
    """RPN proposal generation.

    scores (N, A, H, W); bbox_deltas (N, 4A, H, W); img_size (N, 2) as
    (h, w); anchors/variances (H, W, A, 4) or (H*W*A, 4). Per image:
    top-``pre_nms_top_n`` scores -> center-size delta decode (variances
    folded in, dw/dh clipped at log(1000/16)) -> clip to image -> drop
    boxes under ``min_size`` -> NMS at ``nms_thresh`` -> top
    ``post_nms_top_n``. Returns (rois (R,4), roi_probs (R,1),
    rois_num (N,)).
    """
    sc = _np(scores)
    dl = _np(bbox_deltas)
    im = _np(img_size)
    an = _np(anchors).reshape(-1, 4).astype(np.float64)
    va = _np(variances).reshape(-1, 4).astype(np.float64)
    N, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    log_max = np.log(1000.0 / 16.0)

    all_rois, all_probs, nums = [], [], []
    for i in range(N):
        s = sc[i].transpose(1, 2, 0).reshape(-1)           # (H, W, A)
        d = dl[i].reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4).astype(np.float64)
        k = min(pre_nms_top_n, s.size)
        top = np.argsort(-s, kind="stable")[:k]
        s_t, d_t, an_t, va_t = s[top], d[top], an[top], va[top]

        aw = an_t[:, 2] - an_t[:, 0] + off
        ah = an_t[:, 3] - an_t[:, 1] + off
        acx = an_t[:, 0] + 0.5 * aw
        acy = an_t[:, 1] + 0.5 * ah
        cx = va_t[:, 0] * d_t[:, 0] * aw + acx
        cy = va_t[:, 1] * d_t[:, 1] * ah + acy
        w = np.exp(np.minimum(va_t[:, 2] * d_t[:, 2], log_max)) * aw
        h = np.exp(np.minimum(va_t[:, 3] * d_t[:, 3], log_max)) * ah
        boxes = np.stack([cx - 0.5 * w, cy - 0.5 * h,
                          cx + 0.5 * w - off, cy + 0.5 * h - off], axis=1)
        ih, iw = float(im[i][0]), float(im[i][1])
        boxes[:, 0] = np.clip(boxes[:, 0], 0, iw - off)
        boxes[:, 1] = np.clip(boxes[:, 1], 0, ih - off)
        boxes[:, 2] = np.clip(boxes[:, 2], 0, iw - off)
        boxes[:, 3] = np.clip(boxes[:, 3], 0, ih - off)
        bw = boxes[:, 2] - boxes[:, 0] + off
        bh = boxes[:, 3] - boxes[:, 1] + off
        ok = (bw >= max(min_size, 1.0)) & (bh >= max(min_size, 1.0))
        boxes, s_t = boxes[ok], s_t[ok]
        if boxes.shape[0]:
            keep = _nms_np(boxes, s_t, nms_thresh, top_k=post_nms_top_n,
                           offset=off, eta=eta)
            boxes, s_t = boxes[keep], s_t[keep]
        all_rois.append(boxes.astype(np.float32))
        all_probs.append(s_t.astype(np.float32)[:, None])
        nums.append(boxes.shape[0])
    rois = np.concatenate(all_rois, 0) if all_rois else \
        np.zeros((0, 4), np.float32)
    probs = np.concatenate(all_probs, 0) if all_probs else \
        np.zeros((0, 1), np.float32)
    return (jnp.asarray(rois), jnp.asarray(probs),
            jnp.asarray(np.asarray(nums, np.int32)))


@register_op("multiclass_nms3", differentiable=False,
             ref="paddle/phi/kernels/gpu/multiclass_nms3_kernel.cu",
             n_outputs=3)
def multiclass_nms3(bboxes, scores, rois_num=None,
                    score_threshold: float = 0.05, nms_top_k: int = 1000,
                    keep_top_k: int = 100, nms_threshold: float = 0.3,
                    normalized: bool = True, nms_eta: float = 1.0,
                    background_label: int = 0, return_index: bool = False):
    """Per-class NMS + cross-class top-k (the detection-head decoder).

    Two input layouts, matching the reference:
    - batched: bboxes (N, M, 4), scores (N, C, M);
    - packed (``rois_num`` given — the generate_proposals chaining form):
      bboxes (R, 4) or (R, C, 4), scores (R, C), split into per-image
      segments by ``rois_num``.

    Per image and per class (skipping ``background_label``, default 0 as
    in the reference): score filter -> top ``nms_top_k`` -> NMS (adaptive
    ``nms_eta``) -> merge classes, sort by score, keep ``keep_top_k``.
    Returns (out (R, 6) as [label, score, x1, y1, x2, y2], index (R, 1)
    into the flattened box list, nms_rois_num (N,)).
    """
    bx = _np(bboxes)
    sc = _np(scores)
    off = 0.0 if normalized else 1.0
    if rois_num is not None:
        rn = _np(rois_num).astype(np.int64)
        starts = np.concatenate([[0], np.cumsum(rn)])
        images = []
        for i in range(len(rn)):
            lo, hi = int(starts[i]), int(starts[i + 1])
            b = bx[lo:hi]                      # (r, 4) or (r, C, 4)
            s = sc[lo:hi].T                    # (C, r)
            images.append((b, s, lo))
    else:
        # batched layout: scores are already (C, M)
        images = [(bx[i], sc[i], i * bx.shape[1]) for i in range(bx.shape[0])]
    outs, idxs, nums = [], [], []
    for b_img, s_img, base in images:
        C = s_img.shape[0]
        dets = []          # (label, score, box, flat_index)
        for c in range(C):
            if c == background_label:
                continue
            s = s_img[c]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            order = sel[np.argsort(-s[sel], kind="stable")][:nms_top_k]
            boxes_c = b_img[:, c] if b_img.ndim == 3 else b_img
            keep = _nms_np(boxes_c[order], s[order], nms_threshold,
                           offset=off, eta=nms_eta)
            for j in order[keep]:
                dets.append((c, s[j], boxes_c[j], base + j))
        dets.sort(key=lambda t: -t[1])
        if keep_top_k >= 0:
            dets = dets[:keep_top_k]
        for c, s_, b, fi in dets:
            outs.append(np.concatenate([[np.float32(c), np.float32(s_)],
                                        b.astype(np.float32)]))
            idxs.append(fi)
        nums.append(len(dets))
    out = np.stack(outs, 0) if outs else np.zeros((0, 6), np.float32)
    index = np.asarray(idxs, np.int64)[:, None] if idxs else \
        np.zeros((0, 1), np.int64)
    return (jnp.asarray(out), jnp.asarray(index),
            jnp.asarray(np.asarray(nums, np.int32)))


@register_op("yolo_loss",
             ref="paddle/phi/kernels/impl/yolo_loss_kernel_impl.h")
def yolo_loss(x, gt_box, gt_label, anchors: Sequence[int],
              anchor_mask: Sequence[int], class_num: int,
              ignore_thresh: float, downsample_ratio: int,
              gt_score=None, use_label_smooth: bool = True,
              scale_x_y: float = 1.0):
    """YOLOv3 training loss — fully differentiable jnp (the genuinely
    missing capability behind the r4 absences: yolo_box covered inference
    only).

    x (N, A*(5+C), H, W) raw predictions for the ``anchor_mask`` anchors;
    gt_box (N, B, 4) as center-x, center-y, w, h in [0, 1] image-relative
    units (zero rows = padding); gt_label (N, B) ints; ``anchors`` the
    FULL flat (w0, h0, w1, h1, ...) list, ``anchor_mask`` this head's
    indices into it. Per YOLOv3: each gt is assigned to the anchor with
    best shape-IoU over ALL anchors; only gts whose best anchor is in
    this head's mask produce positives here. Loss terms: sigmoid-CE on
    the cell offsets, L1 on log-scales (both weighted 2 - gw*gh),
    sigmoid-CE objectness where negatives whose decoded box overlaps any
    gt above ``ignore_thresh`` are ignored, sigmoid-CE classification
    (optional label smoothing with delta = 1/class_num). Returns (N,).
    """
    if scale_x_y != 1.0:
        raise NotImplementedError(
            "yolo_loss: scale_x_y != 1.0 (the YOLOv4/PP-YOLO grid-"
            "sensitive decode) is not implemented; computing the loss "
            "without the scale would silently mistrain such models")
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = np.asarray(anchor_mask, np.int64)
    Am = len(mask)
    xv = x
    N, _, H, W = xv.shape
    C = class_num
    in_w = W * downsample_ratio
    in_h = H * downsample_ratio

    p = jnp.reshape(xv, (N, Am, 5 + C, H, W))
    px, py = p[:, :, 0], p[:, :, 1]            # (N, Am, H, W)
    pw, ph = p[:, :, 2], p[:, :, 3]
    pobj = p[:, :, 4]
    pcls = p[:, :, 5:]                         # (N, Am, C, H, W)

    gb = gt_box
    gl = gt_label.astype(jnp.int32)
    B = gb.shape[1]
    gs = (jnp.ones((N, B), jnp.float32) if gt_score is None
          else gt_score.astype(jnp.float32))
    valid = gb[:, :, 2] > 0                    # (N, B) padded rows excluded

    # best anchor per gt by shape IoU (both centered at origin)
    gw = gb[:, :, 2] * in_w                    # gt w in pixels
    gh = gb[:, :, 3] * in_h
    aw = jnp.asarray(anchors[:, 0])            # (Atot,)
    ah = jnp.asarray(anchors[:, 1])
    inter = jnp.minimum(gw[:, :, None], aw) * jnp.minimum(gh[:, :, None], ah)
    union = gw[:, :, None] * gh[:, :, None] + aw * ah - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # (N, B)
    # position of the best anchor inside this head's mask (-1 = not ours)
    mask_pos = jnp.full((len(anchors),), -1, jnp.int32)
    mask_pos = mask_pos.at[jnp.asarray(mask)].set(
        jnp.arange(Am, dtype=jnp.int32))
    k = mask_pos[best]                         # (N, B)
    ours = valid & (k >= 0)

    gi = jnp.clip((gb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
    kk = jnp.maximum(k, 0)

    bidx = jnp.broadcast_to(jnp.arange(N)[:, None], (N, B))
    sel = (bidx, kk, gj, gi)

    # scatter gt targets onto the prediction grid; weight 0 where not ours
    wgt = jnp.where(ours, gs * (2.0 - gb[:, :, 2] * gb[:, :, 3]), 0.0)
    tx = gb[:, :, 0] * W - gi
    ty = gb[:, :, 1] * H - gj
    ma = jnp.asarray(anchors[mask])            # (Am, 2) this head's anchors
    tw = jnp.log(jnp.maximum(gw, 1e-9) / jnp.maximum(ma[kk][:, :, 0], 1e-9))
    th = jnp.log(jnp.maximum(gh, 1e-9) / jnp.maximum(ma[kk][:, :, 1], 1e-9))

    def sce(logit, target):
        # sigmoid cross entropy, numerically stable
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    zeros = jnp.zeros((N, Am, H, W), jnp.float32)
    obj_t = zeros.at[sel].max(jnp.where(ours, 1.0, 0.0))
    obj_w = zeros.at[sel].max(jnp.where(ours, gs, 0.0))

    # coordinate/size losses gathered at assigned cells (per-gt)
    lx = sce(px[sel], tx) + sce(py[sel], ty)
    lwh = jnp.abs(pw[sel] - tw) + jnp.abs(ph[sel] - th)
    loss_box = jnp.sum(wgt * (lx + lwh), axis=1)

    # classification at assigned cells
    delta = 1.0 / C if (use_label_smooth and C > 1) else 0.0
    onehot = jax.nn.one_hot(gl, C)             # (N, B, C)
    tcls = onehot * (1.0 - delta) + delta * (1.0 - onehot) \
        if delta else onehot
    pc = jnp.moveaxis(pcls, 2, -1)[sel]        # (N, B, C)
    loss_cls = jnp.sum(jnp.where(ours, gs, 0.0)[:, :, None]
                       * sce(pc, tcls), axis=(1, 2))

    # objectness: decode all predictions, ignore negatives overlapping a
    # gt above ignore_thresh
    cell_x = jnp.arange(W, dtype=jnp.float32)
    cell_y = jnp.arange(H, dtype=jnp.float32)
    bx = (jax.nn.sigmoid(px) + cell_x[None, None, None, :]) / W
    by = (jax.nn.sigmoid(py) + cell_y[None, None, :, None]) / H
    bw = jnp.exp(jnp.clip(pw, -20, 20)) * ma[:, 0][None, :, None, None] \
        / in_w
    bh = jnp.exp(jnp.clip(ph, -20, 20)) * ma[:, 1][None, :, None, None] \
        / in_h
    # IoU of every pred box vs every gt (relative units)
    px1, px2 = bx - bw / 2, bx + bw / 2
    py1, py2 = by - bh / 2, by + bh / 2
    gx1 = gb[:, :, 0] - gb[:, :, 2] / 2
    gx2 = gb[:, :, 0] + gb[:, :, 2] / 2
    gy1 = gb[:, :, 1] - gb[:, :, 3] / 2
    gy2 = gb[:, :, 1] + gb[:, :, 3] / 2

    # one broadcast over the gt axis (B small, grid big: a Python loop
    # over B would trace B full-grid IoU blocks and defeat fusion)
    def bc(v):          # (N, B) -> (N, B, 1, 1, 1) against (N,1,Am,H,W)
        return v[:, :, None, None, None]

    ix1 = jnp.maximum(px1[:, None], bc(gx1))
    ix2 = jnp.minimum(px2[:, None], bc(gx2))
    iy1 = jnp.maximum(py1[:, None], bc(gy1))
    iy2 = jnp.minimum(py2[:, None], bc(gy2))
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    ga = (gx2 - gx1) * (gy2 - gy1)             # (N, B)
    pa = (bw * bh)[:, None]
    iou_all = inter / jnp.maximum(pa + bc(ga) - inter, 1e-10)
    iou_all = jnp.where(bc(valid), iou_all, 0.0)
    # initial=0 also covers B == 0 (all-background batches)
    best_iou = jnp.max(iou_all, axis=1, initial=0.0)   # (N, Am, H, W)
    noobj_mask = (best_iou <= ignore_thresh).astype(jnp.float32)
    obj_losses = sce(pobj, obj_t)
    loss_obj = jnp.sum(jnp.where(obj_t > 0, obj_w * obj_losses,
                                 noobj_mask * obj_losses), axis=(1, 2, 3))
    return loss_box + loss_cls + loss_obj
