"""paddle_tpu.vision — torchvision-like models/transforms/datasets
(python/paddle/vision/ analog, SURVEY P16)."""

from paddle_tpu.vision import datasets, models, transforms  # noqa: F401
from paddle_tpu.vision import ops  # noqa: F401
from paddle_tpu.vision.models import *  # noqa: F401,F403
