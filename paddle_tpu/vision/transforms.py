"""Vision transforms (python/paddle/vision/transforms/ analog).

numpy-based host-side transforms; images are HWC uint8/float arrays (or
CHW when `data_format='CHW'` output is requested by ToTensor/Normalize).
"""

from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

from paddle_tpu.framework.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "BrightnessTransform", "to_tensor", "normalize", "resize", "hflip",
    "center_crop",
]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def _as_float(img):
    img = np.asarray(img)
    if img.dtype == np.uint8:
        return img.astype(np.float32) / 255.0
    return img.astype(np.float32)


def to_tensor(img, data_format="CHW") -> Tensor:
    arr = _as_float(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW"):
    arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    """HWC numpy resize via jax.image (device-side when under jit)."""
    import jax.image

    arr = np.asarray(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    out = np.asarray(jax.image.resize(
        arr.astype(np.float32), (size[0], size[1], arr.shape[2]),
        method=interpolation))
    if arr.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    return out[:, :, 0] if squeeze else out


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def center_crop(img, output_size):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return arr[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            if arr.ndim == 3:
                pad.append((0, 0))
            arr = np.pad(arr, pad, mode="constant")
        h, w = arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return arr[i:i + th, j:j + tw]


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        elif len(p) == 2:  # (horizontal, vertical) paddle form
            p = (p[0], p[1], p[0], p[1])
        pad = [(p[1], p[3]), (p[0], p[2])]
        if arr.ndim == 3:
            pad.append((0, 0))
        kwargs = {"constant_values": self.fill} if self.mode == "constant" else {}
        return np.pad(arr, pad, mode=self.mode, **kwargs)


class BrightnessTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        arr = np.asarray(img).astype(np.float32)
        factor = 1.0 + random.uniform(-self.value, self.value)
        out = arr * factor
        if np.asarray(img).dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out
